"""Fleet observatory campaign (ISSUE 16): consensus round forensics,
telemetry federation over the mesh, and the crash flight recorder.

Deterministic halves of the observatory's contract:

- the round ledger's state machine under an injected clock: first-wins
  edges and votes, phase spans, cap eviction, height-addressed notes;
- cross-node alignment: probe-offset correction, inter-node skew, and
  straggler attribution (largest mean vote lateness behind first arrival);
- federation over the in-proc mesh: one dead + one slow peer — the merged
  document degrades rows, never drops them, and strikes quarter a dead
  peer's budget;
- the flight recorder's four death doors (InjectedCrash, Node.stop, the
  fatal-halt path, SIGTERM) each leave a parseable black box;
- ``FISCO_FLEET_OBS=0`` pins: noop ledger on the engine, no federation
  endpoint, disabled recorder;
- the Pro-split front door serves /fleet, /round/<h>, /rounds through the
  facade (RemoteTelemetry), like /metrics and /health.
"""

import json
import signal
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

sys.path.insert(0, "tests")

from test_pipeline import make_chain  # noqa: E402

from fisco_bcos_tpu.front import ModuleID  # noqa: E402
from fisco_bcos_tpu.observability import flight as flight_mod  # noqa: E402
from fisco_bcos_tpu.observability.flight import (  # noqa: E402
    FLIGHT,
    FlightRecorder,
    install_signal_flush,
    post_mortem,
)
from fisco_bcos_tpu.observability.roundlog import (  # noqa: E402
    NOOP_LEDGER,
    ROUND_PHASE_BUCKETS_MS,
    ROUND_SKEW_BUCKETS_MS,
    RoundLedger,
    align_rounds,
    fleet_obs_enabled,
    phase_spans,
    round_doc,
    rounds_doc,
)
from fisco_bcos_tpu.resilience.crashpoints import (  # noqa: E402
    CrashPlan,
    InjectedCrash,
    clear_crash_plan,
    crashpoint,
    install_crash_plan,
)
from fisco_bcos_tpu.utils.metrics import REGISTRY  # noqa: E402


class Ticker:
    """Deterministic injected clock."""

    def __init__(self, t=0.0, step=0.0):
        self.t = t
        self.step = step

    def __call__(self):
        self.t += self.step
        return self.t


def _ledger(**kw):
    kw.setdefault("clock", Ticker())
    kw.setdefault("emit_metrics", False)
    return RoundLedger(node_tag=kw.pop("node_tag", "n0"), **kw)


# -- the ledger state machine -------------------------------------------------


def test_round_ledger_records_full_round():
    led = _ledger()
    led.note(5, 0, "pre_prepare", t=1.0)
    for i, t in ((0, 1.001), (1, 1.002), (2, 1.010)):
        led.vote(5, 0, "prepare", i, t=t)
    led.note(5, 0, "prepared", t=1.011)
    led.note(5, 0, "commit_sent", t=1.012)
    for i, t in ((0, 1.013), (1, 1.014), (2, 1.020)):
        led.vote(5, 0, "commit", i, t=t)
    led.note(5, 0, "committed", t=1.021)
    led.note(5, 0, "execute_start", t=1.022)
    led.note(5, 0, "execute_end", t=1.030)
    led.note(5, 0, "stable", t=1.040)
    led.note(5, 0, "durable", t=1.050)
    snap = led.snapshot()
    assert snap["node"] == "n0"
    (rd,) = snap["rounds"]
    assert rd["height"] == 5 and rd["view"] == 0
    assert set(rd["votes"]["prepare"]) == {"0", "1", "2"}
    spans = phase_spans(rd)
    assert spans["prepare"] == pytest.approx(11.0)  # pre_prepare -> prepared
    assert spans["commit"] == pytest.approx(10.0)
    assert spans["execute"] == pytest.approx(8.0)
    assert spans["checkpoint"] == pytest.approx(19.0)
    assert spans["durable"] == pytest.approx(10.0)


def test_round_ledger_first_occurrence_wins():
    """Re-delivered frames must not move an edge or rewrite a vote."""
    led = _ledger()
    led.note(5, 0, "pre_prepare", t=1.0)
    led.note(5, 0, "pre_prepare", t=99.0)
    led.vote(5, 0, "prepare", 0, t=2.0)
    led.vote(5, 0, "prepare", 0, t=99.0)
    (rd,) = led.snapshot()["rounds"]
    assert rd["events"]["pre_prepare"] == 1.0
    assert rd["votes"]["prepare"]["0"] == 2.0


def test_round_ledger_cap_evicts_oldest():
    led = _ledger(cap=2)
    for h in (1, 2, 3):
        led.note(h, 0, "pre_prepare", t=float(h))
    heights = [r["height"] for r in led.snapshot()["rounds"]]
    assert heights == [2, 3]


def test_note_height_targets_newest_view():
    """The async-commit durable callback knows the height, not the view —
    it must land on the newest round at that height (the re-proposal)."""
    led = _ledger()
    led.note(5, 0, "pre_prepare", t=1.0)
    led.note(5, 1, "pre_prepare", t=2.0)
    led.note_height(5, "durable", t=3.0)
    by_view = {r["view"]: r for r in led.snapshot()["rounds"]}
    assert "durable" in by_view[1]["events"]
    assert "durable" not in by_view[0]["events"]


def test_snapshot_filters_last_and_height():
    led = _ledger()
    for h in (1, 2, 3, 4):
        led.note(h, 0, "pre_prepare", t=float(h))
    led.view_change(3, 0, 1, "timeout", t=3.5)
    assert [r["height"] for r in led.snapshot(last=2)["rounds"]] == [3, 4]
    assert [r["height"] for r in led.snapshot(height=2)["rounds"]] == [2]
    (vc,) = led.snapshot()["view_changes"]
    assert vc["cause"] == "timeout" and vc["from_view"] == 0


def test_quorum_edges_emit_round_metrics():
    led = RoundLedger(node_tag="m0", clock=Ticker(), emit_metrics=True)
    led.note(7, 0, "pre_prepare", t=1.0)
    led.vote(7, 0, "prepare", 0, t=1.001)
    led.vote(7, 0, "prepare", 1, t=1.004)
    led.note(7, 0, "prepared", t=1.005)
    out = REGISTRY.render()
    assert 'fisco_round_phase_ms_bucket{phase="prepare"' in out
    assert 'fisco_vote_arrival_spread_ms_bucket{kind="prepare"' in out


def test_bucket_constants_are_sane():
    for buckets in (ROUND_PHASE_BUCKETS_MS, ROUND_SKEW_BUCKETS_MS):
        assert list(buckets) == sorted(buckets)
        assert len(set(buckets)) == len(buckets)


# -- cross-node alignment -----------------------------------------------------


def _snap(label, rounds, clock=100.0):
    return {"node": label, "clock": clock, "rounds": rounds,
            "view_changes": []}


def test_align_rounds_offset_correction():
    """Node B's monotonic clock runs 100 s ahead; with the probe offset the
    skew collapses from ~100 s to the real 20 ms."""
    a = {"height": 4, "view": 0,
         "events": {"pre_prepare": 10.000, "prepared": 10.005,
                    "stable": 10.030},
         "votes": {}}
    b = {"height": 4, "view": 0,
         "events": {"pre_prepare": 110.000, "prepared": 110.004,
                    "stable": 110.050},
         "votes": {}}
    ledgers = {"A": _snap("A", [a]), "B": _snap("B", [b])}
    (doc,) = align_rounds(ledgers, offsets={"A": 0.0, "B": 100.0})
    assert doc["skew_edge"] == "stable"
    assert doc["skew_ms"] == pytest.approx(20.0, abs=1e-6)
    (uncorrected,) = align_rounds(ledgers)
    assert uncorrected["skew_ms"] > 50_000
    # per-node spans are offset-independent (intra-node deltas)
    assert doc["nodes"]["A"]["phases"]["prepare"] == pytest.approx(5.0)
    assert doc["nodes"]["B"]["phases"]["prepare"] == pytest.approx(4.0)
    assert doc["phases"]["prepare"] == {
        "min_ms": pytest.approx(4.0), "max_ms": pytest.approx(5.0)
    }


def test_align_rounds_names_straggler():
    """Signer 2's votes trail the first arrival at BOTH observers — it is
    the straggler; offsets cancel because lateness is intra-node."""
    def votes(base):
        return {"prepare": {"0": base, "1": base + 0.001, "2": base + 0.040}}

    a = {"height": 9, "view": 0, "events": {"stable": 2.0},
         "votes": votes(1.0)}
    b = {"height": 9, "view": 0, "events": {"stable": 502.0},
         "votes": votes(501.0)}
    (doc,) = align_rounds(
        {"A": _snap("A", [a]), "B": _snap("B", [b])},
        offsets={"A": 0.0, "B": 500.0},
    )
    assert doc["straggler"] == 2
    assert doc["straggler_lateness_ms"] == pytest.approx(40.0)
    assert doc["vote_lateness_ms"]["0"] == 0.0


def test_round_doc_and_rounds_doc_shapes():
    a = {"height": 3, "view": 0, "events": {"stable": 1.0}, "votes": {}}
    ledgers = {"A": _snap("A", [a])}
    doc = round_doc(ledgers, height=3)
    assert doc["found"] and doc["rounds"][0]["height"] == 3
    assert not round_doc(ledgers, height=99)["found"]
    rr = rounds_doc(ledgers, last=5)
    assert rr["nodes"] == ["A"] and rr["skew_ms"]["n"] == 0


# -- federation over the in-proc mesh -----------------------------------------


def _label(node):
    return node.node_id.hex()[:8]


def _inject_round(node, height, base):
    led = node.engine.roundlog
    led.note(height, 0, "pre_prepare", t=base)
    for i in range(3):
        led.vote(height, 0, "prepare", i, t=base + 0.001 * (i + 1))
    led.note(height, 0, "prepared", t=base + 0.005)
    led.note(height, 0, "stable", t=base + 0.010)


def test_federation_merges_dead_and_slow_peers():
    """GET /fleet with one dead and one slow replica: every committee
    member appears (the dead one degraded), strikes accumulate on the dead
    peer, and the aligned rounds still merge the reachable ledgers."""
    nodes, _gw = make_chain(4)
    try:
        svc = nodes[0].fleet
        assert svc is not None
        svc.timeout = 0.25  # keep the dead peer's budget cheap
        for n in nodes:
            _inject_round(n, 1, base=10.0)
        # dead replica: frames vanish into a black hole
        dead = nodes[3]
        dead.front.register_module(
            ModuleID.FLEET_TELEMETRY, lambda src, payload: None
        )
        # slow replica: answers, but late (still inside the budget)
        slow = nodes[2]
        orig = slow.fleet._on_message

        def slow_handler(src, payload):
            time.sleep(0.05)
            orig(src, payload)

        slow.front.register_module(ModuleID.FLEET_TELEMETRY, slow_handler)

        doc = svc.fleet_doc()
        assert doc["enabled"] and doc["committee_size"] == 4
        assert set(doc["nodes"]) == {_label(n) for n in nodes}
        assert doc["nodes"][_label(dead)]["status"] == "unreachable"
        assert doc["nodes"][_label(slow)]["status"] == "ok"
        assert doc["reachable"] == 3
        assert doc["heights"][_label(slow)]["durable"] == 0
        # degraded, never missing: the dead peer still has a heights row
        assert _label(dead) in doc["heights"]
        # the reachable ledgers aligned: round 1 exists with 3+ observers
        rd = svc.round_forensics(1)
        assert rd["found"]
        assert len(rd["rounds"][0]["nodes"]) >= 3
        assert _label(dead) not in rd["rounds"][0]["nodes"]
        # strikes: every failed pull counts; after STRIKE_LIMIT the budget
        # quarters (pin the counter, the budget math is unit-level)
        svc.fleet_doc()
        assert svc._strikes.get(dead.node_id, 0) >= 3
    finally:
        for n in nodes:
            n.stop()


def test_probe_offset_corrects_shifted_clock():
    """A peer whose monotonic clock sits 5 s ahead still aligns: the probe
    measures the shift and the aligner subtracts it."""
    nodes, _gw = make_chain(2)
    try:
        shifted = nodes[1].engine.roundlog
        shifted.clock = lambda: time.perf_counter() + 5.0
        now0 = time.perf_counter()
        _inject_round(nodes[0], 1, base=now0)
        _inject_round(nodes[1], 1, base=now0 + 5.0)  # same wall instant
        svc = nodes[0].fleet
        offset, rtt = svc.probe_offset(nodes[1].node_id)
        assert offset == pytest.approx(5.0, abs=0.5)
        assert rtt < 2.0
        rd = svc.round_forensics(1)
        (aligned,) = rd["rounds"]
        assert aligned["skew_ms"] < 1000.0, aligned  # ~5000 uncorrected
    finally:
        for n in nodes:
            n.stop()


def test_idempotency_classification():
    from fisco_bcos_tpu.resilience.retry import is_idempotent

    for m in ("fleet", "round", "rounds", "fleet_pull"):
        assert is_idempotent(m), m
    assert not is_idempotent("handle")


# -- the flight recorder's death doors ----------------------------------------


def test_flight_flush_on_injected_crash(tmp_path, monkeypatch):
    """Door 1: the crash plan flushes the ring BEFORE raising — the dying
    node's black box shows the armed point firing."""
    monkeypatch.setenv("FISCO_FLIGHT_DIR", str(tmp_path))
    install_crash_plan(CrashPlan().arm("scheduler.mid_2pc", scope="aa11bb22"))
    try:
        with pytest.raises(InjectedCrash):
            crashpoint("scheduler.mid_2pc", scope="aa11bb22")
    finally:
        clear_crash_plan()
    doc = json.loads((tmp_path / "flight_aa11bb22.json").read_text())
    assert doc["reason"] == "crash:scheduler.mid_2pc"
    names = {(e["category"], e["name"]) for e in doc["events"]}
    assert ("crash", "armed") in names and ("crash", "fired") in names
    fired = [e for e in doc["events"]
             if e["category"] == "crash" and e["name"] == "fired"]
    assert fired[-1]["detail"]["point"] == "scheduler.mid_2pc"


def test_flight_flush_on_stop_and_fatal_halt(tmp_path, monkeypatch):
    """Doors 2+3: Node.stop and the whole-node fatal halt each flush, with
    the round ledger embedded so one file explains the death."""
    monkeypatch.setenv("FISCO_FLIGHT_DIR", str(tmp_path))
    nodes, _gw = make_chain(1)
    node = nodes[0]
    _inject_round(node, 1, base=1.0)
    scope = node.engine.crash_scope
    node._halt_injected()
    doc = json.loads((tmp_path / f"flight_{scope}.json").read_text())
    assert doc["reason"] == "fatal_halt"
    names = {(e["category"], e["name"]) for e in doc["events"]}
    assert ("halt", "fatal_injected") in names
    node.stop()
    doc = json.loads((tmp_path / f"flight_{scope}.json").read_text())
    assert doc["reason"] == "stop"
    assert any(r["height"] == 1 for r in doc["rounds"]["rounds"])
    pm = post_mortem(str(tmp_path))
    assert pm["nodes"][scope]["reason"] == "stop"
    assert any(e["category"] == "round" for e in pm["timeline"])


def test_flight_flush_on_sigterm_chains_previous_handler(tmp_path):
    """Door 4: SIGTERM flushes, then chains to the pre-existing handler —
    an operator kill leaves a black box without losing its shutdown."""
    hits = []
    old = signal.getsignal(signal.SIGTERM)
    signal.signal(signal.SIGTERM, lambda s, f: hits.append(s))
    try:
        install_signal_flush(lambda: "sigt-node", directory=str(tmp_path))
        signal.raise_signal(signal.SIGTERM)
        assert hits == [signal.SIGTERM]
        doc = json.loads((tmp_path / "flight_sigt-node.json").read_text())
        assert doc["reason"] == "sigterm"
        names = {(e["category"], e["name"]) for e in doc["events"]}
        assert ("halt", "sigterm") in names
    finally:
        signal.signal(signal.SIGTERM, old)
        flight_mod._prev_sigterm = None


def test_flight_ring_is_bounded_and_flush_is_atomic(tmp_path):
    fr = FlightRecorder(cap=8, clock=Ticker(step=1.0),
                        wallclock=Ticker(1000.0), enabled=True)
    for i in range(50):
        fr.record("t", f"e{i}")
    events = fr.snapshot()
    assert len(events) == 8
    assert events[-1]["name"] == "e49"
    path = fr.flush("ringtest", "test", directory=str(tmp_path))
    assert path and not (tmp_path / "flight_ringtest.json.tmp").exists()
    doc = json.loads((tmp_path / "flight_ringtest.json").read_text())
    assert len(doc["events"]) == 8 and doc["node"] == "ringtest"


def test_post_mortem_places_events_on_wall_clock(tmp_path):
    """wall = wall_at_flush - (mono_at_flush - t): two nodes with wildly
    different monotonic origins land on one comparable timeline."""
    a = FlightRecorder(clock=Ticker(10.0, step=1.0),
                       wallclock=lambda: 1000.0, enabled=True)
    b = FlightRecorder(clock=Ticker(5000.0, step=1.0),
                       wallclock=lambda: 1001.0, enabled=True)
    a.record("t", "a-event")
    b.record("t", "b-event")
    a.flush("nodeA", "test", directory=str(tmp_path))
    b.flush("nodeB", "test", directory=str(tmp_path))
    pm = post_mortem(str(tmp_path))
    by_node = {e["node"]: e["wall"] for e in pm["timeline"]}
    # a: event t=11, anchor (12, 1000) -> wall 999; b: t=5001, (5002, 1001)
    assert by_node["nodeA"] == pytest.approx(999.0)
    assert by_node["nodeB"] == pytest.approx(1000.0)
    assert [e["node"] for e in pm["timeline"]] == ["nodeA", "nodeB"]


# -- FISCO_FLEET_OBS=0: the observatory vanishes ------------------------------


def test_fleet_obs_off_is_noop(monkeypatch):
    monkeypatch.setenv("FISCO_FLEET_OBS", "0")
    assert not fleet_obs_enabled()
    from fisco_bcos_tpu.observability.fleet import build_fleet

    assert build_fleet(object()) is None
    nodes, _gw = make_chain(1, secret_base=88_100)
    try:
        node = nodes[0]
        assert node.fleet is None
        assert node.engine.roundlog is NOOP_LEDGER
        # every note is swallowed, nothing allocates
        node.engine.roundlog.note(1, 0, "pre_prepare")
        node.engine.roundlog.vote(1, 0, "prepare", 0)
        assert node.engine.roundlog.snapshot()["rounds"] == []
        fr = FlightRecorder()  # enabled=None reads the env
        fr.record("t", "e")
        assert fr.snapshot() == []
        assert fr.flush("off", "test") is None
    finally:
        for n in nodes:
            n.stop()


def test_fleet_module_not_registered_when_off(monkeypatch):
    monkeypatch.setenv("FISCO_FLEET_OBS", "0")
    nodes, _gw = make_chain(1, secret_base=88_200)
    try:
        assert int(ModuleID.FLEET_TELEMETRY) not in nodes[0].front._dispatch
    finally:
        for n in nodes:
            n.stop()


# -- endpoints: air node + Pro split ------------------------------------------


def test_http_endpoints_direct():
    from fisco_bcos_tpu.rpc.http_server import RpcHttpServer

    srv = RpcHttpServer(
        None, port=0,
        fleet=lambda: {"enabled": True, "nodes": {"x": {}}},
        round_doc=lambda h: {"found": h == 5, "height": h},
        rounds=lambda last: {"rounds": [], "last": last},
    )
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(f"{base}/fleet", timeout=10) as resp:
            assert json.loads(resp.read())["enabled"] is True
        with urllib.request.urlopen(f"{base}/round/5", timeout=10) as resp:
            assert json.loads(resp.read())["height"] == 5
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/round/6", timeout=10)
        assert ei.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/round/nan", timeout=10)
        assert ei.value.code == 404
        with urllib.request.urlopen(f"{base}/rounds?last=7", timeout=10) as resp:
            assert json.loads(resp.read())["last"] == 7
    finally:
        srv.stop()


def test_fleet_endpoints_over_pro_split():
    """The RPC front door serves /fleet, /round/<h> and /rounds by
    forwarding to the node core's facade (RemoteTelemetry) — the node core
    owns the mesh connection to every peer."""
    from fisco_bcos_tpu.service.rpc_service import RpcFacade, RpcService

    nodes, _gw = make_chain(1, secret_base=88_300)
    facade = rpc = None
    try:
        node = nodes[0]
        _inject_round(node, 2, base=4.0)
        facade = RpcFacade(None, fleet=node.fleet)
        facade.start()
        rpc = RpcService(facade.host, facade.port)
        rpc.start()
        base = f"http://127.0.0.1:{rpc.port}"
        with urllib.request.urlopen(f"{base}/fleet", timeout=15) as resp:
            doc = json.loads(resp.read())
        assert doc["enabled"] and doc["reachable"] == 1
        assert doc["committee_size"] == 1
        with urllib.request.urlopen(f"{base}/round/2", timeout=15) as resp:
            rd = json.loads(resp.read())
        assert rd["found"] and rd["rounds"][0]["height"] == 2
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/round/777", timeout=15)
        assert ei.value.code == 404
        with urllib.request.urlopen(f"{base}/rounds?last=4", timeout=15) as resp:
            rr = json.loads(resp.read())
        assert len(rr["rounds"]) == 1
    finally:
        if rpc is not None:
            rpc.stop()
        if facade is not None:
            facade.stop()
        for n in nodes:
            n.stop()


def test_fleet_split_degrades_without_fleet_service():
    """A facade wired without a fleet service (FISCO_FLEET_OBS=0 topology)
    still answers /fleet — with the explicit disabled document."""
    from fisco_bcos_tpu.service.rpc_service import RpcFacade, RpcService

    facade = RpcFacade(None)
    facade.start()
    rpc = RpcService(facade.host, facade.port)
    rpc.start()
    try:
        base = f"http://127.0.0.1:{rpc.port}"
        with urllib.request.urlopen(f"{base}/fleet", timeout=10) as resp:
            doc = json.loads(resp.read())
        assert doc["enabled"] is False and "FISCO_FLEET_OBS" in doc["reason"]
    finally:
        rpc.stop()
        facade.stop()


# -- live rounds through the real engine --------------------------------------


def test_engine_hooks_populate_ledger_on_live_chain():
    """Drive one block through real PBFT and read the forensics: every
    phase edge lands, every committee vote arrives, the fleet doc merges
    all four nodes and names a straggler."""
    from test_pbft import leader_of, submit_txs
    from test_pipeline import drain_chain

    nodes, _gw = make_chain(4, secret_base=88_400)
    try:
        leader = leader_of(nodes, 1)
        submit_txs(leader, 2, start=100)
        assert leader.sealer.seal_and_submit()
        assert all(n.block_number() == 1 for n in nodes)
        drain_chain(nodes)
        svc = nodes[0].fleet
        rd = svc.round_forensics(1)
        assert rd["found"]
        (aligned,) = [r for r in rd["rounds"] if r["view"] == 0]
        assert len(aligned["nodes"]) == 4
        assert "straggler" in aligned
        for phases in (n["phases"] for n in aligned["nodes"].values()):
            assert "prepare" in phases and "commit" in phases, phases
        doc = svc.fleet_doc()
        assert doc["reachable"] == 4
        assert all(
            h["durable"] == 1 for h in doc["heights"].values()
        ), doc["heights"]
        assert doc["round_skew_ms"]["n"] >= 1
    finally:
        for n in nodes:
            n.stop()
