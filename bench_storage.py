#!/usr/bin/env python
"""Storage throughput harness — the analog of the reference's
tests/perf/benchmark.cpp (StateStorage vs KeyPageStorage vs RocksDB
read/write CLI, /root/reference/tests/perf/benchmark.cpp:22-100).

Prints one JSON line per (backend, op) with rows/s. Usage:

    python bench_storage.py [N]          # default 20k rows

Backends: StateStorage overlay (the executor's working set),
KeyPageStorage (page-packed key layout), SqliteStorage (the durable
RocksDB analog, batch-committed).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

from fisco_bcos_tpu.storage.entry import Entry
from fisco_bcos_tpu.storage.keypage import KeyPageStorage
from fisco_bcos_tpu.storage.memory_storage import MemoryStorage
from fisco_bcos_tpu.storage.sqlite_storage import SQLiteStorage
from fisco_bcos_tpu.storage.state_storage import StateStorage

TABLE = "t_bench"


def _emit(backend: str, op: str, n: int, dt: float) -> None:
    print(
        json.dumps(
            {
                "metric": f"storage_{backend}_{op}_rows_per_s",
                "value": round(n / dt, 1),
                "unit": "rows/s",
                "n": n,
            }
        ),
        flush=True,
    )


def _bench(backend: str, store, n: int, batched=None) -> None:
    keys = [b"key-%08d" % i for i in range(n)]
    entries = [Entry({"value": b"v" * 32 + b"%08d" % i}) for i in range(n)]
    t0 = time.perf_counter()
    if batched is not None:
        batched(TABLE, list(zip(keys, entries)))
    else:
        for k, e in zip(keys, entries):
            store.set_row(TABLE, k, e)
    _emit(backend, "write", n, time.perf_counter() - t0)
    t0 = time.perf_counter()
    miss = 0
    for k in keys:
        if store.get_row(TABLE, k) is None:
            miss += 1
    dt = time.perf_counter() - t0
    assert miss == 0, f"{backend}: {miss} missing rows"
    _emit(backend, "read", n, dt)


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 20_000

    _bench("state_storage", StateStorage(MemoryStorage()), n)
    kp = KeyPageStorage(MemoryStorage())
    _bench("keypage", kp, n, batched=kp.set_rows)

    with tempfile.TemporaryDirectory() as d:
        sq = SQLiteStorage(os.path.join(d, "bench.db"))
        _bench("sqlite", sq, n, batched=sq.set_rows)


if __name__ == "__main__":
    main()
