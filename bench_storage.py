#!/usr/bin/env python
"""Storage throughput harness — the analog of the reference's
tests/perf/benchmark.cpp (StateStorage vs KeyPageStorage vs RocksDB
read/write CLI, /root/reference/tests/perf/benchmark.cpp:22-100).

Prints one JSON line per (backend, op) with rows/s. Usage:

    python bench_storage.py [N]          # default 20k rows

Backends: StateStorage overlay (the executor's working set),
KeyPageStorage (page-packed key layout), SqliteStorage (the durable
RocksDB analog, batch-committed).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

from fisco_bcos_tpu.storage.entry import Entry
from fisco_bcos_tpu.storage.keypage import KeyPageStorage
from fisco_bcos_tpu.storage.memory_storage import MemoryStorage
from fisco_bcos_tpu.storage.sqlite_storage import SQLiteStorage
from fisco_bcos_tpu.storage.state_storage import StateStorage

TABLE = "t_bench"


def _emit(backend: str, op: str, n: int, dt: float) -> dict:
    rec = {
        "metric": f"storage_{backend}_{op}_rows_per_s",
        "value": round(n / dt, 1),
        "unit": "rows/s",
        "n": n,
        "backend": backend,
        "op": op,
    }
    print(json.dumps(rec), flush=True)
    return rec


def _bench(backend: str, store, n: int, batched=None, results=None) -> None:
    keys = [b"key-%08d" % i for i in range(n)]
    entries = [Entry({"value": b"v" * 32 + b"%08d" % i}) for i in range(n)]
    t0 = time.perf_counter()
    if batched is not None:
        batched(TABLE, list(zip(keys, entries)))
    else:
        for k, e in zip(keys, entries):
            store.set_row(TABLE, k, e)
    rec = _emit(backend, "write", n, time.perf_counter() - t0)
    if results is not None:
        results.append(rec)
    t0 = time.perf_counter()
    miss = 0
    for k in keys:
        if store.get_row(TABLE, k) is None:
            miss += 1
    dt = time.perf_counter() - t0
    assert miss == 0, f"{backend}: {miss} missing rows"
    rec = _emit(backend, "read", n, dt)
    if results is not None:
        results.append(rec)


def run(n: int = 20_000, deadline: float | None = None) -> list[dict]:
    """All three backend legs; under bench.py's ``--only storage`` child
    the monotonic ``deadline`` stops BETWEEN legs, so a slow disk costs
    the remaining legs' lines, never a budget-killed child."""
    results: list[dict] = []

    def expired(leg: str) -> bool:
        if deadline is not None and time.monotonic() > deadline:
            print(f"# bench_storage: deadline before {leg} leg", flush=True)
            return True
        return False

    if not expired("state_storage"):
        _bench(
            "state_storage", StateStorage(MemoryStorage()), n, results=results
        )
    if not expired("keypage"):
        kp = KeyPageStorage(MemoryStorage())
        _bench("keypage", kp, n, batched=kp.set_rows, results=results)
    if not expired("sqlite"):
        with tempfile.TemporaryDirectory() as d:
            sq = SQLiteStorage(os.path.join(d, "bench.db"))
            _bench("sqlite", sq, n, batched=sq.set_rows, results=results)
    return results


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 20_000
    run(n)


if __name__ == "__main__":
    main()
