"""RPC service — the JSON-RPC front door as its own process.

Reference: fisco-bcos-tars-service/RpcService (RpcServiceServer.cpp): in the
Pro topology the HTTP/WS listener runs as its own process and forwards every
JSON-RPC request to the node core over service RPC. The node hosts an
`RpcFacade` server wrapping its JsonRpcImpl; the RPC process runs the
standard RpcHttpServer with a forwarding `handle` — transport parsing stays
in the RPC process, chain logic stays in the node.

    client ──HTTP──▶ [rpc process] RpcHttpServer(RemoteJsonRpc) ──RPC──▶
                     [node process] RpcFacade(JsonRpcImpl.handle)
"""

from __future__ import annotations

import json

from ..codec.flat import FlatReader, FlatWriter
from ..utils.log import get_logger
from .rpc import ServiceClient, ServiceServer

_log = get_logger("rpc-svc")


class RpcFacade:
    """Node-side server exposing JsonRpcImpl.handle over service RPC, plus
    the node's telemetry surface (`metrics`/`trace` methods) so the RPC
    process can serve `GET /metrics` and `GET /trace` for the whole split
    deployment — the node core owns the registry and tracer, the RPC
    process only forwards."""

    def __init__(
        self, impl, host: str = "127.0.0.1", port: int = 0, metrics=None,
        tracer=None, health=None, fleet=None,
    ):
        self.impl = impl
        self.metrics = metrics
        self.tracer = tracer
        # degraded-mode registry (resilience.HEALTH shape: .to_json());
        # served to the RPC process for GET /health
        self.health = health
        # fleet observatory (ISSUE 16): the node core owns the FleetService
        # (mesh access + round ledger); the RPC process only forwards
        self.fleet = fleet
        self.server = ServiceServer("rpc-facade", host, port)
        self.server.register("handle", self._handle)
        self.server.register("metrics", self._metrics)
        self.server.register("trace", self._trace)
        self.server.register("trace_tx", self._trace_tx)
        self.server.register("health", self._health)
        self.server.register("pipeline", self._pipeline)
        self.server.register("device", self._device)
        # concurrent: the profiler blocks for seconds reading only
        # sys._current_frames() — under the dispatch lock one /profile
        # would stall every JSON-RPC call on the split
        self.server.register("profile", self._profile, concurrent=True)
        # concurrent: a fleet merge waits out per-peer deadlines against
        # dead peers (seconds) — it must never serialize JSON-RPC traffic
        self.server.register("fleet", self._fleet, concurrent=True)
        self.server.register("round", self._round, concurrent=True)
        self.server.register("rounds", self._rounds, concurrent=True)
        # concurrent, the /profile lesson: a slow observability GET must
        # not stall JSON-RPC dispatch (the snapshot walks the whole block
        # ring + shard samples under the recorder lock)
        self.server.register("storage", self._storage, concurrent=True)
        self.host, self.port = self.server.host, self.server.port

    def start(self) -> None:
        self.server.start()

    def stop(self) -> None:
        self.server.stop()

    def _handle(self, payload: bytes) -> bytes:
        req = json.loads(payload)
        return json.dumps(self.impl.handle(req)).encode()

    def _metrics(self, payload: bytes) -> bytes:
        if self.metrics is None:
            return b""
        if payload == b"openmetrics":
            # no silent downgrade: the RPC process labels the response
            # application/openmetrics-text, so a renderer without the
            # kwarg must surface as an error reply, not classic text
            # masquerading as OpenMetrics (no '# EOF', no exemplars)
            return self.metrics.render(openmetrics=True).encode()
        return self.metrics.render().encode()

    def _trace(self, _payload: bytes) -> bytes:
        if self.tracer is None:
            return b'{"traceEvents": []}'
        return self.tracer.export_json().encode()

    def _trace_tx(self, payload: bytes) -> bytes:
        """Raw (un-analyzed) critical-path collection for one tx hash hex:
        the node core owns the tx/block indexes; the RPC process merges its
        OWN ring's spans (the submit root lives there) before analyzing."""
        if self.tracer is None:
            return b'{"found": false, "spans": []}'
        from ..observability import critical_path

        return json.dumps(
            critical_path.collect(payload.decode()), default=str
        ).encode()

    def _health(self, _payload: bytes) -> bytes:
        if self.health is None:
            return b'{"status": "ok", "components": {}}'
        return self.health.to_json().encode()

    def _pipeline(self, _payload: bytes) -> bytes:
        """The node core's stage-occupancy/watermark document — the split
        deployment's GET /pipeline source (the pipeline lives where the
        pipeline workers live)."""
        from ..observability.pipeline import pipeline_doc

        return json.dumps(pipeline_doc(), default=str).encode()

    def _device(self, _payload: bytes) -> bytes:
        """The node core's device-observatory document (compile ledger,
        phase totals, memory watermarks) — the split deployment's
        GET /device source: compiles happen where the DevicePlane lives."""
        from ..observability.device import device_doc

        return json.dumps(device_doc(), default=str).encode()

    def _storage(self, _payload: bytes) -> bytes:
        """The node core's storage-observatory document (codec ledger,
        per-block copy amplification, shard 2PC attribution) — the split
        deployment's GET /storage source: the commit path runs where the
        scheduler lives."""
        from ..observability.storagelog import storage_doc

        return json.dumps(storage_doc(), default=str).encode()

    def _profile(self, payload: bytes) -> bytes:
        """Sample THIS process (the node core — where the pipeline burns
        its wall time) for the requested seconds. Clamped server-side
        below the telemetry proxy's RPC timeout — the client-side clamp
        in RemoteTelemetry must not be the only guard."""
        from ..observability import profiler

        try:
            seconds = float(payload.decode() or "2")
        except ValueError:
            seconds = 2.0
        return json.dumps(
            profiler.profile(min(seconds, 8.0)), default=str
        ).encode()

    def _fleet(self, _payload: bytes) -> bytes:
        """The merged cluster document — the split deployment's GET /fleet
        source: the node core holds the mesh connection to every peer."""
        if self.fleet is None:
            from ..observability.fleet import DISABLED_DOC

            return json.dumps(DISABLED_DOC).encode()
        return json.dumps(self.fleet.fleet_doc(), default=str).encode()

    def _round(self, payload: bytes) -> bytes:
        if self.fleet is None:
            return b'{"found": false, "reason": "FISCO_FLEET_OBS=0"}'
        try:
            height = int(payload.decode() or "0")
        except ValueError:
            height = 0
        return json.dumps(
            self.fleet.round_forensics(height), default=str
        ).encode()

    def _rounds(self, payload: bytes) -> bytes:
        if self.fleet is None:
            return b'{"rounds": [], "reason": "FISCO_FLEET_OBS=0"}'
        try:
            last = int(payload.decode() or "32")
        except ValueError:
            last = 32
        return json.dumps(
            self.fleet.rounds_forensics(last), default=str
        ).encode()


class RemoteJsonRpc:
    """RPC-process-side `handle` that forwards requests to the node's
    facade — a drop-in for JsonRpcImpl wherever a transport needs one
    (RpcHttpServer, WsService request path)."""

    def __init__(self, host: str, port: int, timeout: float = 120.0):
        self.client = ServiceClient(host, port, timeout)

    def handle(self, request: dict) -> dict:
        try:
            method = request.get("method", "")
            from ..rpc.jsonrpc import TRACED_RPC_METHODS

            if method in TRACED_RPC_METHODS:
                from ..observability import TRACER

                # the split deployment's lifecycle root: opened in the RPC
                # process, continued by the node core via the traceparent
                # the service client injects into the facade call. Read
                # polls stay span-free (same ring-churn guard as
                # JsonRpcImpl.handle).
                with TRACER.span("rpc.forward", method=method):
                    resp = self.client.call(
                        "handle", json.dumps(request).encode()
                    )
            else:
                resp = self.client.call("handle", json.dumps(request).encode())
            return json.loads(resp)
        except Exception as e:
            _log.exception("facade call failed")
            return {
                "jsonrpc": "2.0",
                "id": request.get("id"),
                "error": {"code": -32603, "message": f"node unreachable: {e}"},
            }

    def close(self) -> None:
        self.client.close()


class RemoteTelemetry:
    """RPC-process-side metrics/trace proxy over the node facade — duck-
    compatible with MetricsRegistry.render / Tracer.export_json where
    RpcHttpServer needs them. A facade without the telemetry methods (or an
    unreachable node) degrades to empty output, never a 500. Owns its OWN
    ServiceClient (short timeout): ServiceClient serializes calls on one
    connection lock, so a scrape against a stalled node core must never
    queue JSON-RPC requests behind it (nor the reverse)."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.client = ServiceClient(host, port, timeout)

    def render(self, openmetrics: bool = False) -> str:
        try:
            return self.client.call(
                "metrics", b"openmetrics" if openmetrics else b""
            ).decode()
        except Exception:
            return ""

    def export_json(self) -> str:
        try:
            return self.client.call("trace").decode()
        except Exception:
            return '{"traceEvents": []}'

    def trace_tx(self, tx_hash_hex: str) -> dict:
        """Stitch one tx's critical path ACROSS the split: the node core's
        collection (its ring + indexes) merged with THIS process's spans —
        the submit root and any rpc-process work belong to the same trace
        but live in this ring, not the node's."""
        from ..observability import critical_path

        try:
            doc = json.loads(
                self.client.call("trace_tx", tx_hash_hex.encode())
            )
        except Exception:
            return {"found": False, "txHash": tx_hash_hex, "spans": []}
        if doc.get("found"):
            trace_ids = {int(t, 16) for t in doc.get("traceIds", ())}
            local = critical_path.local_spans_for(trace_ids, doc.get("block"))
            known = {(s["trace_id"], s["span_id"]) for s in doc["spans"]}
            doc["spans"].extend(
                s for s in local if (s["trace_id"], s["span_id"]) not in known
            )
        return critical_path.analyze(doc)

    def pipeline(self) -> dict:
        """GET /pipeline over the split: the node core owns the stage
        recorder; an unreachable core degrades to an explicit error doc."""
        try:
            return json.loads(self.client.call("pipeline", b""))
        except Exception as e:
            return {
                "enabled": False,
                "error": f"facade unreachable: {e}",
                "stages": {},
                "watermarks": {},
            }

    def device(self) -> dict:
        """GET /device over the split: the node core owns the compile
        ledger; an unreachable core degrades to an explicit error doc."""
        try:
            return json.loads(self.client.call("device", b""))
        except Exception as e:
            return {
                "enabled": False,
                "error": f"facade unreachable: {e}",
                "ledger": [],
                "phase_ms": {},
            }

    def storage(self) -> dict:
        """GET /storage over the split: the node core owns the commit-path
        ledger; an unreachable core degrades to an explicit error doc."""
        try:
            return json.loads(self.client.call("storage", b""))
        except Exception as e:
            return {
                "enabled": False,
                "error": f"facade unreachable: {e}",
                "codec": {},
                "blocks": [],
                "shards": {},
            }

    def profile(self, seconds=2.0) -> dict:
        """GET /profile over the split — samples the NODE CORE process.
        Clamped below this proxy's RPC timeout so a long profile can never
        read as a dead facade."""
        try:
            seconds = min(float(seconds), 8.0)
        except (TypeError, ValueError):
            seconds = 2.0
        try:
            return json.loads(
                self.client.call("profile", str(seconds).encode())
            )
        except Exception as e:
            return {"error": f"facade unreachable: {e}"}

    def fleet(self) -> dict:
        """GET /fleet over the split: the node core runs the federation
        pull; an unreachable core degrades to an explicit error doc."""
        try:
            return json.loads(self.client.call("fleet", b""))
        except Exception as e:
            return {
                "enabled": False,
                "error": f"facade unreachable: {e}",
                "nodes": {},
            }

    def round_doc(self, height) -> dict:
        """GET /round/<h> over the split — cross-node forensics for one
        consensus height, assembled by the node core."""
        try:
            return json.loads(self.client.call("round", str(int(height)).encode()))
        except Exception as e:
            return {"found": False, "error": f"facade unreachable: {e}"}

    def rounds(self, last=32) -> dict:
        """GET /rounds over the split — recent rounds + skew percentiles."""
        try:
            last = int(last)
        except (TypeError, ValueError):
            last = 32
        try:
            return json.loads(self.client.call("rounds", str(last).encode()))
        except Exception as e:
            return {"rounds": [], "error": f"facade unreachable: {e}"}

    def to_json(self) -> str:
        """Health JSON for GET /health. An unreachable node core IS a
        degraded deployment — report it as such instead of erroring."""
        try:
            return self.client.call("health").decode()
        except Exception as e:
            return json.dumps(
                {
                    "status": "critical",  # no node core = not serving
                    "components": {
                        "node-core": {
                            "status": "degraded",
                            "reason": f"facade unreachable: {e}",
                            "critical": True,
                        }
                    },
                }
            )

    def close(self) -> None:
        self.client.close()


class RpcService:
    """The RPC process: HTTP JSON-RPC listener over a remote node facade
    (RpcServiceServer's process shape). `/metrics` and `/trace` forward to
    the node core's registry/tracer by default (split-mode deployments used
    to serve an empty `/metrics` because nothing bound node metrics here)."""

    def __init__(
        self,
        facade_host: str,
        facade_port: int,
        host: str = "127.0.0.1",
        port: int = 0,
        ssl_context=None,
        metrics=None,
        tracer=None,
        health=None,
    ):
        from ..rpc.http_server import RpcHttpServer

        self.remote = RemoteJsonRpc(facade_host, facade_port)
        self.telemetry = RemoteTelemetry(facade_host, facade_port)
        self.http = RpcHttpServer(
            self.remote, host=host, port=port, ssl_context=ssl_context,
            metrics=metrics if metrics is not None else self.telemetry,
            tracer=tracer if tracer is not None else self.telemetry,
            health=health if health is not None else self.telemetry,
        )
        self.port = self.http.port

    def start(self) -> None:
        self.http.start()

    def stop(self) -> None:
        self.http.stop()
        self.remote.close()
        self.telemetry.close()
