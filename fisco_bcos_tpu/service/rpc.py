"""Length-framed service RPC over TCP — the tars-RPC transport analog.

Reference: bcos-tars-protocol's service clients ride tars RPC between
microservices; this transport carries the same request/response shape with
the framework's flat codec:

    frame   = u32 len ‖ body
    request = u64 id ‖ str method ‖ str traceparent ‖ bytes payload
    reply   = u64 id ‖ u8 ok ‖ bytes payload-or-error

The ``traceparent`` field is the W3C-style trace context
(``00-<trace_id>-<span_id>-<flags>``, empty when the caller has none):
the client injects its ambient context, the server re-attaches it around
the handler and wraps dispatch in a ``svc.<service>.<method>`` span — so
one trace follows a call across the Pro/Max service split.

Servers dispatch method -> handler(payload bytes) -> payload bytes; the
client is synchronous (one in-flight pipeline per connection, matching how
the scheduler drives an executor).

Resilience contract (resilience/): protocol violations raise TYPED errors
(:class:`BadFrame`/:class:`FrameTooLarge`) instead of surfacing as silent
``None`` frames; the client separates connect and recv timeouts, honors
per-call :class:`~fisco_bcos_tpu.resilience.retry.Deadline` budgets, and —
when built with a :class:`~fisco_bcos_tpu.resilience.retry.RetryPolicy` —
auto-retries *classified-idempotent* methods across redials with capped
exponential backoff. The fault-injection layer
(:mod:`fisco_bcos_tpu.resilience.faults`) hooks the connect/send/recv
seams; with no plan installed each hook is one global pointer read.
"""

from __future__ import annotations

import contextlib
import itertools
import socket
import struct
import threading
from typing import Callable

# shared no-op guard for register(concurrent=True) handlers
_NULL_CTX = contextlib.nullcontext()

from ..codec.flat import FlatReader, FlatWriter
from ..observability.tracer import TRACER, TraceContext
from ..resilience import faults
from ..resilience.retry import Deadline, RetryPolicy, is_idempotent
from ..utils.log import get_logger, note_swallowed

_log = get_logger("service-rpc")

faults.ensure_env_plan()

_MAX_FRAME = 256 * 1024 * 1024


class ServiceRemoteError(RuntimeError):
    pass


class ServiceConnectionError(ServiceRemoteError):
    """Transport-level loss (dial failed / connection dropped) as a TYPE:
    failover seams (storage switch handler, limiter fallback) key on this
    class, never on message text — a remote handler error whose text happens
    to mention connections must not trip a term switch."""


class BadFrame(ServiceRemoteError):
    """A wire-protocol violation (undecodable frame, desynced reply id) —
    the connection is poisoned and gets dropped, but the caller learns WHY
    instead of seeing a silent ``None``."""


class FrameTooLarge(BadFrame):
    """A frame header larger than ``_MAX_FRAME`` (corruption or abuse)."""


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


def _send_frame(sock: socket.socket, body: bytes, scope: str = "") -> None:
    wire = struct.pack("<I", len(body)) + body
    plan = faults._PLAN
    if plan is not None:
        chunks, kill = plan.on_send(scope, wire)
        for c in chunks:
            sock.sendall(c)
        if kill:
            raise faults.InjectedFault(f"injected connection kill at {scope}")
        return
    sock.sendall(wire)


def _recv_frame(sock: socket.socket, scope: str = "") -> bytes | None:
    """One framed body; ``None`` on orderly/connection loss; raises
    :class:`FrameTooLarge` on an over-limit header and :class:`BadFrame` on
    a zero-length one (both logged — the old behavior silently returned
    ``None`` and the caller could not tell corruption from a peer close)."""
    head = _recv_exact(sock, 4)
    if head is None:
        return None
    (n,) = struct.unpack("<I", head)
    if n > _MAX_FRAME:
        _log.warning("frame header %d exceeds cap %d at %s", n, _MAX_FRAME, scope)
        raise FrameTooLarge(f"frame of {n} bytes exceeds {_MAX_FRAME} cap")
    if n == 0:
        _log.warning("zero-length frame at %s", scope)
        raise BadFrame("zero-length frame")
    body = _recv_exact(sock, n)
    if body is None:
        return None
    plan = faults._PLAN
    if plan is not None:
        body = plan.on_recv(scope, body)  # may drop/truncate/raise
        if body is None:
            return None
    return body


class ServiceServer:
    """Hosts named methods for one service (a tars servant analog)."""

    def __init__(self, name: str, host: str = "127.0.0.1", port: int = 0):
        self.name = name
        self._methods: dict[str, Callable[[bytes], bytes]] = {}
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self.host, self.port = self._listener.getsockname()
        self._stop = threading.Event()
        # fault-plan scope: rules target a servant by name or by port
        self._scope = f"svc:{name}:{self.port}"
        # one lock: service handlers mutate shared state (executor block
        # context, storage), and tars servants are effectively serialized too
        self._dispatch_lock = threading.Lock()
        # methods opted OUT of that serialization (register(concurrent=True)):
        # read-only handlers that touch no shared mutable state and may block
        # for seconds (the sampling profiler) — serializing them would let
        # one GET /profile stall every JSON-RPC call on the split
        self._concurrent: set[str] = set()
        # live connections, closed on stop so a stopped service drops its
        # clients like a crashed process would (failover tests depend on it)
        self._conns: set[socket.socket] = set()

    def register(
        self, method: str, fn: Callable[[bytes], bytes],
        concurrent: bool = False,
    ) -> None:
        self._methods[method] = fn
        if concurrent:
            self._concurrent.add(method)

    def start(self) -> None:
        threading.Thread(
            target=self._accept_loop, name=f"svc-{self.name}", daemon=True
        ).start()
        _log.info("service %s listening on %s:%d", self.name, self.host, self.port)

    def stop(self) -> None:
        self._stop.set()
        try:
            # shutdown BEFORE close: close alone does not release the
            # listening socket while the accept thread is parked inside the
            # accept(2) syscall (the open file description outlives the fd),
            # leaving the port in LISTEN and un-rebindable
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        for sock in list(self._conns):
            try:
                # RST, not FIN: a stopped service must free its port at once
                # (no FIN_WAIT/TIME_WAIT) so a restart can rebind — the same
                # abrupt teardown a crashed process would produce
                sock.setsockopt(
                    socket.SOL_SOCKET,
                    socket.SO_LINGER,
                    struct.pack("ii", 1, 0),
                )
                sock.shutdown(socket.SHUT_RDWR)
                sock.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(sock,), name=f"svc-{self.name}-conn",
                daemon=True,
            ).start()

    def _serve(self, sock: socket.socket) -> None:
        self._conns.add(sock)
        while not self._stop.is_set():
            try:
                body = _recv_frame(sock, self._scope)
            except BadFrame as e:
                # poisoned stream: drop the connection, the client redials;
                # counted so a corrupt-frame storm is visible at /metrics
                note_swallowed("service.rpc.bad_frame", e)
                _log.warning("service %s: %s — dropping connection", self.name, e)
                break
            except OSError:
                break
            if body is None:
                break
            try:
                r = FlatReader(body)
                req_id = r.u64()
                method = r.str_()
                traceparent = r.str_()
                payload = r.bytes_()
                r.done()
            except Exception as e:
                # an undecodable REQUEST frame desyncs the pipeline: typed
                # log + connection drop (was: thread death with no trace);
                # counted — injected `corrupt` faults land here and the
                # scenario lab asserts the rejects are observable
                note_swallowed("service.rpc.bad_request", e)
                _log.warning(
                    "service %s: bad request frame (%s) — dropping connection",
                    self.name, e,
                )
                break
            w = FlatWriter()
            w.u64(req_id)
            fn = self._methods.get(method)
            try:
                if fn is None:
                    raise ValueError(f"unknown method {method}")
                ctx = (
                    TraceContext.from_traceparent(traceparent)
                    if traceparent and TRACER.enabled
                    else None
                )
                with (
                    _NULL_CTX
                    if method in self._concurrent
                    else self._dispatch_lock
                ):
                    if ctx is not None:
                        # the remote caller's trace continues here: the
                        # handler (and every span it opens) joins it
                        with TRACER.attach(ctx), TRACER.span(
                            f"svc.{self.name}.{method}"
                        ):
                            out = fn(payload)
                    else:
                        out = fn(payload)
                w.u8(1)
                w.bytes_(out)
            except Exception as e:  # error crosses the wire, not the stack
                _log.info("service %s.%s failed: %s", self.name, method, e)
                w.u8(0)
                w.bytes_(str(e).encode())
            try:
                _send_frame(sock, w.out(), self._scope)
            except OSError:
                break
        self._conns.discard(sock)
        try:
            sock.close()
        except OSError:
            pass


class ServiceClient:
    """Self-healing: a transport failure poisons only the CURRENT call —
    the broken socket is discarded and the next call redials, so a service
    restart (gateway/rpc/executor process bounce) heals without restarting
    every client process (tars proxies reconnect the same way).

    ``timeout`` bounds each recv (a hung servant surfaces as a typed
    connection error instead of a wedged caller); ``connect_timeout``
    bounds the dial separately (a dead endpoint refuses in milliseconds, a
    blackholed one in seconds — not the full IO budget). With ``retry``
    set, calls to classified-idempotent methods (resilience.retry) survive
    transient connection loss via redial + capped exponential backoff; a
    per-call ``deadline`` caps the whole retry loop AND the socket waits.
    """

    def __init__(
        self,
        host: str,
        port: int,
        timeout: float = 60.0,
        connect_timeout: float = 5.0,
        retry: RetryPolicy | None = None,
        scope: str | None = None,
    ):
        self._addr = (host, port)
        self._timeout = timeout
        self._connect_timeout = min(connect_timeout, timeout)
        self._retry = retry
        # fault-plan scope: rules target a client by endpoint
        self._scope = scope or f"{host}:{port}"
        # LAZY dial: the first call connects. Constructing a client of a
        # not-yet-/currently-down service must not crash the mounting
        # process — every caller with a failover path (gateway limiter,
        # storage switch seam) depends on construction always succeeding.
        self.sock: socket.socket | None = None
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    def _drop_sock_locked(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None

    def call(
        self,
        method: str,
        payload: bytes = b"",
        deadline: Deadline | None = None,
    ) -> bytes:
        """One request/response exchange. Auto-retries connection loss for
        idempotent methods when the client has a RetryPolicy; every attempt
        (and every backoff sleep) stays inside ``deadline`` when given."""
        policy = self._retry
        if policy is None or not is_idempotent(method):
            return self._call_once(method, payload, deadline)
        # BadFrame retries too: a corrupt/desynced stream was already
        # dropped, so the re-attempt starts from a clean redial
        return policy.run(
            self._call_once,
            method,
            payload,
            deadline,
            retry_on=(ServiceConnectionError, BadFrame),
            deadline=deadline,
        )

    def _call_once(
        self, method: str, payload: bytes, deadline: Deadline | None = None
    ) -> bytes:
        scope = f"{self._scope}/{method}"
        if deadline is not None:
            deadline.check(method)
        with self._lock:
            if self.sock is None:
                try:
                    plan = faults._PLAN
                    if plan is not None:
                        plan.on_connect(self._scope)
                    dial = self._connect_timeout
                    if deadline is not None:
                        dial = deadline.clamp(dial)
                    self.sock = socket.create_connection(self._addr, timeout=dial)
                    # the dial timeout must not linger as the IO timeout
                    self.sock.settimeout(self._timeout)
                except OSError as e:
                    raise ServiceConnectionError(f"{method}: reconnect failed: {e}")
            if deadline is not None:
                # bound this exchange by what is left of the call budget
                self.sock.settimeout(deadline.clamp(self._timeout))
            req_id = next(self._ids)
            w = FlatWriter()
            w.u64(req_id)
            w.str_(method)
            # trace context crosses the split here; "" when the tracer is
            # off or nothing is in flight (one contextvar read either way)
            w.str_(TRACER.current_traceparent())
            w.bytes_(payload)
            bad: BadFrame | None = None
            try:
                _send_frame(self.sock, w.out(), scope)
                body = _recv_frame(self.sock, scope)
            except BadFrame as e:
                body, bad = None, e
            except OSError:
                body = None
            if body is None:
                self._drop_sock_locked()
            elif deadline is not None:
                self.sock.settimeout(self._timeout)  # restore for next call
        if bad is not None:
            raise bad
        if body is None:
            raise ServiceConnectionError(f"{method}: connection lost")
        try:
            r = FlatReader(body)
            got_id = r.u64()
            ok = r.u8()
            out = r.bytes_()
            r.done()
        except Exception as e:
            with self._lock:
                self._drop_sock_locked()  # reply stream is garbage: resync by redial
            raise BadFrame(f"{method}: undecodable reply ({e})")
        if got_id != req_id:
            # a stale reply (e.g. a duplicated request's second answer) has
            # desynced the pipeline; drop the socket so the next call starts
            # from a clean stream instead of shifting every reply by one
            with self._lock:
                self._drop_sock_locked()
            raise BadFrame(f"{method}: response id mismatch")
        if not ok:
            raise ServiceRemoteError(f"{method}: {out.decode(errors='replace')}")
        return out

    def close(self) -> None:
        with self._lock:
            self._drop_sock_locked()
