"""Length-framed service RPC over TCP — the tars-RPC transport analog.

Reference: bcos-tars-protocol's service clients ride tars RPC between
microservices; this transport carries the same request/response shape with
the framework's flat codec:

    frame   = u32 len ‖ body
    request = u64 id ‖ str method ‖ bytes payload
    reply   = u64 id ‖ u8 ok ‖ bytes payload-or-error

Servers dispatch method -> handler(payload bytes) -> payload bytes; the
client is synchronous (one in-flight pipeline per connection, matching how
the scheduler drives an executor).
"""

from __future__ import annotations

import itertools
import socket
import struct
import threading
from typing import Callable

from ..codec.flat import FlatReader, FlatWriter
from ..utils.log import get_logger

_log = get_logger("service-rpc")

_MAX_FRAME = 256 * 1024 * 1024


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


def _send_frame(sock: socket.socket, body: bytes) -> None:
    sock.sendall(struct.pack("<I", len(body)) + body)


def _recv_frame(sock: socket.socket) -> bytes | None:
    head = _recv_exact(sock, 4)
    if head is None:
        return None
    (n,) = struct.unpack("<I", head)
    if not 0 < n <= _MAX_FRAME:
        return None
    return _recv_exact(sock, n)


class ServiceServer:
    """Hosts named methods for one service (a tars servant analog)."""

    def __init__(self, name: str, host: str = "127.0.0.1", port: int = 0):
        self.name = name
        self._methods: dict[str, Callable[[bytes], bytes]] = {}
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self.host, self.port = self._listener.getsockname()
        self._stop = threading.Event()
        # one lock: service handlers mutate shared state (executor block
        # context, storage), and tars servants are effectively serialized too
        self._dispatch_lock = threading.Lock()
        # live connections, closed on stop so a stopped service drops its
        # clients like a crashed process would (failover tests depend on it)
        self._conns: set[socket.socket] = set()

    def register(self, method: str, fn: Callable[[bytes], bytes]) -> None:
        self._methods[method] = fn

    def start(self) -> None:
        threading.Thread(
            target=self._accept_loop, name=f"svc-{self.name}", daemon=True
        ).start()
        _log.info("service %s listening on %s:%d", self.name, self.host, self.port)

    def stop(self) -> None:
        self._stop.set()
        try:
            # shutdown BEFORE close: close alone does not release the
            # listening socket while the accept thread is parked inside the
            # accept(2) syscall (the open file description outlives the fd),
            # leaving the port in LISTEN and un-rebindable
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        for sock in list(self._conns):
            try:
                # RST, not FIN: a stopped service must free its port at once
                # (no FIN_WAIT/TIME_WAIT) so a restart can rebind — the same
                # abrupt teardown a crashed process would produce
                sock.setsockopt(
                    socket.SOL_SOCKET,
                    socket.SO_LINGER,
                    struct.pack("ii", 1, 0),
                )
                sock.shutdown(socket.SHUT_RDWR)
                sock.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(sock,), name=f"svc-{self.name}-conn",
                daemon=True,
            ).start()

    def _serve(self, sock: socket.socket) -> None:
        self._conns.add(sock)
        while not self._stop.is_set():
            body = _recv_frame(sock)
            if body is None:
                break
            r = FlatReader(body)
            req_id = r.u64()
            method = r.str_()
            payload = r.bytes_()
            r.done()
            w = FlatWriter()
            w.u64(req_id)
            fn = self._methods.get(method)
            try:
                if fn is None:
                    raise ValueError(f"unknown method {method}")
                with self._dispatch_lock:
                    out = fn(payload)
                w.u8(1)
                w.bytes_(out)
            except Exception as e:  # error crosses the wire, not the stack
                _log.info("service %s.%s failed: %s", self.name, method, e)
                w.u8(0)
                w.bytes_(str(e).encode())
            try:
                _send_frame(sock, w.out())
            except OSError:
                break
        self._conns.discard(sock)
        try:
            sock.close()
        except OSError:
            pass


class ServiceRemoteError(RuntimeError):
    pass


class ServiceConnectionError(ServiceRemoteError):
    """Transport-level loss (dial failed / connection dropped) as a TYPE:
    failover seams (storage switch handler, limiter fallback) key on this
    class, never on message text — a remote handler error whose text happens
    to mention connections must not trip a term switch."""


class ServiceClient:
    """Self-healing: a transport failure poisons only the CURRENT call —
    the broken socket is discarded and the next call redials, so a service
    restart (gateway/rpc/executor process bounce) heals without restarting
    every client process (tars proxies reconnect the same way)."""

    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self._addr = (host, port)
        self._timeout = timeout
        # LAZY dial: the first call connects. Constructing a client of a
        # not-yet-/currently-down service must not crash the mounting
        # process — every caller with a failover path (gateway limiter,
        # storage switch seam) depends on construction always succeeding.
        self.sock: socket.socket | None = None
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    def _drop_sock(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None

    def call(self, method: str, payload: bytes = b"") -> bytes:
        with self._lock:
            if self.sock is None:
                try:
                    self.sock = socket.create_connection(
                        self._addr, timeout=self._timeout
                    )
                except OSError as e:
                    raise ServiceConnectionError(f"{method}: reconnect failed: {e}")
            req_id = next(self._ids)
            w = FlatWriter()
            w.u64(req_id)
            w.str_(method)
            w.bytes_(payload)
            try:
                _send_frame(self.sock, w.out())
                body = _recv_frame(self.sock)
            except OSError:
                body = None
            if body is None:
                self._drop_sock()
        if body is None:
            raise ServiceConnectionError(f"{method}: connection lost")
        r = FlatReader(body)
        got_id = r.u64()
        ok = r.u8()
        out = r.bytes_()
        r.done()
        if got_id != req_id:
            raise ServiceRemoteError(f"{method}: response id mismatch")
        if not ok:
            raise ServiceRemoteError(f"{method}: {out.decode(errors='replace')}")
        return out

    def close(self) -> None:
        with self._lock:
            self._drop_sock()
