"""Executor service — the execution engine as its own process.

Reference: fisco-bcos-tars-service/ExecutorService (the Pro/Max remote
executor behind tars RPC; discovered/driven by TarsRemoteExecutorManager).
`ExecutorService` wraps a real TransactionExecutor behind service/rpc.py;
`RemoteExecutor` is a drop-in for the scheduler's executor seam —
next_block_header / execute_transactions / dag_execute_transactions /
get_hash / call / 2PC all cross the wire as flat-coded protocol objects.

The same servant carries the DMC cross-shard protocol — the reference's
multi-machine DMC ("DMC的多机拓展"): `dmc_execute` moves ExecutionMessage
batches (bcos-scheduler/src/DmcExecutor.cpp:239 dmcExecuteTransactions over
Tars), and `RemoteShard` is a drop-in for the DMCScheduler's shard seam, so
cross-contract calls pause, migrate BETWEEN PROCESSES, and resume — with the
scheduler-side lock graph and deadlock revert unchanged (key-lock claims
ride the messages).
"""

from __future__ import annotations

import threading

from ..codec.flat import FlatReader, FlatWriter
from ..protocol.block_header import BlockHeader
from ..protocol.receipt import TransactionReceipt
from ..protocol.transaction import Transaction
from ..scheduler.dmc import ExecutorShard, decode_messages, encode_messages
from ..storage.entry import Entry
from ..storage.interfaces import StorageInterface, TwoPCParams
from ..utils.log import note_swallowed
from .rpc import ServiceClient, ServiceServer


def _encode_txs(txs: list[Transaction]) -> bytes:
    w = FlatWriter()
    w.seq(txs, lambda w2, t: w2.bytes_(t.encode()))
    return w.out()


def _decode_receipts(buf: bytes) -> list[TransactionReceipt]:
    r = FlatReader(buf)
    out = [TransactionReceipt.decode(b) for b in r.seq(lambda r2: r2.bytes_())]
    r.done()
    return out


class ExecutorService:
    def __init__(
        self, executor, host: str = "127.0.0.1", port: int = 0, name: str = "executor0"
    ):
        self.executor = executor
        self._name = name
        self.shard = ExecutorShard(executor, name)
        self.server = ServiceServer("executor", host, port)
        s = self.server
        s.register("next_block_header", self._next_block_header)
        s.register("execute_transactions", self._execute)
        s.register("dag_execute_transactions", self._dag_execute)
        s.register("get_hash", self._get_hash)
        s.register("call", self._call)
        s.register("get_code", self._get_code)
        s.register("get_abi", self._get_abi)
        s.register("known_callee", self._known_callee)
        s.register("prepare", self._prepare)
        s.register("commit", self._commit)
        s.register("rollback", self._rollback)
        # DMC cross-process protocol (DmcExecutor.cpp over the wire)
        s.register("dmc_execute", self._dmc_execute)
        s.register("dmc_cancel", self._dmc_cancel)
        s.register("dmc_commit_ctx", self._dmc_commit_ctx)
        s.register("dmc_set_ownership", self._dmc_set_ownership)
        s.register("ctx_floor", self._ctx_floor)
        s.register("align", self._align)
        s.register("get_storage", self._get_storage)
        # lifecycle tracing: hand this process's ring spans to the node
        # core's /trace/tx stitcher (critical_path.SPAN_SOURCES)
        s.register("trace_spans", self._trace_spans)
        self.host, self.port = s.host, s.port

    def start(self) -> None:
        self.server.start()

    def register_with(
        self, registry_host: str, registry_port: int, interval: float = 1.0
    ) -> None:
        """Join a Max-topology executor fleet: register with the scheduler's
        registry servant, then heartbeat with this process's status seq
        (TarsRemoteExecutorManager's endpoint+seq discovery, push-based: the
        tars name service is replaced by direct registration).  A heartbeat
        answered with "unknown" re-registers — the registry restarted."""
        import time as _time

        self._seq = getattr(self, "_seq", int(_time.time() * 1000) % (1 << 31))
        self._hb_stop = threading.Event()
        client = ServiceClient(registry_host, registry_port, timeout=5.0)

        def _register() -> None:
            w = FlatWriter()
            w.str_(self._name)
            w.str_(self.host)
            w.i64(self.port)
            w.i64(self._seq)
            client.call("register", w.out())

        def _loop() -> None:
            try:
                _register()
            except Exception as e:
                # registry may come up after us; heartbeat loop re-registers
                note_swallowed("executor_service.register", e)
            while not self._hb_stop.wait(interval):
                try:
                    w = FlatWriter()
                    w.str_(self._name)
                    w.i64(self._seq)
                    resp = client.call("heartbeat", w.out())
                    r = FlatReader(resp)
                    if r.u32() != 0:  # registry lost us: re-register
                        _register()
                except Exception as e:
                    # registry down/restarting; keep trying
                    note_swallowed("executor_service.heartbeat", e)
                    continue

        self._hb_thread = threading.Thread(
            target=_loop, name=f"hb-{self._name}", daemon=True
        )
        self._hb_thread.start()

    def stop(self) -> None:
        hb = getattr(self, "_hb_stop", None)
        if hb is not None:
            hb.set()
        self.server.stop()

    # -- handlers -------------------------------------------------------------

    def _next_block_header(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        header = BlockHeader.decode(r.bytes_())
        gas_limit = r.u64()
        r.done()
        # a new block invalidates all DMC state from the previous one —
        # including a block ABANDONED mid-execution (executor-loss retry):
        # stale contexts must not merge writes into the dead block storage
        self.shard.reset()
        self.executor.next_block_header(header, gas_limit=gas_limit)
        return b""

    def _run_txs(self, payload: bytes, dag: bool) -> bytes:
        r = FlatReader(payload)
        txs = [Transaction.decode(b) for b in r.seq(lambda r2: r2.bytes_())]
        r.done()
        fn = (
            self.executor.dag_execute_transactions
            if dag
            else self.executor.execute_transactions
        )
        receipts = fn(txs)
        w = FlatWriter()
        w.seq(receipts, lambda w2, rc: w2.bytes_(rc.encode()))
        return w.out()

    def _execute(self, payload: bytes) -> bytes:
        return self._run_txs(payload, dag=False)

    def _dag_execute(self, payload: bytes) -> bytes:
        return self._run_txs(payload, dag=True)

    def _get_hash(self, payload: bytes) -> bytes:
        return self.executor.get_hash()

    def _call(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        tx = Transaction.decode(r.bytes_())
        r.done()
        return self.executor.call(tx).encode()

    def _get_code(self, payload: bytes) -> bytes:
        return self.executor.get_code(payload)

    def _known_callee(self, payload: bytes) -> bytes:
        w = FlatWriter()
        w.u32(1 if self.executor.known_callee(payload) else 0)
        return w.out()

    def _get_abi(self, payload: bytes) -> bytes:
        return self.executor.get_abi(payload)

    def _prepare(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        number = r.u64()
        rows = r.seq(
            lambda r2: (r2.str_(), r2.bytes_(), Entry.decode(r2.bytes_()))
        )
        r.done()
        extra = None
        if rows:
            from ..storage import MemoryStorage

            extra = MemoryStorage()
            for t, k, e in rows:
                extra.set_row(t, k, e)
        self.executor.prepare(TwoPCParams(number=number), extra_writes=extra)
        return b""

    def _commit(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        number = r.u64()
        r.done()
        self.executor.commit(TwoPCParams(number=number))
        return b""

    def _rollback(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        number = r.u64()
        r.done()
        self.executor.rollback(TwoPCParams(number=number))
        return b""

    # -- DMC handlers ---------------------------------------------------------

    def _dmc_execute(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        contract = r.bytes_()
        msgs = decode_messages(r.bytes_())
        r.done()
        return encode_messages(self.shard.execute(contract, msgs))

    def _dmc_cancel(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        ctx = r.u64()
        r.done()
        self.shard.cancel_context(ctx)
        return b""

    def _dmc_commit_ctx(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        ctx = r.u64()
        r.done()
        self.shard.commit_context(ctx)
        return b""

    def _dmc_set_ownership(self, payload: bytes) -> bytes:
        """Configure which contracts live on this shard: mode 'only' (own
        exactly these addresses) or 'except' (own everything else). The
        reference derives this from the scheduler's contract->executor
        registry (TarsRemoteExecutorManager); here the scheduler pushes it."""
        r = FlatReader(payload)
        mode = r.str_()
        addrs = set(r.seq(lambda r2: r2.bytes_()))
        r.done()
        if mode == "only":
            self.shard.owns = lambda c: c in addrs
        elif mode == "except":
            self.shard.owns = lambda c: c not in addrs
        else:
            raise ValueError(f"unknown ownership mode {mode!r}")
        return b""

    def _ctx_floor(self, payload: bytes) -> bytes:
        w = FlatWriter()
        w.u64(self.shard.ctx_floor())
        return w.out()

    def _align(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        upto = r.u64()
        r.done()
        self.shard.align(upto)
        return b""

    def _get_storage(self, payload: bytes) -> bytes:
        """Read one row from the current block state (ops/debug surface —
        the reference exposes the same via its storage service getRow)."""
        r = FlatReader(payload)
        table = r.str_()
        key = r.bytes_()
        r.done()
        block = self.executor._block
        store = block.storage if block is not None else self.executor.storage
        entry = store.get_row(table, key)
        w = FlatWriter()
        if entry is None:
            w.u8(0)
        else:
            w.u8(1)
            w.bytes_(entry.encode())
        return w.out()

    def _trace_spans(self, payload: bytes) -> bytes:
        import json

        from ..observability import critical_path

        req = json.loads(payload or b"{}")
        ids = {int(t, 16) for t in req.get("traceIds", ())}
        return json.dumps(
            critical_path.local_spans_for(ids, req.get("block")), default=str
        ).encode()


class RemoteExecutor:
    """The scheduler-facing executor seam, over the wire
    (TarsRemoteExecutorManager's client half)."""

    def __init__(self, host: str, port: int, timeout: float = 300.0):
        self.client = ServiceClient(host, port, timeout)

    def next_block_header(self, header: BlockHeader, gas_limit: int = 3_000_000_000) -> None:
        w = FlatWriter()
        w.bytes_(header.encode())
        w.u64(gas_limit)
        self.client.call("next_block_header", w.out())

    def execute_transactions(self, txs: list[Transaction]) -> list[TransactionReceipt]:
        return _decode_receipts(self.client.call("execute_transactions", _encode_txs(txs)))

    def dag_execute_transactions(self, txs: list[Transaction]) -> list[TransactionReceipt]:
        return _decode_receipts(
            self.client.call("dag_execute_transactions", _encode_txs(txs))
        )

    def get_hash(self) -> bytes:
        return self.client.call("get_hash")

    def trace_spans(self, trace_ids: set, block=None) -> list[dict]:
        """Fetch the executor process's ring spans for a stitched set —
        a critical_path.SPAN_SOURCES provider (node/node.py wires it)."""
        import json

        req = {"traceIds": [f"{t:032x}" for t in trace_ids], "block": block}
        return json.loads(self.client.call("trace_spans", json.dumps(req).encode()))

    def call(self, tx: Transaction) -> TransactionReceipt:
        w = FlatWriter()
        w.bytes_(tx.encode())
        return TransactionReceipt.decode(self.client.call("call", w.out()))

    def get_code(self, addr: bytes) -> bytes:
        return self.client.call("get_code", bytes(addr))

    def get_abi(self, addr: bytes) -> bytes:
        return self.client.call("get_abi", bytes(addr))

    def known_callee(self, addr: bytes) -> bool:
        r = FlatReader(self.client.call("known_callee", bytes(addr)))
        v = r.u32()
        r.done()
        return bool(v)

    def prepare(self, params: TwoPCParams, extra_writes: StorageInterface | None = None) -> None:
        w = FlatWriter()
        w.u64(params.number)
        rows = []
        if extra_writes is not None:
            rows = [(t, k, e) for t, k, e in extra_writes.traverse()]
        w.seq(
            rows,
            lambda w2, row: (
                w2.str_(row[0]),
                w2.bytes_(bytes(row[1])),
                w2.bytes_(row[2].encode()),
            ),
        )
        self.client.call("prepare", w.out())

    def commit(self, params: TwoPCParams) -> None:
        w = FlatWriter()
        w.u64(params.number)
        self.client.call("commit", w.out())

    def rollback(self, params: TwoPCParams) -> None:
        w = FlatWriter()
        w.u64(params.number)
        self.client.call("rollback", w.out())

    def close(self) -> None:
        self.client.close()


class RemoteShard:
    """DMCScheduler-facing shard seam over the wire: drop-in for
    scheduler.dmc.ExecutorShard, so the round loop pauses/migrates/resumes
    executives across OS processes (TarsRemoteExecutorManager +
    DmcExecutor::go over Tars). One RemoteShard per remote executor
    process; `set_ownership` pushes the contract->shard mapping down so the
    remote EVM knows which callees are local (inline sub-call) vs foreign
    (pause + migrate)."""

    def __init__(self, host: str, port: int, name: str, timeout: float = 300.0):
        self.name = name
        self.client = ServiceClient(host, port, timeout)

    def set_ownership(self, mode: str, addrs: list[bytes]) -> None:
        w = FlatWriter()
        w.str_(mode)
        w.seq(addrs, lambda w2, a: w2.bytes_(a))
        self.client.call("dmc_set_ownership", w.out())

    def execute(self, contract: bytes, msgs: list) -> list:
        w = FlatWriter()
        w.bytes_(contract)
        w.bytes_(encode_messages(msgs))
        return decode_messages(self.client.call("dmc_execute", w.out()))

    def cancel_context(self, ctx: int) -> None:
        w = FlatWriter()
        w.u64(ctx)
        self.client.call("dmc_cancel", w.out())

    def commit_context(self, ctx: int) -> None:
        w = FlatWriter()
        w.u64(ctx)
        self.client.call("dmc_commit_ctx", w.out())

    def ctx_floor(self) -> int:
        r = FlatReader(self.client.call("ctx_floor"))
        v = r.u64()
        r.done()
        return v

    def align(self, upto: int) -> None:
        w = FlatWriter()
        w.u64(upto)
        self.client.call("align", w.out())

    def get_storage(self, table: str, key: bytes):
        w = FlatWriter()
        w.str_(table)
        w.bytes_(key)
        resp = FlatReader(self.client.call("get_storage", w.out()))
        if not resp.u8():
            resp.done()
            return None
        raw = resp.bytes_()
        resp.done()
        return Entry.decode(raw)

    def close(self) -> None:
        self.client.close()
