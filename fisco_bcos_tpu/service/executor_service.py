"""Executor service — the execution engine as its own process.

Reference: fisco-bcos-tars-service/ExecutorService (the Pro/Max remote
executor behind tars RPC; discovered/driven by TarsRemoteExecutorManager).
`ExecutorService` wraps a real TransactionExecutor behind service/rpc.py;
`RemoteExecutor` is a drop-in for the scheduler's executor seam —
next_block_header / execute_transactions / dag_execute_transactions /
get_hash / call / 2PC all cross the wire as flat-coded protocol objects.

Scope note (documented deviation): DMC cross-shard *message migration*
stays in-process (scheduler/dmc.py); the service split covers the serial +
DAG execution path — the reference's multi-machine DMC rides the same
servant with ExecutionMessage IDLs.
"""

from __future__ import annotations

from ..codec.flat import FlatReader, FlatWriter
from ..protocol.block_header import BlockHeader
from ..protocol.receipt import TransactionReceipt
from ..protocol.transaction import Transaction
from ..storage.entry import Entry
from ..storage.interfaces import StorageInterface, TwoPCParams
from .rpc import ServiceClient, ServiceServer


def _encode_txs(txs: list[Transaction]) -> bytes:
    w = FlatWriter()
    w.seq(txs, lambda w2, t: w2.bytes_(t.encode()))
    return w.out()


def _decode_receipts(buf: bytes) -> list[TransactionReceipt]:
    r = FlatReader(buf)
    out = [TransactionReceipt.decode(b) for b in r.seq(lambda r2: r2.bytes_())]
    r.done()
    return out


class ExecutorService:
    def __init__(self, executor, host: str = "127.0.0.1", port: int = 0):
        self.executor = executor
        self.server = ServiceServer("executor", host, port)
        s = self.server
        s.register("next_block_header", self._next_block_header)
        s.register("execute_transactions", self._execute)
        s.register("dag_execute_transactions", self._dag_execute)
        s.register("get_hash", self._get_hash)
        s.register("call", self._call)
        s.register("get_code", self._get_code)
        s.register("get_abi", self._get_abi)
        s.register("prepare", self._prepare)
        s.register("commit", self._commit)
        s.register("rollback", self._rollback)
        self.host, self.port = s.host, s.port

    def start(self) -> None:
        self.server.start()

    def stop(self) -> None:
        self.server.stop()

    # -- handlers -------------------------------------------------------------

    def _next_block_header(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        header = BlockHeader.decode(r.bytes_())
        gas_limit = r.u64()
        r.done()
        self.executor.next_block_header(header, gas_limit=gas_limit)
        return b""

    def _run_txs(self, payload: bytes, dag: bool) -> bytes:
        r = FlatReader(payload)
        txs = [Transaction.decode(b) for b in r.seq(lambda r2: r2.bytes_())]
        r.done()
        fn = (
            self.executor.dag_execute_transactions
            if dag
            else self.executor.execute_transactions
        )
        receipts = fn(txs)
        w = FlatWriter()
        w.seq(receipts, lambda w2, rc: w2.bytes_(rc.encode()))
        return w.out()

    def _execute(self, payload: bytes) -> bytes:
        return self._run_txs(payload, dag=False)

    def _dag_execute(self, payload: bytes) -> bytes:
        return self._run_txs(payload, dag=True)

    def _get_hash(self, payload: bytes) -> bytes:
        return self.executor.get_hash()

    def _call(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        tx = Transaction.decode(r.bytes_())
        r.done()
        return self.executor.call(tx).encode()

    def _get_code(self, payload: bytes) -> bytes:
        return self.executor.get_code(payload)

    def _get_abi(self, payload: bytes) -> bytes:
        return self.executor.get_abi(payload)

    def _prepare(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        number = r.u64()
        rows = r.seq(
            lambda r2: (r2.str_(), r2.bytes_(), Entry.decode(r2.bytes_()))
        )
        r.done()
        extra = None
        if rows:
            from ..storage import MemoryStorage

            extra = MemoryStorage()
            for t, k, e in rows:
                extra.set_row(t, k, e)
        self.executor.prepare(TwoPCParams(number=number), extra_writes=extra)
        return b""

    def _commit(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        number = r.u64()
        r.done()
        self.executor.commit(TwoPCParams(number=number))
        return b""

    def _rollback(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        number = r.u64()
        r.done()
        self.executor.rollback(TwoPCParams(number=number))
        return b""


class RemoteExecutor:
    """The scheduler-facing executor seam, over the wire
    (TarsRemoteExecutorManager's client half)."""

    def __init__(self, host: str, port: int, timeout: float = 300.0):
        self.client = ServiceClient(host, port, timeout)

    def next_block_header(self, header: BlockHeader, gas_limit: int = 3_000_000_000) -> None:
        w = FlatWriter()
        w.bytes_(header.encode())
        w.u64(gas_limit)
        self.client.call("next_block_header", w.out())

    def execute_transactions(self, txs: list[Transaction]) -> list[TransactionReceipt]:
        return _decode_receipts(self.client.call("execute_transactions", _encode_txs(txs)))

    def dag_execute_transactions(self, txs: list[Transaction]) -> list[TransactionReceipt]:
        return _decode_receipts(
            self.client.call("dag_execute_transactions", _encode_txs(txs))
        )

    def get_hash(self) -> bytes:
        return self.client.call("get_hash")

    def call(self, tx: Transaction) -> TransactionReceipt:
        w = FlatWriter()
        w.bytes_(tx.encode())
        return TransactionReceipt.decode(self.client.call("call", w.out()))

    def get_code(self, addr: bytes) -> bytes:
        return self.client.call("get_code", bytes(addr))

    def get_abi(self, addr: bytes) -> bytes:
        return self.client.call("get_abi", bytes(addr))

    def prepare(self, params: TwoPCParams, extra_writes: StorageInterface | None = None) -> None:
        w = FlatWriter()
        w.u64(params.number)
        rows = []
        if extra_writes is not None:
            rows = [(t, k, e) for t, k, e in extra_writes.traverse()]
        w.seq(
            rows,
            lambda w2, row: (
                w2.str_(row[0]),
                w2.bytes_(bytes(row[1])),
                w2.bytes_(row[2].encode()),
            ),
        )
        self.client.call("prepare", w.out())

    def commit(self, params: TwoPCParams) -> None:
        w = FlatWriter()
        w.u64(params.number)
        self.client.call("commit", w.out())

    def rollback(self, params: TwoPCParams) -> None:
        w = FlatWriter()
        w.u64(params.number)
        self.client.call("rollback", w.out())

    def close(self) -> None:
        self.client.close()
