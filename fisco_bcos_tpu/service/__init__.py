"""Service RPC — the Pro/Max microservice split.

Reference: bcos-tars-protocol (service IDLs + async clients) and
fisco-bcos-tars-service (per-service servants): in Pro/Max deployments the
executor and storage run as separate OS processes behind service RPC.
Here the same seam is a length-framed flat-codec RPC over TCP
(service/rpc.py), with servers/clients for the executor
(ExecutorService/RemoteExecutor — ExecutorServiceServer.cpp analog) and
the storage backend (StorageService/RemoteStorage — StorageService
servant analog).
"""

from .executor_service import ExecutorService, RemoteExecutor, RemoteShard
from .gateway_service import FrontEndpoint, GatewayService, RemoteGateway
from .rpc_service import RemoteJsonRpc, RpcFacade, RpcService
from .rpc import ServiceClient, ServiceServer
from .storage_service import RemoteStorage, StorageService

__all__ = [
    "ExecutorService",
    "FrontEndpoint",
    "GatewayService",
    "RemoteExecutor",
    "RemoteGateway",
    "RemoteJsonRpc",
    "RemoteShard",
    "RemoteStorage",
    "RpcFacade",
    "RpcService",
    "ServiceClient",
    "ServiceServer",
    "StorageService",
]
