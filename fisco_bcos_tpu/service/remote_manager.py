"""Max-topology executor fleet: registry, heartbeats, failover.

Reference: bcos-scheduler/src/TarsRemoteExecutorManager.cpp (the Max
architecture's remote-executor discovery: a work loop polls the tars name
service for active ExecutorService endpoints and each executor's status
seq; any membership or seq change triggers executor-set rebuild and a
scheduler term switch via SchedulerManager::onExecutorSwitch) and
ExecutorManager.h:29-37 (contract -> executor dispatch).

The tars name service is replaced by a registry servant hosted INSIDE the
scheduler process: executor services call ``register`` once and
``heartbeat`` periodically over the same service RPC used for execution
traffic.  The manager reaps executors whose heartbeat goes stale and
notices seq changes (an executor that restarted lost its in-memory block
context even though its state lives in the shared storage service), both
of which bump ``term`` and invalidate in-flight blocks — the caller
re-executes against the surviving fleet, which works because Max
executors are STATELESS over shared distributed storage (TiKVStorage in
the reference; the storage service here).
"""

from __future__ import annotations

import threading
import time

from ..codec.flat import FlatReader, FlatWriter
from ..protocol.block_header import BlockHeader
from ..protocol.receipt import TransactionReceipt
from ..protocol.transaction import Transaction
from ..resilience import HEALTH, Deadline, RetryPolicy
from ..storage.interfaces import TwoPCParams
from ..utils.log import get_logger, note_swallowed
from .executor_service import RemoteExecutor, RemoteShard
from .rpc import (
    ServiceClient,
    ServiceConnectionError,
    ServiceRemoteError,
    ServiceServer,
)

_log = get_logger("remote-exec-manager")

# health-registry component for the whole fleet (GET /health)
_FLEET = "executor-fleet"

# one quick in-place retry for idempotent calls: a transient connection blip
# (GC pause, accept-queue hiccup) heals by redial without nuking the term;
# a genuinely dead executor still fails in <1s and falls through to
# mark_dead. Non-idempotent calls (execute/DMC) NEVER retry in place — the
# request may have half-applied, so the only safe recovery is the term
# switch + full re-execution the scheduler already drives.
_READ_RETRY = RetryPolicy(max_attempts=2, base_delay=0.05, max_delay=0.25)


def _guarded(manager: "RemoteExecutorManager", member: "_Member", fn, *args,
             idempotent: bool = False):
    """THE executor-RPC failure contract (replaces four copies of the same
    ad-hoc except block): classified retry for idempotent calls, then
    mark-dead + typed re-raise so the block driver re-executes against the
    survivors (SchedulerManager::asyncSwitchTerm analog)."""
    try:
        if idempotent:
            return _READ_RETRY.run(fn, *args, retry_on=(ServiceConnectionError,))
        return fn(*args)
    except (ServiceRemoteError, OSError) as e:
        manager.mark_dead(member.name)
        raise ServiceRemoteError(f"executor {member.name} failed: {e}") from e


class _Member:
    def __init__(self, name: str, host: str, port: int, seq: int, now: float):
        self.name = name
        self.host = host
        self.port = port
        self.seq = seq
        self.last_seen = now
        self.executor = RemoteExecutor(host, port)
        self.shard = RemoteShard(host, port, name)

    def close(self) -> None:
        self.executor.close()
        try:
            self.shard.client.close()
        except Exception as e:
            note_swallowed("remote_manager.shard_close", e)


class RemoteExecutorManager:
    """Registry + live executor set + contract dispatch (Max form).

    ``term`` increments on every membership/seq change; callers snapshot it
    before executing a block and re-execute when it moved (the
    SchedulerManager::asyncSwitchTerm analog for executor loss)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        heartbeat_timeout: float = 6.0,
    ):
        self.heartbeat_timeout = heartbeat_timeout
        self.term = 0
        self.on_change: list = []  # cb(term) after every fleet change
        self._members: dict[str, _Member] = {}
        self._lock = threading.RLock()
        self.server = ServiceServer("executor-registry", host, port)
        self.server.register("register", self._rpc_register)
        self.server.register("heartbeat", self._rpc_heartbeat)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self.server.start()
        # the reaper IS the failure detector: without it a hung executor
        # (heartbeats stop, socket stays open) would stall block RPCs for
        # the full client timeout instead of being dropped within
        # heartbeat_timeout (TarsRemoteExecutorManager's executeWorker loop)
        self._reap_stop = threading.Event()
        self._reap_thread = threading.Thread(
            target=self._reap_loop, name="executor-reaper", daemon=True
        )
        self._reap_thread.start()

    def _reap_loop(self) -> None:
        interval = max(0.2, self.heartbeat_timeout / 3.0)
        while not self._reap_stop.wait(interval):
            try:
                self.reap()
            except Exception:
                _log.exception("reaper pass failed")

    def stop(self) -> None:
        stop = getattr(self, "_reap_stop", None)
        if stop is not None:
            stop.set()
        self.server.stop()
        with self._lock:
            for m in self._members.values():
                m.close()
            self._members.clear()

    @property
    def host(self) -> str:
        return self.server.host

    @property
    def port(self) -> int:
        return self.server.port

    # -- registry servant ----------------------------------------------------

    def _rpc_register(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        name, host, port, seq = r.str_(), r.str_(), r.i64(), r.i64()
        r.done()
        self._admit(name, host, int(port), int(seq))
        return b""

    def _rpc_heartbeat(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        name, seq = r.str_(), r.i64()
        r.done()
        changed = False
        with self._lock:
            m = self._members.get(name)
            if m is None:
                w = FlatWriter()
                w.u32(1)  # unknown: executor must re-register
                return w.out()
            if m.seq != seq:
                # restarted executor: its block context is gone
                _log.warning(
                    "executor %s seq %d -> %d (restart): term switch",
                    name, m.seq, seq,
                )
                m.seq = int(seq)
                m.last_seen = time.monotonic()
                changed = True
            else:
                m.last_seen = time.monotonic()
        if changed:
            self._bump()
        w = FlatWriter()
        w.u32(0)
        return w.out()

    def _admit(self, name: str, host: str, port: int, seq: int) -> None:
        with self._lock:
            old = self._members.pop(name, None)
            if old is not None:
                old.close()
            self._members[name] = _Member(name, host, port, seq, time.monotonic())
            _log.info(
                "executor %s registered at %s:%d seq=%d (%d live)",
                name, host, port, seq, len(self._members),
            )
        # a (re)joined executor ends the fleet's degraded episode
        HEALTH.ok(_FLEET, f"{name} joined")
        self._bump()

    # -- liveness ------------------------------------------------------------

    def reap(self) -> bool:
        """Drop members whose heartbeat went stale; True when the fleet
        changed (TarsRemoteExecutorManager::refresh's endpoint-set diff)."""
        now = time.monotonic()
        with self._lock:
            stale = [
                n for n, m in self._members.items()
                if now - m.last_seen > self.heartbeat_timeout
            ]
            for n in stale:
                _log.warning("executor %s heartbeat stale: dropping", n)
                self._members.pop(n).close()
            left = len(self._members)
        if stale:
            # with survivors the fleet keeps executing (critical=False —
            # reduced capacity, still serving); an EMPTY fleet cannot, and
            # /health must answer 503 until an executor registers
            HEALTH.degrade(
                _FLEET, f"heartbeat lost: {','.join(stale)} ({left} live)",
                critical=(left == 0),
            )
            self._bump()
        return bool(stale)

    def mark_dead(self, name: str) -> None:
        """Immediate removal after an observed RPC failure (faster than
        waiting out the heartbeat timeout)."""
        with self._lock:
            m = self._members.pop(name, None)
            if m is not None:
                _log.warning("executor %s marked dead after RPC failure", name)
                m.close()
            left = len(self._members)
        if m is not None:
            HEALTH.degrade(
                _FLEET, f"{name} failed an RPC ({left} live)",
                critical=(left == 0),
            )
            self._bump()

    def _bump(self) -> None:
        """Advance the term and notify listeners. Deliberately OUTSIDE the
        member lock: listeners take their own locks (scheduler term switch),
        and an executor-death callback racing a reaper must not ABBA."""
        with self._lock:
            self.term += 1
            term = self.term
        for cb in list(self.on_change):
            try:
                cb(term)
            except Exception:
                _log.exception("on_change callback failed")

    # -- dispatch ------------------------------------------------------------

    @property
    def size(self) -> int:
        with self._lock:
            return len(self._members)

    def members(self) -> list[_Member]:
        with self._lock:
            return sorted(self._members.values(), key=lambda m: m.name)

    def shard_of(self, contract: bytes) -> RemoteShard:
        return self._member_of(contract).shard

    def _member_of(self, contract: bytes) -> _Member:
        live = self.members()
        if not live:
            raise RuntimeError("no live executors")
        idx = int.from_bytes(contract[-4:] or b"\x00", "big") % len(live)
        return live[idx]

    def wait_for_executors(self, n: int = 1, timeout: float = 30.0) -> None:
        """Block until at least n executors registered
        (TarsRemoteExecutorManager::waitForExecutorConnection)."""
        deadline = Deadline.after(timeout)
        while self.size < n:
            if deadline.expired():
                raise RuntimeError(
                    f"only {self.size}/{n} executors connected after {timeout}s"
                )
            time.sleep(0.05)


class _ShardGuard:
    """Forwards shard calls to a member's RemoteShard; an RPC failure marks
    the member dead on the manager before re-raising."""

    def __init__(self, member: _Member, manager: RemoteExecutorManager):
        self._member = member
        self._manager = manager

    @property
    def name(self) -> str:
        return self._member.shard.name

    def __getattr__(self, item):
        attr = getattr(self._member.shard, item)
        if not callable(attr):
            return attr
        member, manager = self._member, self._manager

        def wrapped(*a, **kw):
            # DMC traffic is never idempotent (messages move state between
            # shards) — fail fast into the term switch
            return _guarded(manager, member, lambda: attr(*a, **kw))

        return wrapped


class CompositeRemoteExecutor:
    """The scheduler's single-executor seam over a fleet of remote
    executors (Max form): contract-partitioned dispatch, DMC for serial
    batches (cross-contract calls migrate between executor processes),
    XOR-combined state roots, fanned-out 2PC.

    Any RPC failure marks the executor dead on the manager (term bump) and
    re-raises — the block driver re-executes against the survivors, which
    is sound because executors share one storage service."""

    def __init__(self, manager: RemoteExecutorManager):
        self.manager = manager
        self._header: BlockHeader | None = None
        self._gas_limit = 3_000_000_000
        # one guard per member name: DMCScheduler dedups shards by identity
        # ({shard_of(tx.to) for tx in txs}), so shard_failfast must return
        # the SAME object for the same member across calls
        self._guards: dict[str, object] = {}

    # -- helpers -------------------------------------------------------------

    def _fanout(self, fn, *args, idempotent: bool = False):
        out = []
        for m in self.manager.members():
            out.append(
                (m, _guarded(self.manager, m, fn, m, *args, idempotent=idempotent))
            )
        return out

    def _on_member(self, m: _Member, fn, *args, idempotent: bool = False):
        return _guarded(self.manager, m, fn, *args, idempotent=idempotent)

    # -- executor surface ----------------------------------------------------

    def next_block_header(self, header: BlockHeader, gas_limit: int = 3_000_000_000) -> None:
        self._header = header
        self._gas_limit = gas_limit
        self._fanout(
            lambda m: m.executor.next_block_header(header, gas_limit),
            idempotent=True,  # re-opening the same header is a reset, not a mutation
        )

    def replay_block_header(self) -> None:
        """Re-open the current block on the (possibly changed) fleet after a
        term switch: new members never saw next_block_header."""
        if self._header is not None:
            self.next_block_header(self._header, self._gas_limit)

    def execute_transactions(self, txs: list[Transaction]) -> list[TransactionReceipt]:
        from ..scheduler.dmc import DMCScheduler

        sched = DMCScheduler(lambda c: self.shard_failfast(c))
        return sched.execute(txs)

    def shard_failfast(self, contract: bytes):
        m = self.manager._member_of(contract)
        guard = self._guards.get(m.name)
        if guard is None or guard._member is not m:  # member was replaced
            guard = _ShardGuard(m, self.manager)
            self._guards[m.name] = guard
        return guard

    def dag_execute_transactions(self, txs: list[Transaction]) -> list[TransactionReceipt]:
        """Partition the conflict-free batch by owning executor and run each
        partition in one RPC (BlockExecutive's DAG dispatch across the
        executor fleet)."""
        by_member: dict[str, tuple[_Member, list[int]]] = {}
        for i, tx in enumerate(txs):
            m = self.manager._member_of(tx.to)
            by_member.setdefault(m.name, (m, []))[1].append(i)
        receipts: list[TransactionReceipt | None] = [None] * len(txs)
        for m, idxs in by_member.values():
            rcs = self._on_member(
                m, m.executor.dag_execute_transactions, [txs[i] for i in idxs]
            )
            for i, rc in zip(idxs, rcs):
                receipts[i] = rc
        return receipts  # type: ignore[return-value]

    def get_hash(self) -> bytes:
        """XOR of per-executor dirty-set roots — ownership partitions are
        disjoint, so the combined root is order-independent (the same
        combiner the single-process state root uses across shards)."""
        roots = self._fanout(lambda m: m.executor.get_hash(), idempotent=True)
        out = bytes(32)
        for _m, r in roots:
            out = bytes(a ^ b for a, b in zip(out, r))
        return out

    def get_hash_async(self):
        out = self.get_hash()
        return lambda: out

    def call(self, tx: Transaction) -> TransactionReceipt:
        m = self.manager._member_of(tx.to)
        return self._on_member(m, m.executor.call, tx, idempotent=True)

    def get_code(self, addr: bytes) -> bytes:
        m = self.manager._member_of(addr)
        return self._on_member(m, m.executor.get_code, addr, idempotent=True)

    def get_abi(self, addr: bytes) -> bytes:
        m = self.manager._member_of(addr)
        return self._on_member(m, m.executor.get_abi, addr, idempotent=True)

    def known_callee(self, addr: bytes, storage=None) -> bool:
        """The owner executor answers (registry precompiles, EVM builtins,
        deployed code) — same admission semantics as the in-process form."""
        m = self.manager._member_of(addr)
        return self._on_member(m, m.executor.known_callee, addr, idempotent=True)

    # -- 2PC -----------------------------------------------------------------

    def prepare(self, params: TwoPCParams, extra_writes=None) -> None:
        # extra_writes (the ledger rows) go through ONE member only — the
        # executors share a single storage backend, and staging the same
        # rows from every member would double-write the 2PC slot
        first = True
        for m in self.manager.members():
            # 2PC verbs are idempotent by design (keyed on block number)
            _guarded(
                self.manager, m,
                m.executor.prepare, params, extra_writes if first else None,
                idempotent=True,
            )
            first = False

    def commit(self, params: TwoPCParams) -> None:
        self._fanout(lambda m: m.executor.commit(params), idempotent=True)

    def rollback(self, params: TwoPCParams) -> None:
        self._fanout(lambda m: m.executor.rollback(params), idempotent=True)
