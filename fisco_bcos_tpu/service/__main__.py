"""Service process entrypoints — the Pro-mode service binaries.

Reference: fisco-bcos-tars-service/{GatewayService/GatewayServiceApp,
RpcService/RpcServiceApp} — the gateway and RPC front door each run as
their own OS process, serving node cores over service RPC.

    python -m fisco_bcos_tpu.service gateway --node-id <hex> \
        [--service-port N] [--p2p-port N] [--peers h:p,...]
    python -m fisco_bcos_tpu.service rpc --facade h:p [--port N]
    python -m fisco_bcos_tpu.service storage [--db path.db] [--port N]
    python -m fisco_bcos_tpu.service executor --storage h:p [--port N]

Each prints one ``READY key=port ...`` line once listening (port 0 resolves
to a kernel-assigned port), then serves until SIGTERM/SIGINT.
"""

from __future__ import annotations

# these are pure-IO processes: pin jax to CPU before anything imports it,
# or the axon sitecustomize would route the import through the TPU tunnel
try:  # pragma: no cover - environment-dependent
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception as e:
    from ..utils.log import note_swallowed

    note_swallowed("service_main.jax_cpu_pin", e)

import argparse
import signal
import sys
import threading


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="fisco-bcos-tpu-service", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    g = sub.add_parser("gateway", help="P2P gateway process")
    g.add_argument("--node-id", required=True, help="node id (hex, 64 bytes)")
    g.add_argument("--service-port", type=int, default=0)
    g.add_argument("--p2p-port", type=int, default=0)
    g.add_argument("--peers", default="", help="comma-separated host:port dials")
    r = sub.add_parser("rpc", help="JSON-RPC front-door process")
    r.add_argument("--facade", required=True, help="node RpcFacade host:port")
    r.add_argument("--port", type=int, default=0)
    s = sub.add_parser("storage", help="storage backend process")
    s.add_argument("--db", default="", help="sqlite path; empty = in-memory")
    s.add_argument("--port", type=int, default=0)
    e = sub.add_parser("executor", help="transaction executor process")
    e.add_argument("--storage", required=True, help="storage service host:port")
    e.add_argument("--port", type=int, default=0)
    e.add_argument("--sm", action="store_true", help="SM crypto suite")
    e.add_argument("--name", default="executor")
    e.add_argument(
        "--registry", default="",
        help="Max form: executor-registry host:port to join (heartbeats)",
    )
    args = ap.parse_args(argv)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_a: stop.set())

    if args.cmd == "gateway":
        from ..gateway.tcp import TcpGateway
        from .gateway_service import GatewayService

        gw = TcpGateway(bytes.fromhex(args.node_id), port=args.p2p_port)
        svc = GatewayService(gw, port=args.service_port)
        svc.start()
        for hp in filter(None, args.peers.split(",")):
            host, port = hp.rsplit(":", 1)
            gw.connect_peer(host, int(port))
        print(f"READY service={svc.port} p2p={gw.port}", flush=True)
        stop.wait()
        svc.stop()
    elif args.cmd == "rpc":
        from .rpc_service import RpcService

        host, port = args.facade.rsplit(":", 1)
        svc = RpcService(host, int(port), port=args.port)
        svc.start()
        print(f"READY service={svc.port}", flush=True)
        stop.wait()
        svc.stop()
    elif args.cmd == "storage":
        from ..storage import MemoryStorage, SQLiteStorage
        from .storage_service import StorageService

        backend = SQLiteStorage(args.db) if args.db else MemoryStorage()
        svc = StorageService(backend, port=args.port)
        svc.start()
        print(f"READY service={svc.port}", flush=True)
        stop.wait()
        svc.stop()
    else:  # executor
        from ..crypto.suite import ecdsa_suite, sm_suite
        from ..executor import TransactionExecutor
        from .executor_service import ExecutorService
        from .storage_service import RemoteStorage

        host, port = args.storage.rsplit(":", 1)
        store = RemoteStorage(host, int(port))
        suite = sm_suite() if args.sm else ecdsa_suite()
        executor = TransactionExecutor(store, suite)
        svc = ExecutorService(executor, name=args.name, port=args.port)
        svc.start()
        if args.registry:
            rhost, rport = args.registry.rsplit(":", 1)
            svc.register_with(rhost, int(rport))
        print(f"READY service={svc.port}", flush=True)
        stop.wait()
        svc.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
