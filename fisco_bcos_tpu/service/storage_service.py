"""Storage service — the backend KV store as its own process.

Reference: the Pro/Max StorageService servant (fisco-bcos-tars-service) over
bcos-storage: other services reach durable state through service RPC.
`StorageService` exposes a TransactionalStorage over service/rpc.py;
`RemoteStorage` implements the same interface as a client, so a node (or a
remote executor) can mount a storage process exactly where it would mount
sqlite.
"""

from __future__ import annotations

from typing import Iterator

from ..codec.flat import FlatReader, FlatWriter
from ..resilience import RetryPolicy
from ..storage.entry import Entry
from ..utils.log import note_swallowed
from ..storage.interfaces import (
    TransactionalStorage,
    TraversableStorage,
    TwoPCParams,
)
from .rpc import ServiceClient, ServiceConnectionError, ServiceServer

# every storage verb is idempotent (blind puts + number-keyed 2PC), so a
# transient shard blip heals inside the call instead of surfacing as a term
# switch; a genuinely dead shard exhausts ~0.2s of backoff and still raises
_STORAGE_RETRY = dict(max_attempts=3, base_delay=0.05, max_delay=0.5)


class StorageService:
    def __init__(self, backend: TransactionalStorage, host: str = "127.0.0.1", port: int = 0):
        self.backend = backend
        self.server = ServiceServer("storage", host, port)
        s = self.server
        s.register("get_row", self._get_row)
        s.register("set_row", self._set_row)
        s.register("set_rows", self._set_rows)
        s.register("get_primary_keys", self._get_primary_keys)
        s.register("prepare", self._prepare)
        s.register("commit", self._commit)
        s.register("rollback", self._rollback)
        s.register("pending_2pc", self._pending_2pc)
        self.host, self.port = s.host, s.port

    def start(self) -> None:
        self.server.start()

    def stop(self) -> None:
        self.server.stop()

    # -- handlers -------------------------------------------------------------

    def _get_row(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        table, key = r.str_(), r.bytes_()
        r.done()
        e = self.backend.get_row(table, key)
        w = FlatWriter()
        w.u8(0 if e is None else 1)
        if e is not None:
            w.bytes_(e.encode())
        return w.out()

    def _set_row(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        table, key, data = r.str_(), r.bytes_(), r.bytes_()
        r.done()
        self.backend.set_row(table, key, Entry.decode(data))
        return b""

    def _set_rows(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        table = r.str_()
        items = r.seq(lambda r2: (r2.bytes_(), Entry.decode(r2.bytes_())))
        r.done()
        self.backend.set_rows(table, items)
        return b""

    def _get_primary_keys(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        table = r.str_()
        r.done()
        w = FlatWriter()
        w.seq(self.backend.get_primary_keys(table), lambda w2, k: w2.bytes_(k))
        return w.out()

    def _prepare(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        number = r.u64()
        rows = r.seq(
            lambda r2: (r2.str_(), r2.bytes_(), Entry.decode(r2.bytes_()))
        )
        r.done()

        class _View(TraversableStorage):
            def traverse(self) -> Iterator:
                yield from rows

        self.backend.prepare(TwoPCParams(number=number), _View())
        return b""

    def _commit(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        number = r.u64()
        r.done()
        self.backend.commit(TwoPCParams(number=number))
        return b""

    def _rollback(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        number = r.u64()
        r.done()
        self.backend.rollback(TwoPCParams(number=number))
        return b""

    def _pending_2pc(self, payload: bytes) -> bytes:
        # interface method (TransactionalStorage.pending_numbers): every
        # backend must answer truthfully or recovery skips its stuck slots
        nums = self.backend.pending_numbers()
        w = FlatWriter()
        w.seq(nums, lambda w2, n: w2.u64(n))
        return w.out()


class RemoteStorage(TransactionalStorage):
    """TransactionalStorage client over a StorageService.

    Failover seam (TiKVStorage.cpp:582 ``setSwitchHandler`` →
    libinitializer/Initializer.cpp:225-235 → SchedulerManager term switch):
    a transport-level loss fires ``switch_handler`` once per outage episode
    before the error propagates, so the scheduler can drop its in-flight
    term instead of wedging on half-committed state; the underlying
    ServiceClient redials on the next call, which ends the episode.
    """

    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self.client = ServiceClient(
            host,
            port,
            timeout,
            connect_timeout=min(3.0, timeout),
            retry=RetryPolicy(**_STORAGE_RETRY),
        )
        self.switch_handler = None  # callable() | None
        self.heal_handler = None  # callable() | None — outage-episode END
        self._outage = False

    def set_switch_handler(self, fn) -> None:
        self.switch_handler = fn

    def set_heal_handler(self, fn) -> None:
        """Fires once per outage episode, on the first successful call after
        the loss — the degraded→ok edge (tars reconnect's 'alive again')."""
        self.heal_handler = fn

    def _healed(self) -> None:
        if self._outage:
            self._outage = False
            handler = self.heal_handler
            if handler is not None:
                try:
                    handler()
                except Exception as e:
                    # reporting must never break the storage path
                    note_swallowed("storage_service.heal_handler", e)

    def _call(self, method: str, payload: bytes = b"") -> bytes:
        try:
            out = self.client.call(method, payload)
        except ServiceConnectionError:
            if not self._outage:
                self._outage = True
                handler = self.switch_handler
                if handler is not None:
                    try:
                        handler()
                    except Exception as e:
                        # the switch must never mask the storage error
                        note_swallowed("storage_service.switch_handler", e)
            raise
        except Exception:
            # a reply frame arrived — the transport healed, so the outage
            # episode is over even though the HANDLER failed; otherwise the
            # next real outage would be silently swallowed
            self._healed()
            raise
        self._healed()
        return out

    def get_row(self, table: str, key: bytes) -> Entry | None:
        w = FlatWriter()
        w.str_(table)
        w.bytes_(bytes(key))
        out = self._call("get_row", w.out())
        r = FlatReader(out)
        if not r.u8():
            r.done()
            return None
        e = Entry.decode(r.bytes_())
        r.done()
        return None if e.deleted else e

    def set_row(self, table: str, key: bytes, entry: Entry) -> None:
        w = FlatWriter()
        w.str_(table)
        w.bytes_(bytes(key))
        w.bytes_(entry.encode())
        self._call("set_row", w.out())

    def set_rows(self, table: str, items) -> None:
        w = FlatWriter()
        w.str_(table)
        w.seq(
            list(items),
            lambda w2, kv: (w2.bytes_(bytes(kv[0])), w2.bytes_(kv[1].encode())),
        )
        self._call("set_rows", w.out())

    def get_primary_keys(self, table: str) -> list[bytes]:
        w = FlatWriter()
        w.str_(table)
        out = self._call("get_primary_keys", w.out())
        r = FlatReader(out)
        keys = r.seq(lambda r2: r2.bytes_())
        r.done()
        return keys

    def prepare(self, params: TwoPCParams, writes: TraversableStorage) -> None:
        w = FlatWriter()
        w.u64(params.number)
        w.seq(
            [(t, k, e) for t, k, e in writes.traverse()],
            lambda w2, row: (
                w2.str_(row[0]),
                w2.bytes_(bytes(row[1])),
                w2.bytes_(row[2].encode()),
            ),
        )
        self._call("prepare", w.out())

    def commit(self, params: TwoPCParams) -> None:
        w = FlatWriter()
        w.u64(params.number)
        self._call("commit", w.out())

    def rollback(self, params: TwoPCParams) -> None:
        w = FlatWriter()
        w.u64(params.number)
        self._call("rollback", w.out())

    def pending_numbers(self) -> list[int]:
        r = FlatReader(self._call("pending_2pc"))
        nums = r.seq(lambda r2: r2.u64())
        r.done()
        return [int(n) for n in nums]

    def close(self) -> None:
        self.client.close()
