"""Gateway service — the P2P gateway as its own process.

Reference: fisco-bcos-tars-service/GatewayService (GatewayServiceServer.cpp)
paired with FrontService: in the Pro topology the gateway — TCP listener,
TLS, routing, broadcast relay — runs as one process per machine, and node
processes reach it over service RPC. Inbound P2P frames flow BACK to the
node over the same wire: the node hosts a `FrontEndpoint` server the
gateway calls into (the reference's FrontService is itself a Tars servant
the gateway invokes — FrontServiceClient in GatewayServiceApp).

    [node process]                         [gateway process]
    FrontService ── RemoteGateway ──RPC──▶ GatewayService ── TcpGateway ─▶ P2P
        ▲                                        │
        └────────── FrontEndpoint ◀──RPC─────────┘ (inbound frames)
"""

from __future__ import annotations

from ..codec.flat import FlatReader, FlatWriter
from ..front.front import FrontService, GatewayInterface
from ..utils.log import get_logger
from ..utils.worker import Worker
from .rpc import ServiceClient, ServiceServer

_log = get_logger("gateway-svc")


class FrontEndpoint:
    """Node-side server the gateway process delivers inbound frames to
    (the FrontService servant half).

    Delivery is acked IMMEDIATELY and dispatched on a worker thread: a
    module handler doing heavy work (a tx-sync push triggering a device
    signature batch) must not hold the gateway's synchronous delivery
    pipeline — one slow frame would queue every later frame, consensus
    messages included, behind it. FIFO order is preserved (one worker)."""

    def __init__(self, front: FrontService, host: str = "127.0.0.1", port: int = 0):
        self.front = front
        self.server = ServiceServer("front", host, port)
        self.server.register("on_receive", self._on_receive)
        self.host, self.port = self.server.host, self.server.port
        self._worker = Worker("front-endpoint")

    def start(self) -> None:
        self._worker.start()
        self.server.start()

    def stop(self) -> None:
        self.server.stop()
        self._worker.stop()

    def _on_receive(self, payload: bytes) -> bytes:
        from ..observability.tracer import TRACER

        r = FlatReader(payload)
        module_id = r.u32()
        src = r.bytes_()
        data = r.bytes_()
        r.done()
        # the RPC server attached the gateway frame's trace context around
        # this handler; re-attach it on the dispatch worker so the module
        # handler's spans stay in the sender's trace (the worker hand-off
        # would otherwise drop it — contextvars don't cross threads)
        ctx = TRACER.current_context()
        self._worker.post(
            lambda: self._dispatch(ctx, module_id, src, data)
        )
        return b""

    def _dispatch(self, ctx, module_id: int, src: bytes, data: bytes) -> None:
        from ..observability.tracer import TRACER

        with TRACER.attach(ctx):
            self.front.on_receive(module_id, src, data)


class _ForwardingFront:
    """Gateway-side stub standing in for the node's FrontService: relays
    every delivered frame to the registered node endpoints over RPC.

    Endpoints are keyed by (host, port): re-registration after a node
    restart replaces the old client instead of accumulating duplicates,
    and an endpoint whose delivery fails is dropped immediately — a dead
    endpoint must not stall the gateway's receive path until its timeout
    on every subsequent frame (the restarted node re-registers)."""

    def __init__(self, node_id: bytes):
        self.node_id = node_id
        self._clients: dict[tuple[str, int], ServiceClient] = {}

    def set_gateway(self, gw) -> None:  # FrontService duck-type
        pass

    def add_endpoint(self, host: str, port: int) -> None:
        old = self._clients.pop((host, port), None)
        if old is not None:
            old.close()
        self._clients[(host, port)] = ServiceClient(host, port, timeout=60.0)

    def on_receive(self, module_id: int, src: bytes, payload: bytes) -> None:
        w = FlatWriter()
        w.u32(module_id)
        w.bytes_(src)
        w.bytes_(payload)
        buf = w.out()
        for key, c in list(self._clients.items()):
            try:
                c.call("on_receive", buf)
            except Exception as e:
                _log.warning(
                    "front endpoint %s:%d dropped after failed delivery: %s",
                    key[0], key[1], e,
                )
                if self._clients.get(key) is c:
                    del self._clients[key]
                c.close()


class GatewayService:
    """Hosts a TcpGateway behind service RPC (GatewayServiceServer)."""

    def __init__(self, gateway, host: str = "127.0.0.1", port: int = 0):
        self.gateway = gateway
        self._front = _ForwardingFront(gateway.node_id)
        gateway.connect(self._front)
        self.server = ServiceServer("gateway", host, port)
        s = self.server
        s.register("send", self._send)
        s.register("broadcast", self._broadcast)
        s.register("peers", self._peers)
        s.register("connect_peer", self._connect_peer)
        s.register("register_front", self._register_front)
        self.host, self.port = s.host, s.port

    def start(self) -> None:
        self.gateway.start()
        self.server.start()

    def stop(self) -> None:
        self.server.stop()
        self.gateway.stop()

    # -- handlers -------------------------------------------------------------

    def _send(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        module_id = r.u32()
        src = r.bytes_()
        dst = r.bytes_()
        data = r.bytes_()
        r.done()
        self.gateway.send(module_id, src, dst, data)
        return b""

    def _broadcast(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        module_id = r.u32()
        src = r.bytes_()
        data = r.bytes_()
        r.done()
        self.gateway.broadcast(module_id, src, data)
        return b""

    def _peers(self, payload: bytes) -> bytes:
        w = FlatWriter()
        w.seq(self.gateway.peers(), lambda w2, p: w2.bytes_(p))
        return w.out()

    def _connect_peer(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        host = r.str_()
        port = r.u32()
        r.done()
        ok = self.gateway.connect_peer(host, port)
        w = FlatWriter()
        w.u8(1 if ok else 0)
        return w.out()

    def _register_front(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        host = r.str_()
        port = r.u32()
        r.done()
        self._front.add_endpoint(host, port)
        return b""


class RemoteGateway(GatewayInterface):
    """Node-side GatewayInterface over the wire (what FrontService sends
    through when the gateway lives in another process)."""

    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self.client = ServiceClient(host, port, timeout)

    # send/broadcast keep the GatewayInterface best-effort contract
    # (TcpGateway logs and drops; it never raises): consensus and sync
    # threads call these, and a gateway-process bounce must cost dropped
    # frames — which PBFT re-delivery tolerates — not dead node threads.
    # The self-healing ServiceClient redials on the next call.

    def send(
        self, module_id: int, src: bytes, dst: bytes, payload: bytes,
        group: str = "",
    ) -> None:
        w = FlatWriter()
        w.u32(module_id)
        w.bytes_(src)
        w.bytes_(dst)
        w.bytes_(payload)
        try:
            self.client.call("send", w.out())
        except Exception as e:
            _log.warning("gateway send dropped (%s)", e)

    def broadcast(
        self, module_id: int, src: bytes, payload: bytes, group: str = ""
    ) -> None:
        w = FlatWriter()
        w.u32(module_id)
        w.bytes_(src)
        w.bytes_(payload)
        try:
            self.client.call("broadcast", w.out())
        except Exception as e:
            _log.warning("gateway broadcast dropped (%s)", e)

    def peers(self) -> list[bytes]:
        r = FlatReader(self.client.call("peers"))
        out = r.seq(lambda r2: r2.bytes_())
        r.done()
        return out

    def connect_peer(self, host: str, port: int) -> bool:
        w = FlatWriter()
        w.str_(host)
        w.u32(port)
        r = FlatReader(self.client.call("connect_peer", w.out()))
        ok = bool(r.u8())
        r.done()
        return ok

    def register_front(self, host: str, port: int) -> None:
        """Tell the gateway process where this node's FrontEndpoint
        listens, so inbound frames flow back."""
        w = FlatWriter()
        w.str_(host)
        w.u32(port)
        self.client.call("register_front", w.out())

    def close(self) -> None:
        self.client.close()
