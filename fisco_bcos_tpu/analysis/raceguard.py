"""Sampling lockset race recorder — the dynamic half of "tpu-tsan".

The static guarded-state checker sees lexical ``with self._lock:`` blocks;
it cannot see a field guarded by a caller's lock three frames up, a guard
taken in another module, or a field that is *never* guarded because every
author assumed someone else held the lock. This module watches real field
traffic the way the PR 5 lock-factory patch watches real lock traffic:

- :meth:`RaceGuard.watch` instruments a class's ``__setattr__`` /
  ``__getattribute__`` so every read/write of the *watched fields* reports
  to the guard (everything else pays one set-membership test);
- each access records the per-thread **lockset** — by default the
  instrumented-lock chain the :mod:`.lockorder` recorder already tracks,
  so the two runtime tools share one notion of "what this thread holds";
- per (instance, field) the guard runs the classic Eraser state machine:
  *exclusive* while a single thread owns the field (construction,
  hand-off), *shared* once a second thread reads it, *shared-modified*
  once writes race in — in the modified states the candidate lockset is
  intersected on every access, and an **empty intersection means no
  single lock protected every access**: a data-race candidate, reported
  once per ``Class.field`` with the access site that emptied the set.

**Sampling**: ``sample_every=N`` records one access in N (plus every
write) — the recorder is meant to ride whole test suites, where field
reads are hot; lockset soundness degrades gracefully (a missed access can
only *miss* a race, never invent one... except via the also-classic
Eraser false positives: ad-hoc synchronization, write-once-publish.
Those get waivers in the watch-list, not silence).

Suite-wide use: ``FISCO_RACEGUARD=1`` makes ``tests/conftest.py`` call
:func:`install` (default **off** — the tier-1 timing budget), watching
:data:`DEFAULT_WATCHLIST` — the hot shared-state classes named by the
concurrency roadmap item. The interleave explorer builds its own private
:class:`RaceGuard` per schedule with ``access_hook`` as its preemption
point, so every watched access is also a forced context switch.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass

from .lockorder import _REAL_LOCK, RECORDER

# Eraser states
_EXCLUSIVE = "exclusive"
_SHARED = "shared"  # read by >1 thread, no second-thread write yet
_SHARED_MOD = "shared-modified"  # racing writes: lockset intersection live

_PKG_MARKER = f"fisco_bcos_tpu{os.sep}"


_TOOLING = ("raceguard.py", "interleave.py", "lockorder.py")


def _access_site() -> str:
    """repo-style file:line of the package frame performing the access
    (the race tooling's own frames are skipped, harness frames are not)."""
    import sys

    f = sys._getframe(3)
    while f is not None:
        fn = f.f_code.co_filename
        i = fn.rfind(_PKG_MARKER)
        if i >= 0 and not fn.endswith(_TOOLING):
            return fn[i:].replace(os.sep, "/") + f":{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


@dataclass
class _FieldState:
    __slots__ = ("state", "owner", "lockset", "writers")
    state: str
    owner: int  # owning thread ident while exclusive
    lockset: frozenset | None  # candidate lockset (None until shared)
    writers: int


@dataclass
class Race:
    """One confirmed lockset violation (reported once per Class.field)."""

    cls: str
    fld: str
    kind: str  # "write" | "read"
    site: str  # package file:line of the access that emptied the lockset
    threads: tuple[str, str]  # (example earlier thread, racing thread)

    def render(self) -> str:
        return (
            f"{self.cls}.{self.fld}: no common lock across threads "
            f"{self.threads[0]!r}/{self.threads[1]!r} — lockset emptied by a "
            f"{self.kind} at {self.site}"
        )


class RaceGuard:
    """Watches field traffic on instrumented classes and runs the lockset
    state machine. One process-wide instance (:data:`RACEGUARD`) for the
    suite-wide recorder; the interleave explorer builds private ones."""

    def __init__(self, lockset_fn=None, sample_every: int = 1,
                 thread_filter=None):
        self._mu = _REAL_LOCK()
        self.lockset_fn = lockset_fn or RECORDER.held_sites
        self.sample_every = max(1, int(sample_every))
        # None = record every thread; else a () -> bool gate (the explorer
        # restricts recording to its managed workers so unrelated daemon
        # threads from earlier tests cannot pollute a schedule)
        self.thread_filter = thread_filter
        # called (cls_name, field, is_write) AFTER recording, outside _mu —
        # the interleave explorer's preemption point
        self.access_hook = None
        # the interleave explorer pauses the suite-wide guard during its
        # runs: harness traffic rides cooperative locks the lockorder
        # recorder cannot see, so its locksets would read empty here
        self.paused = False
        self._patched: dict[type, tuple] = {}
        self._states: dict[tuple[int, str], _FieldState] = {}
        self._owner_names: dict[int, str] = {}
        self.races: dict[tuple[str, str], Race] = {}
        self._tick = 0  # sampling counter (racy on purpose: it IS a sampler)

    # -- instrumentation -------------------------------------------------------

    def watch(self, cls: type, fields) -> None:
        """Patch ``cls`` so reads/writes of ``fields`` report here.
        Idempotent per class (fields merge into the watched set).

        A ``"?name"`` field is a **write-once-publish waiver** (the classic
        Eraser false positive the module docstring promises a waiver for):
        lock-free *reads* of a field assigned once at construction are by
        design — e.g. the flight ring's GIL-atomic ``deque.append`` rides
        an attribute read — so only *writes* report; a post-publication
        reassignment from a second thread still flags."""
        fields = frozenset(fields)
        waived = frozenset(f[1:] for f in fields if f.startswith("?"))
        fields = frozenset(f for f in fields if not f.startswith("?")) | waived
        with self._mu:
            if cls in self._patched:
                orig_set, orig_get, fs, wv = self._patched[cls]
                self._patched[cls] = (orig_set, orig_get, fs | fields, wv | waived)
                return
            orig_set = cls.__setattr__
            orig_get = cls.__getattribute__
            self._patched[cls] = (orig_set, orig_get, fields, waived)
        guard = self

        def __setattr__(obj, name, value):
            entry = guard._patched.get(cls)
            if entry is not None and name in entry[2]:
                guard._on_access(obj, cls.__name__, name, True)
            orig_set(obj, name, value)

        def __getattribute__(obj, name):
            entry = guard._patched.get(cls)
            if entry is not None and name in entry[2] and name not in entry[3]:
                guard._on_access(obj, cls.__name__, name, False)
            return orig_get(obj, name)

        cls.__setattr__ = __setattr__
        cls.__getattribute__ = __getattribute__

    def unwatch_all(self) -> None:
        with self._mu:
            patched, self._patched = self._patched, {}
        for cls, (orig_set, orig_get, _fields, _waived) in patched.items():
            cls.__setattr__ = orig_set
            cls.__getattribute__ = orig_get

    # -- the lockset state machine --------------------------------------------

    def _on_access(self, obj, cls_name: str, fld: str, is_write: bool) -> None:
        if self.paused:
            return
        if self.thread_filter is not None and not self.thread_filter():
            return
        if not is_write and self.sample_every > 1:
            self._tick += 1
            if self._tick % self.sample_every:
                return
        tid = threading.get_ident()
        held = frozenset(self.lockset_fn())
        key = (id(obj), fld)
        race: Race | None = None
        with self._mu:
            self._owner_names.setdefault(tid, threading.current_thread().name)
            st = self._states.get(key)
            if st is None:
                self._states[key] = _FieldState(
                    _EXCLUSIVE, tid, None, 1 if is_write else 0
                )
            elif st.state == _EXCLUSIVE:
                if tid == st.owner:
                    st.writers += 1 if is_write else 0
                else:
                    # second thread: the hand-off point — candidate lockset
                    # starts at THIS access's locks (first-thread accesses
                    # were construction)
                    st.state = _SHARED_MOD if (is_write or st.writers) else _SHARED
                    st.lockset = held
                    if is_write:
                        st.writers += 1
                    race = self._check_locked(st, cls_name, fld, is_write, tid)
            else:
                if is_write:
                    st.state = _SHARED_MOD
                    st.writers += 1
                st.lockset = (
                    held if st.lockset is None else st.lockset & held
                )
                race = self._check_locked(st, cls_name, fld, is_write, tid)
        hook = self.access_hook
        if hook is not None:
            hook(cls_name, fld, is_write)
        if race is not None:
            self._note_race(race)

    def _check_locked(self, st, cls_name, fld, is_write, tid) -> Race | None:
        if st.state != _SHARED_MOD or st.lockset:
            return None
        if (cls_name, fld) in self.races:
            return None
        other = next(
            (n for t, n in self._owner_names.items() if t != tid), "?"
        )
        return Race(
            cls_name, fld, "write" if is_write else "read", _access_site(),
            (other, self._owner_names.get(tid, "?")),
        )

    def _note_race(self, race: Race) -> None:
        with self._mu:
            self.races.setdefault((race.cls, race.fld), race)

    # -- reporting -------------------------------------------------------------

    def report(self) -> list[str]:
        with self._mu:
            return [r.render() for _, r in sorted(self.races.items())]

    def reset(self) -> None:
        with self._mu:
            self._states.clear()
            self.races.clear()
            self._owner_names.clear()


RACEGUARD = RaceGuard()

# the hot shared-state classes from the concurrency roadmap item, with the
# fields whose guard discipline the recorder checks. Dict-valued fields
# report attr-level loads (the read before .setdefault/[]) — enough for the
# lockset intersection to see which lock was held at the touch.
DEFAULT_WATCHLIST: tuple[tuple[str, str, tuple[str, ...]], ...] = (
    ("fisco_bcos_tpu.device.plane", "DevicePlane",
     ("requests", "dispatches", "merged_requests", "items", "_busy",
      "_deficit", "_drr_rotor")),
    ("fisco_bcos_tpu.proofs.plane", "ProofPlane",
     ("requests", "hits", "misses", "builds_commit", "builds_lazy",
      "coalesced_builds")),
    ("fisco_bcos_tpu.txpool.quota", "AdmissionQuotas", ("_groups",)),
    ("fisco_bcos_tpu.scheduler.scheduler", "Scheduler",
     ("term", "_committing_thread")),
    ("fisco_bcos_tpu.utils.metrics", "MetricsRegistry",
     ("_counters", "_gauges", "_histograms")),
    # the pipeline observatory's always-on shared state (ISSUE 9): stage
    # stat maps and per-stage accumulators, hit concurrently by every
    # pipeline worker plus the watermark sampler thread
    ("fisco_bcos_tpu.observability.pipeline", "PipelineRecorder",
     ("_stages", "_probes", "_marks")),
    ("fisco_bcos_tpu.observability.pipeline", "StageStats",
     ("busy_ms", "intervals", "blocked_intervals", "n_busy", "n_blocked",
      "state")),
    # the fleet observatory's shared state (ISSUE 16): the round ledger is
    # written by the engine worker + transport threads and snapshotted by
    # the federation aggregator; the flight ring is append-only from every
    # subsystem and drained by crash-flush
    ("fisco_bcos_tpu.observability.roundlog", "RoundLedger",
     ("_rounds", "_view_changes")),
    # "?": lock-free ring reads are the design (GIL-atomic deque.append);
    # only a post-publication reassignment of the ring itself may flag
    ("fisco_bcos_tpu.observability.flight", "FlightRecorder", ("?_ring",)),
    # the storage observatory's shared state (ISSUE 19): codec/copy cells
    # are bumped from every executor/cache/commit thread and the open
    # commit window is handed between the scheduler's 2PC phases, all
    # under the recorder lock; "?" on the dict-valued cells because the
    # registry's pull-gauges read them lock-free at scrape by design
    # (GIL-atomic dict.get of a published dict) — reassignment still flags
    ("fisco_bcos_tpu.observability.storagelog", "StorageRecorder",
     ("?_codec", "?_copies", "_pages", "_blocks", "_cur", "_shard_ms",
      "_shard_totals")),
)

_installed = False


def install(watchlist=None, sample_every: int | None = None) -> None:
    """Watch the default hot-class list on the process-wide guard.
    Idempotent. ``FISCO_RACEGUARD_SAMPLE`` tunes the read-sampling rate."""
    global _installed
    if _installed:
        return
    _installed = True
    if sample_every is None:
        try:
            sample_every = int(os.environ.get("FISCO_RACEGUARD_SAMPLE", "1"))
        except ValueError:
            sample_every = 1
    RACEGUARD.sample_every = max(1, sample_every)
    import importlib

    for mod_name, cls_name, fields in (watchlist or DEFAULT_WATCHLIST):
        mod = importlib.import_module(mod_name)
        RACEGUARD.watch(getattr(mod, cls_name), fields)


def uninstall() -> None:
    global _installed
    RACEGUARD.unwatch_all()
    _installed = False
