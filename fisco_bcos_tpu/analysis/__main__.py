"""CLI: ``python -m fisco_bcos_tpu.analysis [--format=json|text] ...``.

Exit codes: 0 = clean (no non-baselined findings, no stale baseline
entries), 1 = new findings or stale baseline entries — the same contract
the tier-1 test and the ``bench.py --telemetry`` gate enforce — 2 = usage
error. ``--update-baseline`` rewrites the baseline to the current finding
set (review the diff before committing it — the baseline is accepted
debt, not a mute button).
"""

from __future__ import annotations

import argparse
import json
import sys

from . import (
    DEFAULT_BASELINE,
    diff_findings,
    load_baseline,
    run_all,
    save_baseline,
)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m fisco_bcos_tpu.analysis",
        description="project-native invariant analyzers (see "
        "docs/static_analysis.md)",
    )
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--root", default=None, help="package dir to analyze")
    p.add_argument("--baseline", default=DEFAULT_BASELINE)
    p.add_argument(
        "--checker", action="append", metavar="NAME",
        help="run only this checker (repeatable / comma-separated); the "
        "baseline diff is scoped to the selected checkers' keys",
    )
    p.add_argument(
        "--list", action="store_true",
        help="print the registered checkers with one-line descriptions",
    )
    p.add_argument(
        "--list-jit", action="store_true",
        help="print the jit-program inventory (what tool/warm_cache.py "
        "pre-compiles) and exit",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding, ignoring accepted debt",
    )
    p.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline file to the current finding set",
    )
    args = p.parse_args(argv)

    if args.list_jit:
        from . import jitmap

        progs = jitmap.inventory(args.root)
        if args.format == "json":
            print(json.dumps(progs, indent=2))
        else:
            for p_ in progs:
                names = ", ".join(p_["names"])
                print(f"{p_['file']}:{p_['line']}  {p_['qualname']}  [{names}]")
            print(f"{len(progs)} jitted program(s)")
        return 0

    from .checkers import ALL_CHECKERS, checker_by_name

    if args.list:
        width = max(len(c.name) for c in ALL_CHECKERS)
        for c in ALL_CHECKERS:
            desc = getattr(c, "description", "") or "(no description)"
            print(f"{c.name:<{width}}  {desc}")
        return 0

    selected = None
    if args.checker:
        names = [n for arg in args.checker for n in arg.split(",") if n]
        if not names:
            # an empty selection must not run ALL checkers against a
            # baseline scoped to NONE (every accepted debt would read new)
            print("--checker given but no checker names resolved")
            return 2
        selected = []
        for n in names:
            cls = checker_by_name(n)
            if cls is None:
                known = ", ".join(c.name for c in ALL_CHECKERS)
                print(f"unknown checker {n!r} (known: {known})")
                return 2
            selected.append(cls)
        if args.update_baseline:
            print("--update-baseline requires the full checker set "
                  "(a filtered run would drop every other checker's debt)")
            return 2

    findings = run_all(args.root, checkers=selected)
    if args.update_baseline:
        old_notes = load_baseline(args.baseline)
        save_baseline(findings, args.baseline, notes=old_notes)
        print(
            f"baseline updated: {len(findings)} accepted findings -> "
            f"{args.baseline}"
        )
        return 0
    if args.no_baseline:
        new, stale = findings, []
    else:
        baseline = load_baseline(args.baseline)
        if selected is not None:
            # scope the diff to the selected checkers: every other
            # checker's accepted debt would otherwise read as stale
            chosen = {c.name for c in selected}
            baseline = {
                k: v for k, v in baseline.items()
                if k.split(":", 1)[0] in chosen
            }
        new, stale = diff_findings(findings, baseline)

    if args.format == "json":
        print(
            json.dumps(
                {
                    "new": [
                        {
                            "key": f.key,
                            "file": f.file,
                            "line": f.line,
                            "checker": f.checker,
                            "message": f.message,
                        }
                        for f in new
                    ],
                    "stale_baseline": stale,
                    "total_findings": len(findings),
                },
                indent=2,
            )
        )
    else:
        for f in new:
            print(f.render())
        for key in stale:
            print(f"stale baseline entry (debt paid? remove it): {key}")
        print(
            f"{len(new)} new finding(s), {len(findings) - len(new)} "
            f"baselined, {len(stale)} stale baseline entr(ies)"
        )
    return 1 if (new or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
