"""CLI: ``python -m fisco_bcos_tpu.analysis [--format=json|text] ...``.

Exit codes: 0 = clean (no non-baselined findings, no stale baseline
entries), 1 = new findings or stale baseline entries — the same contract
the tier-1 test and the ``bench.py --telemetry`` gate enforce — 2 = usage
error. ``--update-baseline`` rewrites the baseline to the current finding
set (review the diff before committing it — the baseline is accepted
debt, not a mute button).
"""

from __future__ import annotations

import argparse
import json
import sys

from . import (
    DEFAULT_BASELINE,
    diff_findings,
    load_baseline,
    run_all,
    save_baseline,
)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m fisco_bcos_tpu.analysis",
        description="project-native invariant analyzers (see "
        "docs/static_analysis.md)",
    )
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--root", default=None, help="package dir to analyze")
    p.add_argument("--baseline", default=DEFAULT_BASELINE)
    p.add_argument(
        "--checker", action="append", metavar="NAME",
        help="run only this checker (repeatable / comma-separated); the "
        "baseline diff is scoped to the selected checkers' keys",
    )
    p.add_argument(
        "--list", action="store_true",
        help="print the registered checkers with one-line descriptions",
    )
    p.add_argument(
        "--list-jit", action="store_true",
        help="print the jit-program inventory (what tool/warm_cache.py "
        "pre-compiles) and exit",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding, ignoring accepted debt",
    )
    p.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline file to the current finding set",
    )
    p.add_argument(
        "--jaxpr", action="store_true",
        help="abstract-eval the jit inventory and diff fingerprints/costs "
        "against tool/jaxpr_baseline.json (new/stale/changed/missing all "
        "fail; slow-marked programs verify by coverage only)",
    )
    p.add_argument(
        "--jaxpr-full", action="store_true",
        help="like --jaxpr but re-trace slow-marked programs too (the BLS "
        "pairing Miller loops — minutes-class)",
    )
    p.add_argument(
        "--jaxpr-programs", default=None, metavar="KEYS",
        help="comma-separated file:qualname (or bare qualname) subset to "
        "audit; coverage/stale checks still run against the full inventory",
    )
    p.add_argument(
        "--jaxpr-baseline", default=None,
        help="jaxpr baseline path (default tool/jaxpr_baseline.json)",
    )
    p.add_argument(
        "--update-jaxpr-baseline", action="store_true",
        help="re-audit the FULL inventory (slow programs included) and "
        "rewrite the jaxpr baseline — review the diff before committing",
    )
    p.add_argument(
        "--fusion-report", action="store_true",
        help="rank mergeable program pairs from the jaxpr baseline "
        "(+ measured dispatch adjacency via --adjacency)",
    )
    p.add_argument(
        "--adjacency", default=None, metavar="JSON",
        help="device artifact (GET /device or bench_telemetry.*.device."
        "json) whose 'adjacency' map weights the fusion report",
    )
    args = p.parse_args(argv)

    if args.update_jaxpr_baseline or args.jaxpr or args.jaxpr_full:
        return _jaxpr_main(args)
    if args.fusion_report:
        return _fusion_main(args)

    if args.list_jit:
        from . import jitmap

        progs = jitmap.inventory(args.root)
        if args.format == "json":
            print(json.dumps(progs, indent=2))
        else:
            for p_ in progs:
                names = ", ".join(p_["names"])
                print(f"{p_['file']}:{p_['line']}  {p_['qualname']}  [{names}]")
            print(f"{len(progs)} jitted program(s)")
        return 0

    from .checkers import ALL_CHECKERS, checker_by_name

    if args.list:
        width = max(len(c.name) for c in ALL_CHECKERS)
        for c in ALL_CHECKERS:
            desc = getattr(c, "description", "") or "(no description)"
            print(f"{c.name:<{width}}  {desc}")
        return 0

    selected = None
    if args.checker:
        names = [n for arg in args.checker for n in arg.split(",") if n]
        if not names:
            # an empty selection must not run ALL checkers against a
            # baseline scoped to NONE (every accepted debt would read new)
            print("--checker given but no checker names resolved")
            return 2
        selected = []
        for n in names:
            cls = checker_by_name(n)
            if cls is None:
                known = ", ".join(c.name for c in ALL_CHECKERS)
                print(f"unknown checker {n!r} (known: {known})")
                return 2
            selected.append(cls)
        if args.update_baseline:
            print("--update-baseline requires the full checker set "
                  "(a filtered run would drop every other checker's debt)")
            return 2

    findings = run_all(args.root, checkers=selected)
    if args.update_baseline:
        old_notes = load_baseline(args.baseline)
        save_baseline(findings, args.baseline, notes=old_notes)
        print(
            f"baseline updated: {len(findings)} accepted findings -> "
            f"{args.baseline}"
        )
        return 0
    if args.no_baseline:
        new, stale = findings, []
    else:
        baseline = load_baseline(args.baseline)
        if selected is not None:
            # scope the diff to the selected checkers: every other
            # checker's accepted debt would otherwise read as stale
            chosen = {c.name for c in selected}
            baseline = {
                k: v for k, v in baseline.items()
                if k.split(":", 1)[0] in chosen
            }
        new, stale = diff_findings(findings, baseline)

    if args.format == "json":
        print(
            json.dumps(
                {
                    "new": [
                        {
                            "key": f.key,
                            "file": f.file,
                            "line": f.line,
                            "checker": f.checker,
                            "message": f.message,
                        }
                        for f in new
                    ],
                    "stale_baseline": stale,
                    "total_findings": len(findings),
                },
                indent=2,
            )
        )
    else:
        for f in new:
            print(f.render())
        for key in stale:
            print(f"stale baseline entry (debt paid? remove it): {key}")
        print(
            f"{len(new)} new finding(s), {len(findings) - len(new)} "
            f"baselined, {len(stale)} stale baseline entr(ies)"
        )
    return 1 if (new or stale) else 0


def _jaxpr_main(args) -> int:
    """--jaxpr / --jaxpr-full / --update-jaxpr-baseline. Lazy progaudit
    import: these are the only CLI paths that load jax."""
    from . import progaudit

    if args.update_jaxpr_baseline:
        result = progaudit.audit(args.root, include_slow=True)
        if result["failures"] or result["missing_spec"]:
            for f in result["failures"]:
                print(f"audit failure: {f['key']}: {f['error']}")
            for k in result["missing_spec"]:
                print(f"no PROGSPEC entry for inventoried program: {k}")
            return 1
        progaudit.save_jaxpr_baseline(result, args.jaxpr_baseline)
        traced = sum(
            1 for e in result["programs"].values() if "skip" not in e
        )
        print(
            f"jaxpr baseline updated: {traced} program(s) fingerprinted, "
            f"{len(result['programs']) - traced} skipped with reasons"
        )
        return 0

    programs = None
    if args.jaxpr_programs:
        programs = [k for k in args.jaxpr_programs.split(",") if k]
    result = progaudit.audit(
        args.root, programs=programs, include_slow=args.jaxpr_full
    )
    baseline = progaudit.load_jaxpr_baseline(args.jaxpr_baseline)
    diff = progaudit.diff_audit(result, baseline)
    if args.format == "json":
        print(json.dumps(diff, indent=2))
    else:
        for key in diff["new"]:
            print(f"NEW program (baseline it): {key}")
        for key in diff["stale"]:
            print(f"stale baseline entry (program deleted?): {key}")
        for key in diff["missing"]:
            print(f"inventory program missing from baseline: {key}")
        for c in diff["changed"]:
            print(f"CHANGED {c['key']}: {c['explanation']}")
        for f in diff["failures"]:
            print(f"audit failure: {f['key']}: {f['error']}")
        for k in diff["missing_spec"]:
            print(f"no PROGSPEC entry for inventoried program: {k}")
        audited = sum(
            1 for e in result["programs"].values() if "skip" not in e
        )
        print(
            f"jaxpr audit: {audited} traced, "
            f"{len(result['not_traced'])} deferred "
            f"(slow/subset), {len(diff['changed'])} changed, "
            f"{len(diff['new'])} new, {len(diff['stale'])} stale, "
            f"{len(diff['missing'])} missing"
        )
    return 0 if diff["ok"] else 1


def _fusion_main(args) -> int:
    from . import progaudit

    baseline = progaudit.load_jaxpr_baseline(args.jaxpr_baseline)
    adjacency = None
    if args.adjacency:
        with open(args.adjacency, encoding="utf-8") as f:
            adjacency = json.load(f).get("adjacency") or None
    report = progaudit.fusion_report(baseline, adjacency)
    if args.format == "json":
        print(json.dumps(report, indent=2))
    else:
        chain = report["admission_chain"]
        print(
            "admission chain "
            + " -> ".join(chain["ops"])
            + f": ~{chain['predicted_saved_bytes']} B/round saved, "
            f"{chain['dispatches_collapsed']} dispatches collapsed"
        )
        for r in report["pairs"]:
            print(
                f"{r['producer']} -> {r['consumer']}  "
                f"[{r['source']}, x{r['count']}]  "
                f"~{r['saved_bytes_per_dispatch']} B/dispatch, "
                f"total ~{r['predicted_saved_bytes']} B"
            )
        if not report["pairs"]:
            print("no rankable pairs (is tool/jaxpr_baseline.json present?)")
    return 0 if report["pairs"] else 1


if __name__ == "__main__":
    sys.exit(main())
