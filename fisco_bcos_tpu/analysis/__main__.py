"""CLI: ``python -m fisco_bcos_tpu.analysis [--format=json|text] ...``.

Exit codes: 0 = clean (no non-baselined findings, no stale baseline
entries), 1 = new findings or stale baseline entries — the same contract
the tier-1 test and the ``bench.py --telemetry`` gate enforce — 2 = usage
error. ``--update-baseline`` rewrites the baseline to the current finding
set (review the diff before committing it — the baseline is accepted
debt, not a mute button).
"""

from __future__ import annotations

import argparse
import json
import sys

from . import (
    DEFAULT_BASELINE,
    diff_findings,
    load_baseline,
    run_all,
    save_baseline,
)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m fisco_bcos_tpu.analysis",
        description="project-native invariant analyzers (see "
        "docs/static_analysis.md)",
    )
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--root", default=None, help="package dir to analyze")
    p.add_argument("--baseline", default=DEFAULT_BASELINE)
    p.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding, ignoring accepted debt",
    )
    p.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline file to the current finding set",
    )
    args = p.parse_args(argv)

    findings = run_all(args.root)
    if args.update_baseline:
        old_notes = load_baseline(args.baseline)
        save_baseline(findings, args.baseline, notes=old_notes)
        print(
            f"baseline updated: {len(findings)} accepted findings -> "
            f"{args.baseline}"
        )
        return 0
    if args.no_baseline:
        new, stale = findings, []
    else:
        new, stale = diff_findings(findings, load_baseline(args.baseline))

    if args.format == "json":
        print(
            json.dumps(
                {
                    "new": [
                        {
                            "key": f.key,
                            "file": f.file,
                            "line": f.line,
                            "checker": f.checker,
                            "message": f.message,
                        }
                        for f in new
                    ],
                    "stale_baseline": stale,
                    "total_findings": len(findings),
                },
                indent=2,
            )
        )
    else:
        for f in new:
            print(f.render())
        for key in stale:
            print(f"stale baseline entry (debt paid? remove it): {key}")
        print(
            f"{len(new)} new finding(s), {len(findings) - len(new)} "
            f"baselined, {len(stale)} stale baseline entr(ies)"
        )
    return 1 if (new or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
