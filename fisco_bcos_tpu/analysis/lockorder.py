"""Runtime lock-order recorder — the dynamic complement to the static
``lock-order`` checker.

The static checker sees lexical nesting inside one module; it cannot see a
lock reached through a callback, a cross-module call chain, or a worker
thread. This module instruments ``threading.Lock``/``threading.RLock`` *at
the factory* so that every lock **created from package code** records, per
thread, the chain of lock sites held at each acquisition:

- each instrumented lock is identified by its **creation site**
  (``fisco_bcos_tpu/txpool/txpool.py:78``) — all instances born at one
  site are the same node in the order graph, exactly like the static
  checker's ``module:Class.attr`` ids;
- acquiring site B while holding site A adds the directed edge ``A -> B``;
- at session end :meth:`LockOrderRecorder.cycles` finds strongly-connected
  components in the edge graph — a cycle means two threads can take the
  same locks in different orders and deadlock under load;
- :func:`install_io_guards` additionally wraps the service-RPC frame IO so
  that blocking remote IO performed while holding any *foreign* lock (any
  instrumented lock not created in ``service/rpc.py`` itself — the client's
  pipeline lock is the baselined by-design exception) is recorded as a
  violation.

Locks created by stdlib / third-party code pass through untouched (the
factory checks the caller's file), so the instrumentation cost is confined
to package locks: one list append/pop per acquire/release plus one dict
update per *nested* acquire. ``threading.Condition`` objects the package
creates without an explicit lock allocate their RLock from inside
``threading.py`` and therefore stay uninstrumented; Conditions built over a
package lock (tx_sync's response cv) route through the wrapper's
``_release_save``/``_acquire_restore`` and keep the held-chain exact across
``wait()``.

Enabled for the whole test suite from ``tests/conftest.py``; production
processes never import this module.
"""

from __future__ import annotations

import os
import sys
import threading
from dataclasses import dataclass

from .core import tarjan_sccs

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

_PKG_MARKER = f"fisco_bcos_tpu{os.sep}"
_SELF_FILE = os.path.abspath(__file__)


def _site_of_caller(depth: int = 2) -> str | None:
    """Creation site (repo-style relpath:line) when the caller is package
    code outside the analysis subpackage, else None."""
    frame = sys._getframe(depth)
    fn = frame.f_code.co_filename
    i = fn.rfind(_PKG_MARKER)
    if i < 0 or f"{os.sep}analysis{os.sep}" in fn[i:]:
        return None
    return fn[i:].replace(os.sep, "/") + f":{frame.f_lineno}"


@dataclass(frozen=True)
class Waiver:
    """An ``allowed_blocking`` entry: the waived hold's reason, plus
    ``forbid`` substrings that re-narrow it — IO whose ``what`` contains
    any of them is a violation even under the waived lock. This lets a
    waiver say "this lock may be held across the execute-path RPC surface
    (broad, evolving) but never across 2PC verbs" without enumerating
    every allowed method."""

    reason: str
    forbid: tuple[str, ...] = ()


class LockOrderRecorder:
    """Per-thread acquisition chains, the global edge set, cycle detection
    and blocking-IO-under-lock violations."""

    def __init__(self):
        self._mu = _REAL_LOCK()  # guards edges/violations; always a leaf
        self._tls = threading.local()
        # (held site, acquired site) -> (example thread name, count)
        self.edges: dict[tuple[str, str], tuple[str, int]] = {}
        # (what, held sites, thread name)
        self.blocking_violations: list[tuple[str, tuple[str, ...], str]] = []
        # site prefixes allowed to be held across blocking IO — the runtime
        # analog of the static baseline; values are reason strings or
        # :class:`Waiver` (scoped by ``forbid``), documented in
        # docs/static_analysis.md
        self.allowed_blocking: dict[str, str | Waiver] = {}

    # -- per-thread chain -----------------------------------------------------

    def _held(self) -> list[str]:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    def on_acquire(self, site: str) -> None:
        held = self._held()
        if held and site not in held:
            # a reentrant re-acquire cannot block, so it orders nothing
            tname = threading.current_thread().name
            with self._mu:
                for h in held:
                    ex, n = self.edges.get((h, site), (tname, 0))
                    self.edges[(h, site)] = (ex, n + 1)
        held.append(site)

    def on_release(self, site: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == site:
                del held[i]
                return

    def on_release_all(self, site: str) -> None:
        """Condition.wait released every recursion level at once."""
        self._tls.held = [s for s in self._held() if s != site]

    def held_sites(self) -> tuple[str, ...]:
        return tuple(self._held())

    # -- blocking IO under a lock ---------------------------------------------

    def _waived(self, site: str, what: str) -> bool:
        for prefix, w in self.allowed_blocking.items():
            if site.startswith(prefix):
                # plain-string entries waive unconditionally (forbid=())
                if not any(f in what for f in getattr(w, "forbid", ())):
                    return True
        return False

    def note_blocking(self, what: str, exclude_file: str = "") -> None:
        """Record blocking IO performed while holding any instrumented lock
        whose creation site is NOT in ``exclude_file`` (the IO layer's own
        pipeline lock is by-design and baselined)."""
        held = [
            s
            for s in self._held()
            if not (exclude_file and s.startswith(exclude_file))
            and not self._waived(s, what)
        ]
        if held:
            with self._mu:
                self.blocking_violations.append(
                    (what, tuple(held), threading.current_thread().name)
                )

    # -- analysis -------------------------------------------------------------

    def cycles(self) -> list[list[str]]:
        """Strongly-connected components of size >= 2 in the order graph —
        each one a set of locks two threads can take in opposite orders."""
        with self._mu:
            graph: dict[str, set[str]] = {}
            for a, b in self.edges:
                graph.setdefault(a, set()).add(b)
                graph.setdefault(b, set())
        return [scc for scc in tarjan_sccs(graph) if len(scc) >= 2]

    def report(self) -> dict:
        with self._mu:
            edges = {
                f"{a} -> {b}": {"thread": t, "count": n}
                for (a, b), (t, n) in sorted(self.edges.items())
            }
            violations = [
                {"what": w, "held": list(h), "thread": t}
                for (w, h, t) in self.blocking_violations
            ]
        return {
            "edges": edges,
            "cycles": self.cycles(),
            "blocking_violations": violations,
        }


RECORDER = LockOrderRecorder()


# -- instrumented lock types --------------------------------------------------


class InstrumentedLock:
    """A ``threading.Lock`` that reports acquire/release to the recorder."""

    _factory = staticmethod(_REAL_LOCK)

    def __init__(self, site: str, recorder: LockOrderRecorder = RECORDER):
        self._inner = self._factory()
        self._site = site
        self._rec = recorder

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._rec.on_acquire(self._site)
        return got

    def release(self) -> None:
        self._inner.release()
        self._rec.on_release(self._site)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<{type(self).__name__} site={self._site} {self._inner!r}>"


class InstrumentedRLock(InstrumentedLock):
    """Reentrant variant; forwards the private Condition protocol so a
    Condition built over a package RLock keeps exact held-chains across
    ``wait()`` (tx_sync's response cv)."""

    _factory = staticmethod(_REAL_RLOCK)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def _release_save(self):
        state = self._inner._release_save()
        self._rec.on_release_all(self._site)
        return state

    def _acquire_restore(self, state) -> None:
        self._inner._acquire_restore(state)
        self._rec.on_acquire(self._site)


# -- installation -------------------------------------------------------------

_installed = False


def _lock_factory():
    site = _site_of_caller()
    if site is None:
        return _REAL_LOCK()
    return InstrumentedLock(site)


def _rlock_factory():
    site = _site_of_caller()
    if site is None:
        return _REAL_RLOCK()
    return InstrumentedRLock(site)


def install() -> None:
    """Patch the ``threading.Lock``/``RLock`` factories so locks created by
    package code from now on are instrumented. Idempotent."""
    global _installed
    if _installed:
        return
    _installed = True
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory


def uninstall() -> None:
    global _installed
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    _installed = False


_io_guarded = False


def install_io_guards() -> None:
    """Wrap service-RPC frame IO: any send/recv performed while holding an
    instrumented lock created outside ``service/rpc.py`` is a recorded
    blocking-IO-under-lock violation (the client's own pipeline lock is the
    baselined by-design hold)."""
    global _io_guarded
    if _io_guarded:
        return
    _io_guarded = True
    from ..service import rpc as _rpc

    real_send, real_recv = _rpc._send_frame, _rpc._recv_frame

    def send_frame(sock, body, scope=""):
        RECORDER.note_blocking(
            f"rpc.send_frame:{scope}", exclude_file="fisco_bcos_tpu/service/rpc.py"
        )
        return real_send(sock, body, scope)

    def recv_frame(sock, scope=""):
        RECORDER.note_blocking(
            f"rpc.recv_frame:{scope}", exclude_file="fisco_bcos_tpu/service/rpc.py"
        )
        return real_recv(sock, scope)

    _rpc._send_frame = send_frame
    _rpc._recv_frame = recv_frame
