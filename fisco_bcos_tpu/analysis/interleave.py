"""Seeded deterministic interleaving explorer — schedule fuzzing for the
package's concurrent hot paths ("tpu-tsan"'s second half).

A race the lockset recorder can describe still needs a *schedule* that
triggers it; the OS scheduler finds that schedule once a month, in
production, at 3am. This module takes scheduling away from the OS: one
worker runs at a time, and at every **preemption point** — cooperative
lock acquire/release, condition wait/notify, watched-field access — the
driver parks the running worker and picks who runs next from a seeded
RNG. The whole interleaving of a harness is then a pure function of the
seed: *same seed ⇒ bit-identical schedule digest*, every failing seed is
replayable, and a failing schedule can be **shrunk** to a minimal digest
by deleting preemptions that don't matter.

Mechanics
---------
- ``threading.Lock``/``RLock`` factories are patched for the duration of
  a run (composing with — and restoring — the lockorder factory patch):
  every lock created during harness setup/execution becomes cooperative.
  A managed worker acquires by try-acquire + park; the driver wakes
  blocked workers when the holder releases. Non-managed threads fall
  through to the real primitive untouched.
- ``Condition.wait``/``notify`` are patched the same way: a managed
  waiter parks until a notify bumps the condition's generation (modelled
  spurious wakeups stay legal); a *timed* waiter is additionally woken
  when nothing else can run — modelling timeout expiry deterministically
  instead of burning wall-clock.
- Watched-field preemption rides a private :class:`~.raceguard.RaceGuard`
  whose ``access_hook`` parks the worker — so the classic lost-update
  interleaving (both threads read, then both write) is *forced*, not
  hoped for.
- A schedule **fails** on: harness ``check()`` assertion, an uncaught
  worker exception, a deadlock (no runnable/wakeable worker), a step-
  budget blowout, or any lockset race the run's RaceGuard confirmed.
- **Determinism contract**: harness threads must not race *unmanaged*
  threads on cooperative state (harnesses stub background workers out),
  and must not branch on wall-clock deltas at preemption granularity.
  Labels use creation sites and per-run condition indexes, never ``id()``.

Deadlock note: a schedule that parks every worker (all blocked on locks
whose holders are blocked) is itself a *finding* — the explorer reports
it with every worker's last label instead of hanging the suite.
"""

from __future__ import annotations

import hashlib
import os
import random
import sys
import threading
from dataclasses import dataclass, field

from .lockorder import _REAL_LOCK, _REAL_RLOCK
from .raceguard import RaceGuard

_REAL_CV_WAIT = threading.Condition.wait
_REAL_CV_NOTIFY = threading.Condition.notify

_EXPLORE_MUTEX = _REAL_LOCK()  # one exploration at a time per process

_tls = threading.local()

# module-global active explorer (read by the cooperative primitives)
_ACTIVE: "Explorer | None" = None


class _Killed(BaseException):
    """Raised inside parked workers during teardown — BaseException so
    harness code's `except Exception` cannot swallow the unwind."""


def _ctx():
    exp = _ACTIVE
    if exp is None or exp._killing:
        return None, None
    w = getattr(_tls, "worker", None)
    if w is None or w.exp is not exp:
        return None, None
    return exp, w


def _site(depth: int = 2) -> str:
    f = sys._getframe(depth)
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


# -- cooperative primitives ---------------------------------------------------


class CoopLock:
    """Lock wrapper: cooperative for managed workers, transparent for
    everyone else (incl. after the exploration that created it ends)."""

    _factory = staticmethod(_REAL_LOCK)

    def __init__(self, site: str):
        self._inner = self._factory()
        self._site = site

    def acquire(self, blocking: bool = True, timeout: float = -1):
        exp, w = _ctx()
        if exp is None:
            return self._inner.acquire(blocking, timeout)
        if not blocking:
            got = self._inner.acquire(False)
            if got:
                w.held.append(self._site)
            return got
        # a timed blocking acquire parks like any other (the holder must be
        # schedulable to release); timeout expiry is modelled like timed
        # cv waits — woken as 'timeout' only when nothing else can run
        return exp._coop_acquire(w, self, timed=timeout >= 0)

    def release(self) -> None:
        self._inner.release()
        exp, w = _ctx()
        if exp is not None:
            exp._coop_released(w, self)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class CoopRLock(CoopLock):
    _factory = staticmethod(_REAL_RLOCK)

    def locked(self) -> bool:  # RLock has no .locked() pre-3.12
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    # Condition protocol (a Condition over this lock stays cooperative)
    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def _release_save(self):
        state = self._inner._release_save()
        exp, w = _ctx()
        if exp is not None:
            w.held = [s for s in w.held if s != self._site]
            exp._coop_released(w, self, pause=False)
        return state

    def _acquire_restore(self, state) -> None:
        exp, w = _ctx()
        if exp is None:
            self._inner._acquire_restore(state)
            return
        count = state[0] if isinstance(state, tuple) else 1
        while not self._inner.acquire(False):
            w.blocked_on = self
            exp._pause(w, f"blocked:{self._site}", "lockwait")
        w.blocked_on = None
        for _ in range(int(count) - 1):
            self._inner.acquire(False)
        w.held.append(self._site)


def _coop_wait(cv, timeout=None):
    exp, w = _ctx()
    if exp is None:
        return _REAL_CV_WAIT(cv, timeout)
    return exp._cv_wait(w, cv, timeout)


def _coop_notify(cv, n=1):
    exp, _w = _ctx()
    if exp is not None:
        exp._cv_notified(cv)
    return _REAL_CV_NOTIFY(cv, n)


# -- worker / outcome ---------------------------------------------------------


class _Gate:
    """Binary semaphore on a REAL lock (``threading.Event`` would be built
    from the patched cooperative Condition and recurse into the driver).
    ``wait`` consumes one ``set``; strictly paired by the drive protocol."""

    __slots__ = ("_lk",)

    def __init__(self):
        self._lk = _REAL_LOCK()
        self._lk.acquire()  # start closed

    def wait(self) -> None:
        self._lk.acquire()

    def set(self) -> None:
        try:
            self._lk.release()
        except RuntimeError:
            pass  # already open (teardown double-set)


class _Worker:
    __slots__ = ("name", "exp", "thread", "go", "parked", "state", "label",
                 "held", "blocked_on", "cv", "timed", "wake_reason", "error")

    def __init__(self, name: str, exp: "Explorer"):
        self.name = name
        self.exp = exp
        self.thread: threading.Thread | None = None
        self.go = _Gate()
        self.parked = _Gate()
        self.state = "ready"
        self.label = "start"
        self.held: list[str] = []
        self.blocked_on = None
        self.cv = None
        self.timed = False
        self.wake_reason = ""
        self.error: BaseException | None = None


@dataclass
class Outcome:
    """One explored schedule. ``digest`` is the stable identity of the
    interleaving (sha256 over the grant sequence); ``decisions`` replays
    it (`Explorer(replay=decisions)`)."""

    seed: int | None
    status: str  # ok | check | exception | deadlock | budget
    error: str = ""
    digest: str = ""
    steps: int = 0
    decisions: list = field(default_factory=list)
    trace: list = field(default_factory=list)
    races: list = field(default_factory=list)

    @property
    def failed(self) -> bool:
        return self.status != "ok" or bool(self.races)

    def summary(self) -> str:
        what = self.status + (f" ({self.error})" if self.error else "")
        if self.races:
            what += f" races={self.races}"
        return f"seed={self.seed} {what} steps={self.steps} digest={self.digest}"


# -- the explorer -------------------------------------------------------------


class Explorer:
    """Drives one harness through one schedule (seeded or replayed)."""

    def __init__(self, seed: int | None = None, replay: list | None = None,
                 max_steps: int = 50_000):
        if seed is None and replay is None:
            raise ValueError("need a seed or a replay decision list")
        self.seed = seed
        self._rng = random.Random(seed if seed is not None else 0)
        self._replay = list(replay) if replay is not None else None
        self.max_steps = int(max_steps)
        self._killing = False
        self.workers: list[_Worker] = []
        self.trace: list[tuple[str, str]] = []
        self.decisions: list[str] = []
        self._cv_gen: dict[int, int] = {}
        self._cv_ids: dict[int, int] = {}
        self._progress = False  # a lock was acquired since the last stall scan
        self._last = None  # previously granted worker (replay fallback)

    # -- worker-side hooks ----------------------------------------------------

    def _pause(self, w: _Worker, label: str, state: str) -> None:
        w.label = label
        w.state = state
        w.parked.set()
        w.go.wait()
        if self._killing:
            raise _Killed()

    def _coop_acquire(self, w: _Worker, lock: CoopLock,
                      timed: bool = False) -> bool:
        while True:
            self._pause(w, f"acquire:{lock._site}", "ready")
            if lock._inner.acquire(False):
                w.held.append(lock._site)
                self._progress = True
                return True
            w.blocked_on = lock
            w.timed = timed
            w.wake_reason = ""
            self._pause(w, f"blocked:{lock._site}", "lockwait")
            w.blocked_on = None
            w.timed = False
            if timed and w.wake_reason == "timeout":
                return False  # modelled expiry: nothing else could run

    def _coop_released(self, w: _Worker | None, lock: CoopLock,
                       pause: bool = True) -> None:
        if w is not None and lock._site in w.held:
            for i in range(len(w.held) - 1, -1, -1):
                if w.held[i] == lock._site:
                    del w.held[i]
                    break
        for other in self.workers:
            if other.state == "lockwait" and other.blocked_on is lock:
                other.state = "ready"
        if pause and w is not None:
            self._pause(w, f"release:{lock._site}", "ready")

    def _cv_label(self, cv) -> str:
        idx = self._cv_ids.setdefault(id(cv), len(self._cv_ids))
        return f"cv{idx}"

    def _cv_wait(self, w: _Worker, cv, timeout) -> bool:
        gen0 = self._cv_gen.get(id(cv), 0)
        label = self._cv_label(cv)
        state = cv._release_save()  # releasing the lock may itself pause
        try:
            # a notify may have landed during the release pause — parking
            # then would be a missed wakeup (nothing would re-ready us)
            if self._cv_gen.get(id(cv), 0) == gen0:
                w.cv = cv
                w.timed = timeout is not None
                w.wake_reason = ""
                self._pause(w, f"wait:{label}", "cvwait")
            notified = (
                self._cv_gen.get(id(cv), 0) != gen0
                or w.wake_reason == "notify"
            )
        finally:
            w.cv = None
            w.timed = False
            cv._acquire_restore(state)
        return notified

    def _cv_notified(self, cv) -> None:
        self._cv_gen[id(cv)] = self._cv_gen.get(id(cv), 0) + 1
        for other in self.workers:
            if other.state == "cvwait" and other.cv is cv:
                other.state = "ready"
                other.wake_reason = "notify"

    def _field_hook(self, cls_name: str, fld: str, is_write: bool) -> None:
        exp, w = _ctx()
        if exp is self and w is not None:
            kind = "w" if is_write else "r"
            self._pause(w, f"{kind}:{cls_name}.{fld}", "ready")

    # -- patching -------------------------------------------------------------

    def _install(self):
        saved = (
            threading.Lock, threading.RLock,
            threading.Condition.wait, threading.Condition.notify,
        )

        def lock_factory():
            return CoopLock(_site())

        def rlock_factory():
            return CoopRLock(_site())

        threading.Lock = lock_factory
        threading.RLock = rlock_factory
        threading.Condition.wait = _coop_wait
        threading.Condition.notify = _coop_notify
        return saved

    @staticmethod
    def _restore(saved) -> None:
        (threading.Lock, threading.RLock,
         threading.Condition.wait, threading.Condition.notify) = saved

    # -- the drive loop -------------------------------------------------------

    def run(self, harness) -> Outcome:
        global _ACTIVE
        with _EXPLORE_MUTEX:
            from ..observability.tracer import TRACER
            from ..utils.metrics import REGISTRY

            guard = RaceGuard(
                lockset_fn=lambda: tuple(getattr(_tls, "worker").held)
                if getattr(_tls, "worker", None) is not None else (),
                thread_filter=lambda: getattr(_tls, "worker", None) is not None,
            )
            guard.access_hook = self._field_hook
            saved_telemetry = (REGISTRY.enabled, TRACER.enabled)
            REGISTRY.enabled = TRACER.enabled = False
            from .raceguard import RACEGUARD

            saved_paused = RACEGUARD.paused
            RACEGUARD.paused = True  # suite guard can't see coop locksets
            saved = self._install()
            _ACTIVE = self
            try:
                for cls, fields in getattr(harness, "watch", ()):
                    guard.watch(cls, fields)
                ctx = harness.setup()
                outcome = self._drive(harness, ctx)
            finally:
                self._kill_stragglers()
                _ACTIVE = None
                self._restore(saved)
                guard.unwatch_all()
                RACEGUARD.paused = saved_paused
                REGISTRY.enabled, TRACER.enabled = saved_telemetry
            outcome.races = guard.report()
            if outcome.status == "ok" and outcome.races:
                outcome.error = "; ".join(outcome.races)
            if outcome.status == "ok" and outcome.error == "":
                try:
                    harness.check(ctx)
                except AssertionError as e:
                    outcome.status = "check"
                    outcome.error = str(e) or "harness check failed"
            outcome.digest = self._digest()
            outcome.decisions = self.decisions
            outcome.trace = self.trace
            return outcome

    def _drive(self, harness, ctx) -> Outcome:
        for name, fn in harness.threads(ctx):
            w = _Worker(name, self)
            w.thread = threading.Thread(
                target=self._worker_main, args=(w, fn),
                name=f"interleave-{name}", daemon=True,
            )
            self.workers.append(w)
        for w in self.workers:
            w.thread.start()
            w.parked.wait()  # workers park at 'start' before running
        steps = 0
        stall_retry = False
        while True:
            live = [w for w in self.workers if w.state != "done"]
            if not live:
                status, err = "ok", ""
                break
            ready = [w for w in live if w.state == "ready"]
            if not ready:
                timed = [
                    w for w in live
                    if w.state in ("cvwait", "lockwait") and w.timed
                ]
                if timed:
                    for w in timed:
                        w.state = "ready"
                        w.wake_reason = "timeout"
                    continue
                lockers = [w for w in live if w.state == "lockwait"]
                if lockers and not stall_retry:
                    # one deterministic re-probe round: with no unmanaged
                    # threads, lock states cannot change while everyone is
                    # parked — if nobody acquires, it is a real deadlock
                    stall_retry = True
                    self._progress = False
                    for w in lockers:
                        w.state = "ready"
                    continue
                status = "deadlock"
                err = "; ".join(
                    f"{w.name}@{w.label} holds {w.held}" for w in live
                )
                break
            if steps >= self.max_steps:
                status, err = "budget", f"exceeded {self.max_steps} steps"
                break
            w = self._choose(ready)
            if self._progress:
                stall_retry = False
            self.trace.append((w.name, w.label))
            self.decisions.append(w.name)
            steps += 1
            self._last = w
            w.state = "running"
            w.go.set()
            w.parked.wait()
        errors = [w for w in self.workers if w.error is not None]
        if errors and status == "ok":
            status = "exception"
            err = "; ".join(f"{w.name}: {w.error!r}" for w in errors)
        return Outcome(self.seed, status, error=err, steps=steps)

    def _choose(self, ready: list[_Worker]) -> _Worker:
        if self._replay is not None:
            if len(self.decisions) < len(self._replay):
                name = self._replay[len(self.decisions)]
                for w in ready:
                    if w.name == name:
                        return w
            # past (or off) the script: run-to-completion — stay on the
            # last-granted worker when possible, else first by position
            if self._last is not None and self._last in ready:
                return self._last
            return ready[0]
        if len(ready) == 1:
            return ready[0]
        return ready[self._rng.randrange(len(ready))]

    def _worker_main(self, w: _Worker, fn) -> None:
        _tls.worker = w
        try:
            self._pause(w, "start", "ready")
            fn()
        except _Killed:
            pass
        except BaseException as e:  # noqa: BLE001 — reported as the outcome
            w.error = e
        finally:
            _tls.worker = None
            w.state = "done"
            w.parked.set()

    def _kill_stragglers(self) -> None:
        self._killing = True
        for w in self.workers:
            if w.state != "done":
                w.go.set()
        for w in self.workers:
            if w.thread is not None:
                w.thread.join(timeout=5.0)

    def _digest(self) -> str:
        h = hashlib.sha256()
        for name, label in self.trace:
            h.update(f"{name}:{label}\n".encode())
        return h.hexdigest()[:16]


# -- exploration / shrinking helpers ------------------------------------------


def sweep(harness_factory, seeds, max_steps: int = 50_000):
    """Run each seed; returns (outcomes, first failing outcome or None)."""
    outcomes = []
    for seed in seeds:
        out = Explorer(seed=seed, max_steps=max_steps).run(harness_factory())
        outcomes.append(out)
        if out.failed:
            return outcomes, out
    return outcomes, None


def replay(harness_factory, decisions, seed=None, max_steps: int = 50_000):
    out = Explorer(seed=seed, replay=decisions, max_steps=max_steps).run(
        harness_factory()
    )
    out.seed = seed
    return out


def _switches(decisions: list) -> int:
    return sum(1 for a, b in zip(decisions, decisions[1:]) if a != b)


def shrink(harness_factory, outcome: Outcome, budget: int = 200) -> Outcome:
    """Greedily delete preemptions from a failing schedule: at every point
    where the grant switched workers, try staying on the previous worker
    and truncating the rest (run-to-completion fallback). A candidate is
    kept when it still fails with strictly fewer context switches (ties
    broken by length); the fixpoint is the minimal schedule and its digest
    is the race's stable identity across runs."""
    best = outcome
    changed = True
    while changed and budget > 0:
        changed = False
        i = 1
        while i < len(best.decisions) and budget > 0:
            d = best.decisions
            if d[i] != d[i - 1]:
                cand = replay(
                    harness_factory, d[:i] + [d[i - 1]], seed=best.seed
                )
                budget -= 1
                if cand.failed and (
                    _switches(cand.decisions), len(cand.decisions)
                ) < (_switches(best.decisions), len(best.decisions)):
                    best = cand
                    changed = True
                    continue
            i += 1
    return best


def find_and_shrink(harness_factory, max_seeds: int = 64,
                    max_steps: int = 50_000):
    """Seeds 0..max_seeds-1 until one fails, then shrinks it.
    Returns (failing seed outcome or None, shrunk outcome or None)."""
    _outcomes, failing = sweep(harness_factory, range(max_seeds), max_steps)
    if failing is None:
        return None, None
    return failing, shrink(harness_factory, failing)
