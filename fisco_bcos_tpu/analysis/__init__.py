"""Project-native static analysis + runtime lock-order recording.

The Python/JAX reproduction's answer to the reference node's C++ tooling
(TSan, clang-tidy, sanitizer CI): an AST-walking framework whose rules
encode THIS project's invariants —

- :mod:`.checkers.device_dispatch` — device crypto/hash dispatch only
  through the DevicePlane seams;
- :mod:`.checkers.shape_bucket` — jit-fed batch shapes routed through the
  bucket ladder (recompile-churn guard);
- :mod:`.checkers.jit_purity` — no side effects inside jit-traced bodies;
- :mod:`.checkers.lock_order` — static lock-acquisition graph: cycles and
  blocking IO held under a lock;
- :mod:`.checkers.guarded_state` — per-class lock-claim inference: writes
  and compound RMWs of a claimed field outside its guard, guarded mutable
  containers escaping by reference;
- :mod:`.checkers.atomicity` — lock-free check-then-act sequences and
  unlocked lazy-init of shared singletons;
- :mod:`.checkers.exceptions` — no silent broad-except swallows;
- :mod:`.checkers.contracts` — RPC idempotency classification, span
  closure, histogram bucket contract, the server-side span seam.

Findings diff against the checked-in baseline
(``tool/analysis_baseline.json``): accepted debt passes, any NEW key
fails. Run locally with ``python -m fisco_bcos_tpu.analysis``; enforced in
tier-1 by ``tests/test_static_analysis.py``.

The runtime complements: :mod:`.lockorder` — instrumented
``threading.Lock``/``RLock`` recording real per-thread acquisition chains
across the test suite, failing the session on ordering cycles or RPC IO
under a foreign lock; :mod:`.raceguard` — the sampling Eraser-lockset
recorder over the hot-class watch-list (``FISCO_RACEGUARD=1``); and
:mod:`.interleave` — the seeded deterministic interleaving explorer that
drives :mod:`.harnesses` through forced preemption schedules
(``tool/check_races.py``).

Everything importable from here is jax-free: the CLI and the tier-1 test
run on a cold interpreter in well under the 30 s budget.
"""

from __future__ import annotations

from .core import (  # noqa: F401
    DEFAULT_BASELINE,
    Checker,
    Finding,
    Source,
    diff_findings,
    load_baseline,
    load_sources,
    save_baseline,
)


def run_all(
    root: str | None = None,
    checkers=None,
    sources: list[Source] | None = None,
) -> list[Finding]:
    """Run every (or the given) checkers over the package; stable order."""
    from .checkers import ALL_CHECKERS

    srcs = sources if sources is not None else load_sources(root)
    out: list[Finding] = []
    for cls in checkers or ALL_CHECKERS:
        out.extend(cls().run(srcs))
    out.sort(key=lambda f: (f.file, f.line, f.key))
    return out


def check_repo(
    root: str | None = None, baseline_path: str | None = None
) -> tuple[list[Finding], list[str]]:
    """(new findings vs baseline, stale baseline keys) — the enforcement
    entry point shared by the CLI, the tier-1 test and bench.py's
    --telemetry gate."""
    findings = run_all(root)
    baseline = load_baseline(baseline_path)
    return diff_findings(findings, baseline)
