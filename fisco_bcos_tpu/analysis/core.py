"""Static-analysis framework: sources, findings, waivers, baseline diff.

The reference node keeps its concurrency/crypto hot paths honest with C++
tooling (TSan, clang-tidy, sanitizer CI); this package is the Python/JAX
reproduction's equivalent — an AST-walking checker framework whose rules
encode THIS project's invariants (DevicePlane-only dispatch, bucket-ladder
shape discipline, jit purity, lock ordering, exception hygiene, the
service-RPC idempotency/span/histogram contracts) rather than generic lint.

Design:

- A :class:`Source` is one parsed module (path + text + AST). The loader
  walks ``fisco_bcos_tpu/`` only — tests/tools are consumers, not subjects.
- A :class:`Finding` is keyed WITHOUT line numbers
  (``checker:relpath:symbol:detail``) so accepted debt in the baseline file
  survives unrelated edits shifting lines; display output still carries
  ``file:line`` for jumping to the site.
- **Waivers**: a ``# analysis: allow(<checker>[, reason])`` comment on the
  flagged line (or the line above it) suppresses the finding at the site —
  the in-code form of accepted debt, for cases where the exception is
  load-bearing and a baseline entry would be too far from the code.
- **Baseline** (:func:`load_baseline` / :func:`diff_findings`): the
  checked-in ``tool/analysis_baseline.json`` lists accepted finding keys
  with notes. Existing debt does not fail the build; any NEW key does, and
  stale baseline entries are reported so the file shrinks as debt is paid.

Everything here is pure AST + text — no jax import, so the CLI and the
tier-1 test stay fast on a cold process.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field

PACKAGE_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPO_ROOT = os.path.dirname(PACKAGE_DIR)
DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tool", "analysis_baseline.json")

_WAIVER_RE = re.compile(r"#\s*analysis:\s*allow\(\s*([\w.-]+)")


@dataclass(frozen=True)
class Finding:
    """One rule violation. ``key`` identifies it across line drift;
    ``file``/``line`` locate it for humans."""

    checker: str
    file: str  # repo-relative path
    line: int
    symbol: str  # enclosing function/class qualname ('' = module level)
    detail: str  # short stable slug distinguishing findings in one symbol
    message: str  # human explanation

    @property
    def key(self) -> str:
        return f"{self.checker}:{self.file}:{self.symbol}:{self.detail}"

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.checker}] {self.message}"


@dataclass
class Source:
    path: str
    relpath: str
    text: str
    tree: ast.Module
    lines: list[str] = field(default_factory=list)

    def __post_init__(self):
        if not self.lines:
            self.lines = self.text.splitlines()

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def waived(self, lineno: int, checker: str) -> bool:
        """True when the flagged line — or the contiguous comment block
        directly above it — carries an ``# analysis: allow(<checker>)``
        waiver for this checker."""
        m = _WAIVER_RE.search(self.line_text(lineno))
        if m and m.group(1) in (checker, "all"):
            return True
        ln = lineno - 1
        while ln >= 1 and self.line_text(ln).lstrip().startswith("#"):
            m = _WAIVER_RE.search(self.line_text(ln))
            if m and m.group(1) in (checker, "all"):
                return True
            ln -= 1
        return False


class Checker:
    """Base: subclasses set ``name`` and implement ``run(sources)``."""

    name = "base"

    def run(self, sources: list[Source]) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(
        self, src: Source, node: ast.AST, symbol: str, detail: str, message: str
    ) -> Finding:
        return Finding(
            self.name,
            src.relpath,
            getattr(node, "lineno", 0),
            symbol,
            detail,
            message,
        )


def load_sources(root: str | None = None) -> list[Source]:
    """Parse every ``*.py`` under ``root`` (default: the installed
    ``fisco_bcos_tpu`` package). Paths are reported relative to the repo
    root when under it, else to ``root``'s parent."""
    root = os.path.abspath(root or PACKAGE_DIR)
    base = REPO_ROOT if root.startswith(REPO_ROOT) else os.path.dirname(root)
    out: list[Source] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d != "__pycache__" and not d.startswith(".")
        )
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, encoding="utf-8") as f:
                text = f.read()
            try:
                tree = ast.parse(text, filename=path)
            except SyntaxError as e:  # surface, don't crash the run
                raise RuntimeError(f"cannot parse {path}: {e}") from e
            out.append(
                Source(path, os.path.relpath(path, base).replace(os.sep, "/"),
                       text, tree)
            )
    return out


# -- qualname helper ----------------------------------------------------------


def qualnames(tree: ast.Module) -> dict[ast.AST, str]:
    """node -> enclosing qualname ('' at module level) for every node."""
    out: dict[ast.AST, str] = {}

    def walk(node: ast.AST, qn: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                child_qn = f"{qn}.{child.name}" if qn else child.name
            else:
                child_qn = qn
            out[child] = child_qn
            walk(child, child_qn)

    out[tree] = ""
    walk(tree, "")
    return out


# -- strongly-connected components --------------------------------------------


def tarjan_sccs(graph: dict[str, set[str]]) -> list[list[str]]:
    """Strongly-connected components of ``graph`` (iterative Tarjan).

    Every vertex appears in exactly one SCC; members come back sorted and
    traversal order is deterministic. Callers filter ``len(scc) >= 2`` for
    cycles. Shared by the static lock-order checker and the runtime
    :mod:`..lockorder` recorder so the algorithm cannot diverge.
    """
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = [0]

    def connect(v: str) -> None:
        work = [(v, iter(sorted(graph[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(sorted(scc))

    for v in sorted(graph):
        if v not in index:
            connect(v)
    return sccs


# -- baseline -----------------------------------------------------------------


def load_baseline(path: str | None = None) -> dict[str, str]:
    """{finding key: note}; empty when the file does not exist."""
    path = path or DEFAULT_BASELINE
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    out: dict[str, str] = {}
    for entry in data.get("findings", []):
        if isinstance(entry, str):
            out[entry] = ""
        else:
            out[entry["key"]] = entry.get("note", "")
    return out


def save_baseline(
    findings: list[Finding], path: str | None = None, notes: dict | None = None
) -> None:
    path = path or DEFAULT_BASELINE
    notes = notes or {}
    data = {
        "_comment": "Accepted static-analysis debt. New findings FAIL; "
        "remove entries as debt is paid. See docs/static_analysis.md.",
        "findings": [
            {"key": f.key, "note": notes.get(f.key, f.message)}
            for f in sorted(findings, key=lambda f: f.key)
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, ensure_ascii=False)
        f.write("\n")


def diff_findings(
    findings: list[Finding], baseline: dict[str, str]
) -> tuple[list[Finding], list[str]]:
    """(new findings not in baseline, stale baseline keys not found now)."""
    found_keys = {f.key for f in findings}
    new = [f for f in findings if f.key not in baseline]
    stale = sorted(k for k in baseline if k not in found_keys)
    return new, stale
