"""Device-dispatch discipline: device crypto/hash entry points may only be
called from the DevicePlane seams.

PR 3 centralized ALL device crypto dispatch behind the plane's seams —
``crypto/suite.py`` batch methods, ``crypto/admission.admit_batch``, the
``ops/`` host wrappers themselves, ``device/plane.py`` and the sharded
wrappers in ``parallel/sharding.py``. A module elsewhere importing an ops
kernel and dispatching its own batch silently forks the dispatch
discipline: no coalescing, no priority lane, no breaker fallback, and its
ad-hoc batch shapes re-open the recompile churn the bucket ladder closed.

Rule: importing a device-kernel module (``ops.keccak``, ``ops.secp256k1``,
``ops.sm2``, ``ops.sm3``, ``ops.sha256``, ``ops.ed25519``, ``ops.merkle``,
``ops.address``, ``ops.pallas_ec``) — or any *device entry* name from one —
outside the seam allowlist is a finding. Host-side helpers are exempt:
``ops.hash_common``/``ops.bigint``/``ops.limb`` everywhere, and the named
host-tree classes from ``ops.merkle`` (``MerkleTree``/``MerkleProofItem``,
which ledger/lightnode legitimately use for proofs).
"""

from __future__ import annotations

import ast

from ..core import Checker, Finding, Source, qualnames

# device-kernel modules: importing these implies device dispatch
DEVICE_MODULES = {
    "keccak", "sha256", "sm3", "sm2", "secp256k1", "ed25519",
    "merkle", "address", "pallas_ec", "bls12_381", "poseidon",
}
# names importable from device modules that are host-side only
HOST_SAFE_NAMES = {
    "MerkleTree", "MerkleProofItem", "bucket_leaves", "bind_root",
}
# modules allowed to dispatch device programs (the seams)
SEAM_PREFIXES = (
    "fisco_bcos_tpu/ops/",
    "fisco_bcos_tpu/crypto/",
    "fisco_bcos_tpu/device/",
    "fisco_bcos_tpu/parallel/",
    "fisco_bcos_tpu/analysis/",  # the checkers read, never dispatch
)


def _imported_device_module(node: ast.AST) -> tuple[str, list[str]] | None:
    """(device module name, imported names ('' = whole module)) or None."""
    if isinstance(node, ast.ImportFrom) and node.module:
        parts = node.module.split(".")
        # from ..ops import keccak / from ..ops.merkle import merkle_root
        if parts[-1] in DEVICE_MODULES and (len(parts) == 1 or "ops" in parts):
            return parts[-1], [a.name for a in node.names]
        if parts[-1] == "ops" or parts[-1:] == ["ops"]:
            mods = [a.name for a in node.names if a.name in DEVICE_MODULES]
            if mods:
                return mods[0] if len(mods) == 1 else ",".join(mods), [""]
    elif isinstance(node, ast.Import):
        for a in node.names:
            parts = a.name.split(".")
            if parts[-1] in DEVICE_MODULES and "ops" in parts:
                return parts[-1], [""]
    return None


class DeviceDispatchChecker(Checker):
    name = "device-dispatch"
    description = (
        "device crypto/hash kernels import only inside the DevicePlane "
        "seams (ops/crypto/device/parallel) — everyone else uses the suite"
    )

    def run(self, sources: list[Source]) -> list[Finding]:
        out: list[Finding] = []
        for src in sources:
            if src.relpath.startswith(SEAM_PREFIXES):
                continue
            qn = qualnames(src.tree)
            for node in ast.walk(src.tree):
                hit = _imported_device_module(node)
                if hit is None:
                    continue
                mod, names = hit
                offenders = [
                    n for n in names if n == "" or n not in HOST_SAFE_NAMES
                ]
                if not offenders:
                    continue
                if src.waived(node.lineno, self.name):
                    continue
                what = ", ".join(n or f"module {mod}" for n in offenders)
                out.append(
                    self.finding(
                        src,
                        node,
                        qn.get(node, ""),
                        f"import-{mod}",
                        f"device kernel `{what}` (ops.{mod}) imported outside "
                        "the DevicePlane seams (crypto/suite, crypto/admission, "
                        "ops/, device/, parallel/) — dispatch must route "
                        "through the plane",
                    )
                )
        return out
