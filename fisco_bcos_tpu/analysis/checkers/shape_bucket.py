"""Bucket-ladder shape discipline for jit-feeding host wrappers.

Every distinct batch shape fed to a jitted program is one more XLA
compilation; ``ops/hash_common._bucket`` bounds the set of shapes (the
"bucket ladder") and ``tool/check_device_plane.py`` asserts the live
compile counter stays ≤ ladder size. That bound only holds if every host
wrapper that BUILDS arrays and CALLS a jitted function pads through the
ladder first.

Rule: a function that (a) calls a name from the package-wide jit inventory
(:mod:`..jitmap`) and (b) constructs arrays whose shape derives from input
length (``np.zeros``/``np.array``/``jnp.asarray``/... or ``len()``) must
(c) also call one of the bucketing/padding helpers (``bucket_batch``,
``_bucket``, ``bucket_leaves``, ``bucket_ladder``, ``pad_rows``,
``pad_keccak``, ``pad_md64``, ``multi_pairing_pad`` — the last is the
pairing product's power-of-two lane ladder, log₂-many shapes rather than
the hash bucket ladder) somewhere in its body. Functions that merely
pass through already-padded tensors (no array construction) are exempt —
the shape decision was made upstream where the rule already applied.
"""

from __future__ import annotations

import ast

from .. import jitmap
from ..core import Checker, Finding, Source, qualnames

BUCKET_HELPERS = {
    "bucket_batch", "_bucket", "bucket_leaves", "bucket_ladder",
    "pad_rows", "pad_keccak", "pad_md64", "multi_pairing_pad",
}
ARRAY_BUILDERS = {
    "zeros", "empty", "ones", "full", "array", "asarray", "frombuffer",
    "stack", "concatenate",
}


def _called_name(call: ast.Call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _module_bucket_names(tree: ast.Module) -> set[str]:
    """BUCKET_HELPERS plus every local alias bound by a ``from ... import
    helper as alias`` (the ops modules import ``bucket_batch as _bucket``,
    ``pad_rows as _pad_rows``)."""
    names = set(BUCKET_HELPERS)
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name in BUCKET_HELPERS and a.asname:
                    names.add(a.asname)
    return names


class ShapeBucketChecker(Checker):
    name = "shape-bucket"
    description = (
        "functions building input-sized arrays for jitted callables must "
        "pad through the bucket ladder — every raw shape is an XLA compile"
    )

    def run(self, sources: list[Source]) -> list[Finding]:
        jits = jitmap.collect(sources)
        jit_names = jitmap.callable_names(jits)
        jit_defs = {id(j.node) for j in jits}
        out: list[Finding] = []
        for src in sources:
            qn = qualnames(src.tree)
            bucket_names = _module_bucket_names(src.tree)
            # func name -> directly calls a bucket helper (for one-level
            # propagation: verify_batch buckets via its device_inputs call)
            direct_buckets: set[str] = set()
            for node in ast.walk(src.tree):
                if isinstance(node, ast.FunctionDef) and any(
                    isinstance(sub, ast.Call)
                    and _called_name(sub) in bucket_names
                    for sub in ast.walk(node)
                ):
                    direct_buckets.add(node.name)
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.FunctionDef):
                    continue
                if id(node) in jit_defs:
                    continue  # the traced body itself is shape-static
                calls_jit_at: ast.Call | None = None
                builds_arrays = False
                buckets = False
                for sub in ast.walk(node):
                    if isinstance(sub, ast.FunctionDef) and sub is not node:
                        if id(sub) in jit_defs:
                            # local jitted def (sharding makers): its caller
                            # is dynamic, skip the enclosing maker
                            calls_jit_at = None
                            builds_arrays = False
                            break
                    if not isinstance(sub, ast.Call):
                        continue
                    name = _called_name(sub)
                    if name in jit_names and calls_jit_at is None:
                        calls_jit_at = sub
                    elif name in bucket_names or name in direct_buckets:
                        buckets = True
                    elif name in ARRAY_BUILDERS:
                        builds_arrays = True
                if calls_jit_at is None or buckets or not builds_arrays:
                    continue
                if src.waived(calls_jit_at.lineno, self.name) or src.waived(
                    node.lineno, self.name
                ):
                    continue
                out.append(
                    self.finding(
                        src,
                        calls_jit_at,
                        qn.get(node, node.name),
                        f"unbucketed-{_called_name(calls_jit_at)}",
                        f"`{node.name}` builds arrays and feeds jitted "
                        f"`{_called_name(calls_jit_at)}` without bucketing "
                        "the batch shape (bucket_batch/pad_* from "
                        "ops.hash_common) — every distinct size recompiles",
                    )
                )
        return out
