"""Guard inference: which lock protects which field, and who forgets it.

The other checkers police *how* locks are taken (ordering, blocking IO
under a hold); nothing checked *what the locks are for*. This checker
infers, per class, the lock discipline the code itself implies and then
holds every access to it:

- **Lock discovery** — ``self.X = threading.Lock()/RLock()/Condition()``
  attributes, exactly like the static lock-order checker. A Condition
  built over a sibling lock (``threading.Condition(self._lock)``) is an
  alias: holding the condition IS holding the lock.
- **Claim inference** — a field *written* while lexically inside
  ``with self.<lock>:`` is claimed by that lock. Writes cover plain
  assignment, compound read-modify-write (``+=``), mutating container
  calls (``.append``/``.pop``/``.update``/…) and subscript stores
  (``self.d[k] = v`` / ``del self.d[k]``).
- **Violations** — any write-class access of a claimed field with no
  claiming lock lexically held. ``__init__`` is exempt (construction
  happens before publication), as are methods whose name ends in
  ``_locked`` — the project's convention for "caller holds the lock"
  (``_insert_locked``, ``_gate_commit_locked``); the convention is the
  documentation the checker enforces everywhere else.
- **Guarded-container escape** — ``return self.f`` / ``yield self.f``
  where ``f`` is a claimed *mutable container* hands the caller a live
  reference that outlives the guard; iterating it while a writer holds
  the lock is the race the guard existed to prevent. Returning a copy
  (``dict(self.f)``, ``list(...)``, ``.copy()``, a comprehension) passes
  because the copy happens under whatever guard the callee holds.

Plain reads are NOT flagged: single-attribute loads are atomic under the
GIL and flagging them would bury the real findings (torn compound
updates, lost increments, mid-iteration mutation) in noise. Accepted
sites carry ``# analysis: allow(guarded-state, reason)`` waivers — the
satellite contract is that benign debt lives in-code, not in the
baseline.

The runtime complement is :mod:`...analysis.raceguard` — the lockset
recorder that sees dynamic guard relationships (fields guarded by a
caller's lock three frames up) that this lexical inference cannot.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from ..core import Checker, Finding, Source, qualnames
from .lock_order import _is_lock_ctor

# container method calls that mutate the receiver in place
MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "add", "remove",
    "discard", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "insert", "move_to_end", "sort", "reverse", "rotate",
}

# init-time RHS forms that mark a field as a mutable container
_CONTAINER_CTORS = {
    "dict", "list", "set", "deque", "OrderedDict", "defaultdict",
    "Counter", "bytearray",
}

# access kinds (write class — reads are deliberately out of scope)
W_ASSIGN = "write"
W_RMW = "rmw"  # AugAssign, mutator calls, subscript stores

_EXEMPT_METHODS = ("__init__", "__post_init__", "__del__")


@dataclass
class _Access:
    fld: str
    kind: str  # W_ASSIGN | W_RMW
    node: ast.AST
    fn_qn: str
    method: str  # the class-level method name (exemption unit)
    held: frozenset


@dataclass
class _Escape:
    fld: str
    node: ast.AST
    fn_qn: str
    method: str


@dataclass
class _ClassState:
    locks: dict = field(default_factory=dict)  # attr -> canonical attr (alias)
    containers: set = field(default_factory=set)
    accesses: list = field(default_factory=list)
    escapes: list = field(default_factory=list)


def _self_attr(node: ast.AST) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _own_exprs(stmt: ast.AST):
    """Every expression node belonging to THIS statement — child statements
    (a compound statement's body) are skipped; they are visited separately
    with their own held-set, and scanning them here would record their
    accesses against the wrong guard."""
    for child in ast.iter_child_nodes(stmt):
        if isinstance(child, (ast.stmt, ast.excepthandler)):
            continue
        yield from ast.walk(child)


class GuardedStateChecker(Checker):
    name = "guarded-state"
    description = (
        "infer which lock guards which self._field (a field written under "
        "`with self._lock:` is claimed by it); flag writes/RMWs outside the "
        "guard and guarded mutable containers escaping by reference"
    )

    def run(self, sources: list[Source]) -> list[Finding]:
        out: list[Finding] = []
        for src in sources:
            qn = qualnames(src.tree)
            for node in src.tree.body:
                if isinstance(node, ast.ClassDef):
                    self._check_class(src, node, qn, out)
        return out

    # -- per-class analysis ----------------------------------------------------

    def _check_class(self, src, cls: ast.ClassDef, qn, out) -> None:
        st = _ClassState()
        self._collect_locks(cls, st)
        if not st.locks:
            return  # no lock, no discipline to infer
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_fn(src, item, qn, st, item.name)
        claims = self._claims(st)
        if not claims:
            return
        seen: set[tuple[str, str]] = set()
        for acc in st.accesses:
            claiming = claims.get(acc.fld)
            if not claiming or acc.held & claiming:
                continue
            if acc.method in _EXEMPT_METHODS or acc.method.endswith("_locked"):
                continue
            if src.waived(acc.node.lineno, self.name):
                continue
            detail = f"unguarded-{acc.kind}-{acc.fld}"
            if (acc.fn_qn, detail) in seen:
                continue
            seen.add((acc.fn_qn, detail))
            locks = "/".join(sorted(f"self.{x}" for x in claiming))
            out.append(
                self.finding(
                    src, acc.node, acc.fn_qn, detail,
                    f"{acc.kind} of `self.{acc.fld}` outside its guard — the "
                    f"field is claimed by `{locks}` (written under it "
                    "elsewhere); take the lock, rename the method *_locked "
                    "if the caller holds it, or waive with `# analysis: "
                    "allow(guarded-state, reason)`",
                )
            )
        for esc in st.escapes:
            claiming = claims.get(esc.fld)
            if not claiming or esc.fld not in st.containers:
                continue
            if src.waived(esc.node.lineno, self.name):
                continue
            detail = f"escape-{esc.fld}"
            if (esc.fn_qn, detail) in seen:
                continue
            seen.add((esc.fn_qn, detail))
            out.append(
                self.finding(
                    src, esc.node, esc.fn_qn, detail,
                    f"`self.{esc.fld}` is a lock-guarded mutable container "
                    "escaping by reference — the caller iterates it outside "
                    "the guard while writers mutate it; return a copy "
                    "(dict(...)/list(...)/.copy()) or waive",
                )
            )

    def _collect_locks(self, cls: ast.ClassDef, st: _ClassState) -> None:
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign) or not _is_lock_ctor(node.value):
                continue
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr is None:
                    continue
                # Condition(self._lock) aliases the condition to its lock:
                # holding either is the same guard
                canonical = attr
                call = node.value
                if isinstance(call, ast.Call) and call.args:
                    arg_attr = _self_attr(call.args[0])
                    if arg_attr is not None:
                        canonical = st.locks.get(arg_attr, arg_attr)
                st.locks[attr] = canonical

    def _claims(self, st: _ClassState) -> dict[str, frozenset]:
        claims: dict[str, set] = {}
        for acc in st.accesses:
            if acc.held:
                claims.setdefault(acc.fld, set()).update(acc.held)
        return {f: frozenset(s) for f, s in claims.items()}

    # -- statement walk with lexical held-set ---------------------------------

    def _walk_fn(self, src, fn, qn, st: _ClassState, method: str) -> None:
        fn_qn = qn.get(fn, fn.name)

        def held_of(with_node: ast.With, held: frozenset) -> frozenset:
            got = set(held)
            for item in with_node.items:
                attr = _self_attr(item.context_expr)
                if attr is not None and attr in st.locks:
                    got.add(st.locks[attr])
            return frozenset(got)

        def walk(node: ast.AST, held: frozenset) -> None:
            for sub in ast.iter_child_nodes(node):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)):
                    # nested defs run later, outside this lexical guard
                    walk(sub, frozenset())
                    continue
                new_held = held
                if isinstance(sub, (ast.With, ast.AsyncWith)):
                    new_held = held_of(sub, held)
                self._note_stmt(src, sub, fn_qn, method, new_held, st)
                walk(sub, new_held)

        self._note_init_containers(fn, method, st)
        walk(fn, frozenset())

    def _note_init_containers(self, fn, method: str, st: _ClassState) -> None:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr is None:
                    continue
                v = node.value
                if isinstance(v, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                                  ast.DictComp, ast.SetComp)):
                    st.containers.add(attr)
                elif isinstance(v, ast.Call):
                    f = v.func
                    name = f.attr if isinstance(f, ast.Attribute) else (
                        f.id if isinstance(f, ast.Name) else None
                    )
                    if name in _CONTAINER_CTORS:
                        st.containers.add(attr)

    def _note_stmt(self, src, stmt, fn_qn, method, held, st: _ClassState) -> None:
        note = st.accesses.append

        def targets_of(t):
            if isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    yield from targets_of(e)
            else:
                yield t

        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            tgts = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            for t in tgts:
                for tgt in targets_of(t):
                    attr = _self_attr(tgt)
                    if attr is not None and attr not in st.locks:
                        note(_Access(attr, W_ASSIGN, stmt, fn_qn, method, held))
                    elif isinstance(tgt, ast.Subscript):
                        a = _self_attr(tgt.value)
                        if a is not None:
                            st.containers.add(a)
                            note(_Access(a, W_RMW, stmt, fn_qn, method, held))
        elif isinstance(stmt, ast.AugAssign):
            attr = _self_attr(stmt.target)
            if attr is not None:
                note(_Access(attr, W_RMW, stmt, fn_qn, method, held))
            elif isinstance(stmt.target, ast.Subscript):
                a = _self_attr(stmt.target.value)
                if a is not None:
                    st.containers.add(a)
                    note(_Access(a, W_RMW, stmt, fn_qn, method, held))
        elif isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Subscript):
                    a = _self_attr(tgt.value)
                    if a is not None:
                        st.containers.add(a)
                        note(_Access(a, W_RMW, stmt, fn_qn, method, held))
        elif isinstance(stmt, ast.Return) and stmt.value is not None:
            self._note_escape(stmt, stmt.value, fn_qn, method, st)
        # mutator calls + yield escapes in this statement's own expressions
        for sub in _own_exprs(stmt):
            if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
                a = _self_attr(sub.func.value)
                if a is not None and sub.func.attr in MUTATORS:
                    st.containers.add(a)
                    note(_Access(a, W_RMW, sub, fn_qn, method, held))
            elif isinstance(sub, ast.Yield) and sub.value is not None:
                self._note_escape(sub, sub.value, fn_qn, method, st)

    def _note_escape(self, node, value, fn_qn, method, st: _ClassState) -> None:
        vals = value.elts if isinstance(value, ast.Tuple) else [value]
        for v in vals:
            attr = _self_attr(v)
            if attr is not None and attr not in st.locks:
                st.escapes.append(_Escape(attr, node, fn_qn, method))
