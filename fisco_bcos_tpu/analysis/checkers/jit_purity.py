"""Jit purity: side effects inside jit-traced function bodies.

A jitted body runs ONCE per compiled shape at trace time, then never
again — a ``time.time()`` inside it freezes the clock at trace time, a
``TRACER``/``REGISTRY`` call records exactly one fake event per compile, a
lock acquire parks the tracer thread (and is skipped on every cached run),
Python RNG bakes one draw into the program, and mutating captured state
from inside the trace is a silent correctness bug (it happens at trace
time, not run time). The reference catches the C++ analogs with TSan +
code review; here the ~22 jitted functions are walked mechanically.

Flagged inside any body from the :mod:`..jitmap` inventory:

- calls rooted at ``time``/``random``/``os``/``secrets``/``threading``/
  ``socket`` (trace-time constants or real side effects),
- ``print``/``open``/``input`` builtins,
- telemetry (``TRACER``/``REGISTRY``/logger receivers, ``.observe``/
  ``.counter_add``/``.gauge_set``/logging-method names),
- lock traffic (``.acquire()``/``.release()``),
- ``global``/``nonlocal`` declarations,
- attribute stores (``obj.attr = ...`` — captured-state mutation).

``jnp``/``lax``/``np`` numeric calls are the purpose of the body and pass.
"""

from __future__ import annotations

import ast

from .. import jitmap
from ..core import Checker, Finding, Source

IMPURE_ROOTS = {"time", "random", "os", "secrets", "threading", "socket"}
IMPURE_BUILTINS = {"print", "open", "input"}
TELEMETRY_RECEIVERS = {"TRACER", "REGISTRY", "_log", "log", "logger", "logging"}
TELEMETRY_METHODS = {
    "observe", "counter_add", "gauge_set", "gauge_fn", "span", "record",
    "info", "warning", "error", "debug", "exception", "metric",
}
LOCK_METHODS = {"acquire", "release"}


def _root_name(node: ast.AST) -> str | None:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


class JitPurityChecker(Checker):
    name = "jit-purity"
    description = (
        "no side effects inside jit-traced bodies (time/RNG/locks/"
        "telemetry/attr stores run once at trace time, then never again)"
    )

    def _offense(self, sub: ast.AST) -> str | None:
        if isinstance(sub, (ast.Global, ast.Nonlocal)):
            return "global-state"
        if isinstance(sub, (ast.Assign, ast.AugAssign)):
            targets = (
                sub.targets if isinstance(sub, ast.Assign) else [sub.target]
            )
            if any(isinstance(t, ast.Attribute) for t in targets):
                return "captured-mutation"
            return None
        if not isinstance(sub, ast.Call):
            return None
        fn = sub.func
        if isinstance(fn, ast.Name) and fn.id in IMPURE_BUILTINS:
            return f"builtin-{fn.id}"
        if isinstance(fn, ast.Attribute):
            root = _root_name(fn)
            if root in IMPURE_ROOTS:
                return f"impure-{root}.{fn.attr}"
            if root in TELEMETRY_RECEIVERS or fn.attr in TELEMETRY_METHODS:
                return f"telemetry-{fn.attr}"
            if fn.attr in LOCK_METHODS:
                return f"lock-{fn.attr}"
        return None

    def run(self, sources: list[Source]) -> list[Finding]:
        out: list[Finding] = []
        for jit in jitmap.collect(sources):
            src = jit.source
            for sub in ast.walk(jit.node):
                offense = self._offense(sub)
                if offense is None:
                    continue
                if src.waived(sub.lineno, self.name):
                    continue
                out.append(
                    self.finding(
                        src,
                        sub,
                        jit.qualname,
                        offense,
                        f"side effect `{offense}` inside jit-traced "
                        f"`{jit.qualname}` — runs at trace time only, "
                        "skipped on every cached execution",
                    )
                )
        return out
