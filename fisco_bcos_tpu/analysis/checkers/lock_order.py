"""Static lock-acquisition graph: ordering cycles and blocking calls held
under a lock.

The ~20 lock-holding modules (txpool, engine, plane, gateway, storage,
observability) each follow a local discipline, but nothing checked the
GLOBAL order — a PR that takes ``txpool._lock`` under ``engine._lock``
while another path takes them reversed deadlocks only under load. TSan
would catch the C++ analog; here the acquisition graph is built from the
AST:

- **Locks** are ``self.X = threading.Lock()/RLock()/Condition()``
  attributes (node ``module:Class.X``) and module-level ``X = Lock()``
  globals (``module:X``).
- **Edges**: a ``with``-lock body that lexically acquires another lock, or
  calls a same-module function/method whose (transitively computed)
  acquire-set is non-empty, orders the first lock before the second.
- **Cycles** in that graph are findings — every thread must see one global
  order.
- **Blocking calls under a lock**: socket IO, ``time.sleep``, future
  ``.result()``, frame send/recv, ``client.call`` and thread ``.join``
  inside a ``with``-lock body serialize every peer of that lock behind a
  remote's latency (the ACE-runtime offload papers' classic anti-pattern).
  By-design sites (the RPC client's pipeline lock) carry baseline entries.

The runtime complement (:mod:`..lockorder`) records ACTUAL per-thread
acquisition chains across the test suite — what static analysis cannot see
(locks reached through callbacks, cross-module calls).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from ..core import Checker, Finding, Source, qualnames, tarjan_sccs

LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
BLOCKING_ATTRS = {
    "sleep", "result", "recv", "sendall", "accept", "connect", "join",
    "drain",
}
BLOCKING_NAMES = {"_recv_frame", "_send_frame", "create_connection"}


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None
    )
    return name in LOCK_FACTORIES


@dataclass
class _ModuleLocks:
    src: Source
    # attr name -> node id, per class; '' key = module globals
    by_class: dict[str, dict[str, str]] = field(default_factory=dict)


def _lock_expr_id(expr: ast.AST, mod: _ModuleLocks, cls: str) -> str | None:
    """Resolve a with-item expression to a known lock node id."""
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        if expr.value.id == "self":
            hit = mod.by_class.get(cls, {}).get(expr.attr)
            if hit:
                return hit
            # self.<attr> where the attr is a lock of ANOTHER class in the
            # same module (mixins): unique-name match
            hits = {
                v
                for c, attrs in mod.by_class.items()
                for a, v in attrs.items()
                if a == expr.attr and c
            }
            if len(hits) == 1:
                return hits.pop()
    if isinstance(expr, ast.Name):
        return mod.by_class.get("", {}).get(expr.id)
    return None


class LockOrderChecker(Checker):
    name = "lock-order"
    description = (
        "static lock-acquisition graph: ordering cycles (Tarjan SCC) and "
        "blocking calls (sleep/socket/.result/.join/frame IO) under a lock"
    )

    def run(self, sources: list[Source]) -> list[Finding]:
        modules = [self._collect_locks(src) for src in sources]
        out: list[Finding] = []
        edges: dict[tuple[str, str], tuple[Source, ast.AST, str]] = {}
        for mod in modules:
            self._walk_module(mod, edges, out)
        out.extend(self._cycles(edges))
        return out

    # -- lock discovery -------------------------------------------------------

    def _collect_locks(self, src: Source) -> _ModuleLocks:
        mod = _ModuleLocks(src)
        qn = qualnames(src.tree)
        modname = src.relpath
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Assign) or not _is_lock_ctor(node.value):
                continue
            scope = qn.get(node, "")
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    cls = scope.split(".")[0] if scope else ""
                    mod.by_class.setdefault(cls, {})[tgt.attr] = (
                        f"{modname}:{cls}.{tgt.attr}"
                    )
                elif isinstance(tgt, ast.Name) and not scope:
                    mod.by_class.setdefault("", {})[tgt.id] = (
                        f"{modname}:{tgt.id}"
                    )
        return mod

    # -- acquisition graph ----------------------------------------------------

    def _walk_module(self, mod: _ModuleLocks, edges, out) -> None:
        src = mod.src
        qn = qualnames(src.tree)
        funcs: dict[str, ast.FunctionDef] = {}
        for node in ast.walk(src.tree):
            if isinstance(node, ast.FunctionDef):
                funcs[qn.get(node, node.name)] = node

        # transitive acquire-sets over the same-module call graph
        acq_memo: dict[str, set[str]] = {}

        def direct_acquires(fn_qn: str, node: ast.FunctionDef) -> set[str]:
            cls = fn_qn.split(".")[0] if "." in fn_qn else ""
            got: set[str] = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.With):
                    for item in sub.items:
                        lock = _lock_expr_id(item.context_expr, mod, cls)
                        if lock:
                            got.add(lock)
            return got

        def callees(fn_qn: str, node: ast.FunctionDef) -> set[str]:
            cls = fn_qn.split(".")[0] if "." in fn_qn else ""
            found: set[str] = set()
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                f = sub.func
                if (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self"
                    and f"{cls}.{f.attr}" in funcs
                ):
                    found.add(f"{cls}.{f.attr}")
                elif isinstance(f, ast.Name) and f.id in funcs:
                    found.add(f.id)
            return found

        def effective_acquires(fn_qn: str, stack: tuple = ()) -> set[str]:
            if fn_qn in acq_memo:
                return acq_memo[fn_qn]
            if fn_qn in stack or fn_qn not in funcs:
                return set()
            node = funcs[fn_qn]
            got = set(direct_acquires(fn_qn, node))
            for callee in callees(fn_qn, node):
                got |= effective_acquires(callee, stack + (fn_qn,))
            acq_memo[fn_qn] = got
            return got

        for fn_qn, node in funcs.items():
            cls = fn_qn.split(".")[0] if "." in fn_qn else ""
            self._walk_body(
                src, mod, cls, fn_qn, node, funcs, effective_acquires,
                edges, out, held=(),
            )

    def _walk_body(
        self, src, mod, cls, fn_qn, node, funcs, eff_acq, edges, out, held
    ) -> None:
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, ast.FunctionDef) and sub is not node:
                continue  # nested defs run later, outside this lock scope
            new_held = held
            if isinstance(sub, ast.With):
                for item in sub.items:
                    lock = _lock_expr_id(item.context_expr, mod, cls)
                    if lock:
                        for h in new_held:
                            if h != lock:
                                edges.setdefault(
                                    (h, lock), (src, item.context_expr, fn_qn)
                                )
                        new_held = new_held + (lock,)
            elif held and isinstance(sub, (ast.Expr, ast.Assign, ast.Return)):
                self._check_blocking(src, cls, fn_qn, sub, held, out)
            # call-propagated edges inside held regions
            if new_held:
                for call in self._calls_in_stmt(sub):
                    callee = self._resolve_callee(call, cls, funcs)
                    if callee:
                        for lock in eff_acq(callee):
                            for h in new_held:
                                if h != lock:
                                    edges.setdefault(
                                        (h, lock), (src, call, fn_qn)
                                    )
            self._walk_body(
                src, mod, cls, fn_qn, sub, funcs, eff_acq, edges, out, new_held
            )

    @staticmethod
    def _calls_in_stmt(stmt: ast.AST):
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Call):
                yield sub

    @staticmethod
    def _resolve_callee(call: ast.Call, cls: str, funcs) -> str | None:
        f = call.func
        if (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id == "self"
            and f"{cls}.{f.attr}" in funcs
        ):
            return f"{cls}.{f.attr}"
        if isinstance(f, ast.Name) and f.id in funcs:
            return f.id
        return None

    # -- blocking calls under a lock ------------------------------------------

    def _check_blocking(self, src, cls, fn_qn, stmt, held, out) -> None:
        for call in self._calls_in_stmt(stmt):
            f = call.func
            name = None
            if isinstance(f, ast.Attribute):
                if f.attr in BLOCKING_ATTRS:
                    # Condition.wait on the held lock itself is the cv
                    # protocol, not a blocking call under a foreign lock
                    name = f.attr
                elif f.attr == "call" and "client" in ast.dump(f.value).lower():
                    name = "client.call"
            elif isinstance(f, ast.Name) and f.id in BLOCKING_NAMES:
                name = f.id
            if name is None:
                continue
            if src.waived(call.lineno, self.name):
                continue
            lock = held[-1]
            out.append(
                self.finding(
                    src,
                    call,
                    fn_qn,
                    f"blocking-{name}-under-{lock.rsplit(':', 1)[-1]}",
                    f"blocking call `{name}` while holding `{lock}` — "
                    "every peer of that lock serializes behind this IO",
                )
            )

    # -- cycles ---------------------------------------------------------------

    def _cycles(self, edges) -> list[Finding]:
        graph: dict[str, set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        out: list[Finding] = []
        for members in tarjan_sccs(graph):
            if len(members) < 2:
                continue
            scc = set(members)
            src, node, fn_qn = next(
                edges[(a, b)]
                for (a, b) in edges
                if a in scc and b in scc
            )
            out.append(
                self.finding(
                    src,
                    node,
                    "",
                    "cycle-" + "-".join(
                        m.rsplit(":", 1)[-1] for m in members
                    ),
                    "lock-order cycle: " + " <-> ".join(members)
                    + " — threads taking these in different orders deadlock",
                )
            )
        return out
