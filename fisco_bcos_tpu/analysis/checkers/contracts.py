"""Contract completeness: idempotency classification, span closure,
histogram bucket discipline, the server-side span seam.

Four sub-rules over contracts earlier PRs established:

1. **rpc-unclassified** — every method name registered on a
   :class:`~fisco_bcos_tpu.service.rpc.ServiceServer` must appear in
   ``resilience.retry.IDEMPOTENT_METHODS`` or ``NON_IDEMPOTENT_METHODS``
   (parsed statically from retry.py, plus literal ``mark_idempotent("x")``
   calls anywhere). An unclassified method silently opts out of auto-retry
   — or worse, a future default flip double-executes it.
2. **span-not-closed** — ``TRACER.span(...)`` / ``device_span(...)`` must
   be entered as a ``with`` item (directly, or via a name assigned and then
   used as a ``with`` item in the same function). A span that is never
   ``__exit__``-ed never records and silently truncates its whole trace
   subtree.
3. **adhoc-latency-buckets** — ``*.observe("..._ms", ...)`` and
   ``Histogram("..._ms", ...)`` must not pass a literal bucket list:
   latency histograms ride the mtail 0/50/100/150 ms contract
   (``LATENCY_BUCKETS_MS``) or another NAMED ``*_BUCKETS*`` constant, so
   dashboards built against the reference exposition keep parsing. A
   literal that shadows the contract drifts silently.
4. **server-span-seam** — the central ``svc.<service>.<method>`` span in
   ``service/rpc.py``'s dispatch loop must stay present (it is what makes
   rule 1's classification observable across the split); its removal is a
   finding against rpc.py itself.
"""

from __future__ import annotations

import ast

from ..core import Checker, Finding, Source, qualnames

RETRY_MODULE = "fisco_bcos_tpu/resilience/retry.py"
RPC_MODULE = "fisco_bcos_tpu/service/rpc.py"
SPAN_FACTORIES = {"span", "device_span"}
# modules that define/forward the span machinery itself
SPAN_DEFINING = (
    "fisco_bcos_tpu/observability/",
    "fisco_bcos_tpu/analysis/",
)


def _classified_methods(sources: list[Source]) -> set[str]:
    """The union of both classification sets in retry.py, plus every
    literal ``mark_idempotent("name"[, flag])`` call in the package."""
    out: set[str] = set()
    for src in sources:
        if src.relpath == RETRY_MODULE:
            for node in ast.walk(src.tree):
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for tgt in targets:
                        if isinstance(tgt, ast.Name) and tgt.id in (
                            "IDEMPOTENT_METHODS",
                            "NON_IDEMPOTENT_METHODS",
                        ):
                            for el in getattr(node.value, "elts", []):
                                if isinstance(el, ast.Constant) and isinstance(
                                    el.value, str
                                ):
                                    out.add(el.value)
        for node in ast.walk(src.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, (ast.Name, ast.Attribute))
                and (
                    getattr(node.func, "id", None) == "mark_idempotent"
                    or getattr(node.func, "attr", None) == "mark_idempotent"
                )
                and node.args
                and isinstance(node.args[0], ast.Constant)
            ):
                out.add(node.args[0].value)
    return out


class ContractChecker(Checker):
    name = "contract"
    description = (
        "RPC methods classified for idempotency, spans closed as with-"
        "items, *_ms histograms on named *_BUCKETS constants"
    )

    def run(self, sources: list[Source]) -> list[Finding]:
        out: list[Finding] = []
        classified = _classified_methods(sources)
        for src in sources:
            qn = qualnames(src.tree)
            self._check_registrations(src, qn, classified, out)
            if not src.relpath.startswith(SPAN_DEFINING):
                self._check_span_closure(src, qn, out)
            self._check_histogram_buckets(src, qn, out)
        self._check_server_span_seam(sources, out)
        return out

    # -- rule 1: idempotency classification -----------------------------------

    def _check_registrations(self, src, qn, classified, out) -> None:
        for node in ast.walk(src.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "register"
                and len(node.args) == 2
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            method = node.args[0].value
            if method in classified:
                continue
            if src.waived(node.lineno, self.name):
                continue
            out.append(
                self.finding(
                    src,
                    node,
                    qn.get(node, ""),
                    f"rpc-unclassified-{method}",
                    f"service-RPC method `{method}` has no idempotency "
                    "classification (resilience.retry IDEMPOTENT_METHODS / "
                    "NON_IDEMPOTENT_METHODS or mark_idempotent) — retry "
                    "behavior is undefined for it",
                )
            )

    # -- rule 2: span closure -------------------------------------------------

    def _check_span_closure(self, src, qn, out) -> None:
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            with_exprs: list[ast.expr] = []
            with_names: set[str] = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.With):
                    for item in sub.items:
                        with_exprs.append(item.context_expr)
                        if isinstance(item.context_expr, ast.Name):
                            with_names.add(item.context_expr.id)
            assigned_to_with: set[int] = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and self._is_span_call(
                    sub.value
                ):
                    if any(
                        isinstance(t, ast.Name) and t.id in with_names
                        for t in sub.targets
                    ):
                        assigned_to_with.add(id(sub.value))
            for sub in ast.walk(node):
                if not self._is_span_call(sub):
                    continue
                if any(sub is e for e in with_exprs):
                    continue
                if id(sub) in assigned_to_with:
                    continue
                if src.waived(sub.lineno, self.name):
                    continue
                fname = (
                    sub.func.attr
                    if isinstance(sub.func, ast.Attribute)
                    else sub.func.id
                )
                out.append(
                    self.finding(
                        src,
                        sub,
                        qn.get(node, node.name),
                        f"span-not-closed-{fname}",
                        f"`{fname}(...)` is not entered as a `with` item — "
                        "an unclosed span never records and truncates its "
                        "trace subtree",
                    )
                )

    @staticmethod
    def _is_span_call(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in SPAN_FACTORIES:
            # only the tracer's span factory, not arbitrary .span() methods
            root = f.value
            return isinstance(root, ast.Name) and root.id in (
                "TRACER",
                "tracer",
            )
        return isinstance(f, ast.Name) and f.id in SPAN_FACTORIES

    # -- rule 3: histogram bucket discipline ----------------------------------

    def _check_histogram_buckets(self, src, qn, out) -> None:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            is_observe = isinstance(f, ast.Attribute) and f.attr in (
                "observe",
                "histogram",
            )
            is_ctor = isinstance(f, ast.Name) and f.id == "Histogram"
            if not (is_observe or is_ctor):
                continue
            if not (
                node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            metric = node.args[0].value
            if not metric.endswith("_ms"):
                continue
            buckets = next(
                (kw.value for kw in node.keywords if kw.arg == "buckets"),
                None,
            )
            if buckets is None and is_ctor and len(node.args) > 1:
                buckets = node.args[1]
            if buckets is None:
                continue  # default = the mtail contract
            if isinstance(buckets, (ast.Name, ast.Attribute)):
                name = (
                    buckets.id
                    if isinstance(buckets, ast.Name)
                    else buckets.attr
                )
                if "BUCKETS" in name:
                    continue  # a named, reviewable contract
            if src.waived(node.lineno, self.name):
                continue
            out.append(
                self.finding(
                    src,
                    node,
                    qn.get(node, ""),
                    f"adhoc-latency-buckets-{metric}",
                    f"latency histogram `{metric}` passes ad-hoc literal "
                    "buckets — use LATENCY_BUCKETS_MS or a named *_BUCKETS "
                    "constant so the exposition contract stays reviewable",
                )
            )

    # -- rule 4: the server-side span seam ------------------------------------

    def _check_server_span_seam(self, sources, out) -> None:
        rpc = next((s for s in sources if s.relpath == RPC_MODULE), None)
        if rpc is None:
            return  # analyzing a fixture tree, not the package
        if '"svc.' in rpc.text or "f\"svc." in rpc.text or "svc.{" in rpc.text:
            return
        out.append(
            Finding(
                self.name,
                rpc.relpath,
                1,
                "ServiceServer._serve",
                "server-span-seam-missing",
                "the central `svc.<service>.<method>` server-side span is "
                "gone from service/rpc.py dispatch — cross-process traces "
                "lose their server leg",
            )
        )
