"""Checker registry — the project-native rule set, one module per rule."""

from __future__ import annotations

from .atomicity import AtomicityChecker
from .contracts import ContractChecker
from .device_dispatch import DeviceDispatchChecker
from .dtype_drift import DtypeDriftChecker
from .exceptions import ExceptionHygieneChecker
from .guarded_state import GuardedStateChecker
from .host_sync import HostSyncChecker
from .jit_purity import JitPurityChecker
from .lock_order import LockOrderChecker
from .program_coherence import ProgramCoherenceChecker
from .shape_bucket import ShapeBucketChecker

ALL_CHECKERS = (
    DeviceDispatchChecker,
    ShapeBucketChecker,
    JitPurityChecker,
    HostSyncChecker,
    DtypeDriftChecker,
    ProgramCoherenceChecker,
    LockOrderChecker,
    GuardedStateChecker,
    AtomicityChecker,
    ExceptionHygieneChecker,
    ContractChecker,
)


def checker_names() -> list[str]:
    return [c.name for c in ALL_CHECKERS]


def checker_by_name(name: str):
    """Resolve a checker class by its registered name (None if unknown)."""
    for c in ALL_CHECKERS:
        if c.name == name:
            return c
    return None
