"""Checker registry — the project-native rule set, one module per rule."""

from __future__ import annotations

from .atomicity import AtomicityChecker
from .contracts import ContractChecker
from .device_dispatch import DeviceDispatchChecker
from .exceptions import ExceptionHygieneChecker
from .guarded_state import GuardedStateChecker
from .jit_purity import JitPurityChecker
from .lock_order import LockOrderChecker
from .shape_bucket import ShapeBucketChecker

ALL_CHECKERS = (
    DeviceDispatchChecker,
    ShapeBucketChecker,
    JitPurityChecker,
    LockOrderChecker,
    GuardedStateChecker,
    AtomicityChecker,
    ExceptionHygieneChecker,
    ContractChecker,
)


def checker_names() -> list[str]:
    return [c.name for c in ALL_CHECKERS]


def checker_by_name(name: str):
    """Resolve a checker class by its registered name (None if unknown)."""
    for c in ALL_CHECKERS:
        if c.name == name:
            return c
    return None
