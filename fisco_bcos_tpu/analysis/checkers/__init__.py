"""Checker registry — the project-native rule set, one module per rule."""

from __future__ import annotations

from .contracts import ContractChecker
from .device_dispatch import DeviceDispatchChecker
from .exceptions import ExceptionHygieneChecker
from .jit_purity import JitPurityChecker
from .lock_order import LockOrderChecker
from .shape_bucket import ShapeBucketChecker

ALL_CHECKERS = (
    DeviceDispatchChecker,
    ShapeBucketChecker,
    JitPurityChecker,
    LockOrderChecker,
    ExceptionHygieneChecker,
    ContractChecker,
)


def checker_names() -> list[str]:
    return [c.name for c in ALL_CHECKERS]
