"""Atomicity: lock-free check-then-act and unlocked lazy-init.

The guarded-state checker catches writes that forgot their lock; this one
catches the subtler class where every individual operation is atomic under
the GIL yet the *sequence* is not:

- **Check-then-act on shared containers/fields** — in a class that owns a
  lock, an ``if`` whose test reads ``self``-state and whose body acts on
  the same state, with no lock lexically held::

      if k in self._cache:            if not self._started:
          return self._cache[k]           self._started = True
                                          self._spawn()

  Between the test and the act any other thread may mutate the state: the
  read returns a value the act no longer sees (KeyError on the index), or
  two threads both pass the ``not self._started`` gate and double-start.
  Two detail classes: ``check-then-act-<field>`` for the membership/index
  form, ``racy-lazy-init-<field>`` for the test-then-assign form.
- **Unlocked lazy-init of module singletons** — a module-level factory
  that assigns a ``global`` inside ``if X is None:`` with no module lock
  held. Two threads racing the factory each build an instance and one
  wins arbitrarily — callers end up holding *different* singletons (two
  DevicePlanes each coalescing half the traffic). The project's
  double-checked pattern (outer unlocked check, assignment under the
  lock — ``get_plane``/``get_quotas``) passes because the assignment
  itself is guarded.

Scope control (false positives are the death of a gate): the class rules
only fire in classes that own at least one ``threading`` lock — a class
with no lock is single-threaded by design or someone else's problem, and
flagging it would train people to ignore the checker. ``__init__`` and
``*_locked``-suffixed methods are exempt exactly as in guarded-state.
Benign sites carry ``# analysis: allow(atomicity, reason)`` waivers.
"""

from __future__ import annotations

import ast

from ..core import Checker, Finding, Source, qualnames
from .guarded_state import MUTATORS, _EXEMPT_METHODS, _own_exprs, _self_attr
from .lock_order import _is_lock_ctor


def _test_self_membership(test: ast.AST) -> str | None:
    """`k in self.d` / `k not in self.d` -> 'd'."""
    if isinstance(test, ast.Compare) and len(test.ops) == 1:
        if isinstance(test.ops[0], (ast.In, ast.NotIn)):
            return _self_attr(test.comparators[0])
    return None


def _test_self_truthiness(test: ast.AST) -> str | None:
    """`not self.f` / `self.f is None` / `self.f` -> 'f'."""
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _self_attr(test.operand)
    if (
        isinstance(test, ast.Compare)
        and len(test.ops) == 1
        and isinstance(test.ops[0], (ast.Is, ast.Eq))
        and isinstance(test.comparators[0], ast.Constant)
        and test.comparators[0].value is None
    ):
        return _self_attr(test.left)
    return _self_attr(test)


def _tests_of(test: ast.AST):
    """Flatten `a or b` / `a and b` into candidate atoms."""
    if isinstance(test, ast.BoolOp):
        for v in test.values:
            yield from _tests_of(v)
    else:
        yield test


class AtomicityChecker(Checker):
    name = "atomicity"
    description = (
        "flag lock-free check-then-act sequences (`if k in self.d: "
        "... self.d[k]`, `if not self._x: self._x = ...`) in lock-owning "
        "classes, and unlocked lazy-init of module-level singletons"
    )

    def run(self, sources: list[Source]) -> list[Finding]:
        out: list[Finding] = []
        for src in sources:
            qn = qualnames(src.tree)
            module_locks = self._module_locks(src.tree)
            for node in src.tree.body:
                if isinstance(node, ast.ClassDef):
                    self._check_class(src, node, qn, out)
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._check_lazy_init(src, node, qn, module_locks, out)
        return out

    # -- class rules ----------------------------------------------------------

    def _class_locks(self, cls: ast.ClassDef) -> set[str]:
        locks: set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr is not None:
                        locks.add(attr)
        return locks

    def _check_class(self, src, cls: ast.ClassDef, qn, out) -> None:
        locks = self._class_locks(cls)
        if not locks:
            return
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if item.name in _EXEMPT_METHODS or item.name.endswith("_locked"):
                continue
            self._walk(src, item, qn, locks, out, held=False)

    def _walk(self, src, node, qn, locks, out, held: bool) -> None:
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                self._walk(src, sub, qn, locks, out, held=False)
                continue
            now_held = held
            if isinstance(sub, (ast.With, ast.AsyncWith)):
                for item in sub.items:
                    attr = _self_attr(item.context_expr)
                    if attr is not None and attr in locks:
                        now_held = True
            if isinstance(sub, ast.If) and not now_held:
                self._check_if(src, sub, qn, locks, out)
            self._walk(src, sub, qn, locks, out, now_held)

    def _check_if(self, src, node: ast.If, qn, locks, out) -> None:
        fn_qn = qn.get(node, "")
        for test in _tests_of(node.test):
            fld = _test_self_membership(test)
            if fld is not None and self._acts_on(node.body, fld, locks):
                self._emit(
                    src, node, fn_qn, f"check-then-act-{fld}",
                    f"lock-free check-then-act on `self.{fld}`: the test and "
                    "the dependent access race other threads' mutations — "
                    "hold the owning lock across both, or use a single "
                    "atomic op (.get/.setdefault/.pop(k, None)), or waive "
                    "with `# analysis: allow(atomicity, reason)`", out,
                )
                return
            fld = _test_self_truthiness(test)
            if fld is not None and self._assigns(node.body, fld, locks):
                self._emit(
                    src, node, fn_qn, f"racy-lazy-init-{fld}",
                    f"test-then-assign of `self.{fld}` without the lock: two "
                    "threads can both pass the gate and double-initialize — "
                    "assign under the owning lock (double-checked is fine) "
                    "or waive with `# analysis: allow(atomicity, reason)`",
                    out,
                )
                return

    def _unguarded_stmts(self, body: list, locks):
        """Statements in `body` NOT under a `with self.<lock>:` — an act
        that re-takes the lock is the double-checked pattern, not a race."""
        for stmt in body:
            if isinstance(stmt, (ast.With, ast.AsyncWith)) and any(
                _self_attr(i.context_expr) in locks for i in stmt.items
            ):
                continue
            yield stmt
            children = []
            for s in ast.iter_child_nodes(stmt):
                if isinstance(s, ast.stmt):
                    children.append(s)
                elif isinstance(s, ast.excepthandler):
                    children.extend(s.body)
            yield from self._unguarded_stmts(children, locks)

    def _acts_on(self, body: list, fld: str, locks) -> bool:
        for stmt in self._unguarded_stmts(body, locks):
            for sub in _own_exprs(stmt):
                if isinstance(sub, ast.Subscript) and _self_attr(sub.value) == fld:
                    return True
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in MUTATORS
                    and _self_attr(sub.func.value) == fld
                ):
                    return True
        return False

    def _assigns(self, body: list, fld: str, locks) -> bool:
        for stmt in self._unguarded_stmts(body, locks):
            if isinstance(stmt, ast.Assign) and any(
                _self_attr(t) == fld for t in stmt.targets
            ):
                return True
            if isinstance(stmt, ast.AugAssign) and _self_attr(stmt.target) == fld:
                return True
        return False

    def _emit(self, src, node, fn_qn, detail, msg, out) -> None:
        if src.waived(node.lineno, self.name):
            return
        out.append(self.finding(src, node, fn_qn, detail, msg))

    # -- module singleton lazy-init -------------------------------------------

    def _module_locks(self, tree: ast.Module) -> set[str]:
        locks: set[str] = set()
        for node in tree.body:
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        locks.add(tgt.id)
        return locks

    def _check_lazy_init(self, src, fn, qn, module_locks, out) -> None:
        declared: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                declared.update(node.names)
        if not declared:
            return
        fn_qn = qn.get(fn, fn.name)

        def walk(node, held: bool) -> None:
            for sub in ast.iter_child_nodes(node):
                now_held = held
                if isinstance(sub, (ast.With, ast.AsyncWith)):
                    for item in sub.items:
                        if (
                            isinstance(item.context_expr, ast.Name)
                            and item.context_expr.id in module_locks
                        ):
                            now_held = True
                if isinstance(sub, ast.If) and not now_held:
                    for test in _tests_of(sub.test):
                        name = self._global_none_test(test, declared)
                        if name is not None and self._assigns_global(
                            sub.body, name, module_locks
                        ):
                            self._emit(
                                src, sub, fn_qn,
                                f"unlocked-lazy-init-{name}",
                                f"lazy-init of module singleton `{name}` "
                                "without a lock: two racing callers each "
                                "build an instance and end up holding "
                                "different singletons — guard the "
                                "assignment (double-checked locking) or "
                                "waive with `# analysis: allow(atomicity, "
                                "reason)`", out,
                            )
                walk(sub, now_held)

        walk(fn, False)

    @staticmethod
    def _global_none_test(test: ast.AST, declared: set[str]) -> str | None:
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            t = test.operand
        elif (
            isinstance(test, ast.Compare)
            and len(test.ops) == 1
            and isinstance(test.ops[0], ast.Is)
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None
        ):
            t = test.left
        else:
            return None
        if isinstance(t, ast.Name) and t.id in declared:
            return t.id
        return None

    def _assigns_global(self, body: list, name: str, module_locks) -> bool:
        """True when `name` is assigned in `body` with no module lock held
        (a nested `with LOCK:` around the assignment passes)."""

        def walk(stmts, held: bool) -> bool:
            for stmt in stmts:
                now_held = held
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    for item in stmt.items:
                        if (
                            isinstance(item.context_expr, ast.Name)
                            and item.context_expr.id in module_locks
                        ):
                            now_held = True
                if not now_held and isinstance(stmt, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == name
                    for t in stmt.targets
                ):
                    return True
                children = [
                    s for s in ast.iter_child_nodes(stmt)
                    if isinstance(s, ast.stmt)
                ]
                if children and walk(children, now_held):
                    return True
            return False

        return walk(body, False)
