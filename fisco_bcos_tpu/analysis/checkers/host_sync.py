"""Host-sync discipline around device values.

A value returned by a jitted program (or a ``*_device`` helper) is an
async device future; touching it with ``.item()``,
``.block_until_ready()``, ``np.asarray``/``np.array``, or an implicit
``float()``/``int()`` forces a host round-trip that serializes the
dispatch pipeline. Inside a PIPELINE stage-busy/blocked region or a
plane-dispatch path that sync steals wall from the stage occupancy the
PR 9 observatory measures.

Rule: flag any sync expression applied to a local bound from a call to a
jit-inventory name or a ``*_device``-suffixed callable — and flag
``.item()``/``.block_until_ready()`` on ANYTHING inside a
``with PIPELINE.busy(...)/PIPELINE.blocked(...)`` block (a stage region
must never park on a device future it didn't dispatch).

Intended sync points DO exist — the ops host wrappers materialize device
results at the plane boundary by design. Those sites carry an in-code
``# analysis: allow(host-sync, <why>)`` waiver naming the contract; the
checker keeps every new, unreviewed sync a red diff.
"""

from __future__ import annotations

import ast

from .. import jitmap
from ..core import Checker, Finding, Source, qualnames

_NP_MODULES = {"np", "numpy", "onp", "jnp"}
_SYNC_METHODS = {"item", "block_until_ready"}
_SYNC_BUILTINS = {"float", "int"}


def _called_name(call: ast.Call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _is_np_materialize(call: ast.Call) -> bool:
    fn = call.func
    return (
        isinstance(fn, ast.Attribute)
        and fn.attr in ("asarray", "array")
        and isinstance(fn.value, ast.Name)
        and fn.value.id in _NP_MODULES
    )


def _stage_region_lines(tree: ast.Module) -> set[int]:
    """Line numbers inside ``with PIPELINE.busy(...)/blocked(...)``."""
    lines: set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.With):
            continue
        for item in node.items:
            ctx = item.context_expr
            if (
                isinstance(ctx, ast.Call)
                and isinstance(ctx.func, ast.Attribute)
                and ctx.func.attr in ("busy", "blocked")
                and isinstance(ctx.func.value, ast.Name)
                and ctx.func.value.id == "PIPELINE"
            ):
                end = getattr(node, "end_lineno", node.lineno)
                lines.update(range(node.lineno, end + 1))
                break
    return lines


class HostSyncChecker(Checker):
    name = "host-sync"
    description = (
        "host syncs (.item()/np.asarray/block_until_ready/float()) on "
        "device values serialize the dispatch pipeline — waive only at "
        "intended plane sync points"
    )

    def run(self, sources: list[Source]) -> list[Finding]:
        jits = jitmap.collect(sources)
        jit_names = jitmap.callable_names(jits)
        out: list[Finding] = []
        for src in sources:
            qn = qualnames(src.tree)
            stage_lines = _stage_region_lines(src.tree)
            for fn_node in ast.walk(src.tree):
                if not isinstance(
                    fn_node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                symbol = qn.get(fn_node, fn_node.name)
                device_vars = self._device_vars(fn_node, jit_names)
                for sub in ast.walk(fn_node):
                    if not isinstance(sub, ast.Call):
                        continue
                    hit = self._sync_detail(
                        sub, device_vars, jit_names, stage_lines
                    )
                    if hit is None:
                        continue
                    detail, what = hit
                    if src.waived(sub.lineno, self.name):
                        continue
                    in_stage = sub.lineno in stage_lines
                    where = (
                        "inside a PIPELINE stage region "
                        if in_stage
                        else ""
                    )
                    out.append(
                        self.finding(
                            src,
                            sub,
                            symbol,
                            detail,
                            f"`{what}` forces a host sync on a device "
                            f"value {where}— it parks the dispatch "
                            "pipeline on one future; keep results on "
                            "device or waive the intended plane sync "
                            "point",
                        )
                    )
        return out

    @staticmethod
    def _device_vars(
        fn_node: ast.AST, jit_names: set[str]
    ) -> set[str]:
        """Locals bound (possibly via tuple unpack) from jit/device calls."""
        names: set[str] = set()
        for node in ast.walk(fn_node):
            if not isinstance(node, ast.Assign):
                continue
            val = node.value
            if not isinstance(val, ast.Call):
                continue
            called = _called_name(val)
            if called is None or not (
                called in jit_names or called.endswith("_device")
            ):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
                elif isinstance(tgt, ast.Tuple):
                    names.update(
                        e.id for e in tgt.elts if isinstance(e, ast.Name)
                    )
        return names

    @staticmethod
    def _sync_detail(
        call: ast.Call,
        device_vars: set[str],
        jit_names: set[str],
        stage_lines: set[int],
    ) -> tuple[str, str] | None:
        fn = call.func
        # x.item() / x.block_until_ready()
        if isinstance(fn, ast.Attribute) and fn.attr in _SYNC_METHODS:
            if isinstance(fn.value, ast.Name) and fn.value.id in device_vars:
                return (
                    f"{fn.attr}-{fn.value.id}",
                    f"{fn.value.id}.{fn.attr}()",
                )
            if call.lineno in stage_lines:
                return (f"{fn.attr}-in-stage", f".{fn.attr}()")
            return None
        # np.asarray(x) / np.array(x) on a device value or a direct jit call
        if _is_np_materialize(call) and call.args:
            arg = call.args[0]
            if isinstance(arg, ast.Name) and arg.id in device_vars:
                return (
                    f"{fn.attr}-{arg.id}",
                    f"{fn.value.id}.{fn.attr}({arg.id})",
                )
            if isinstance(arg, ast.Call):
                inner = _called_name(arg)
                if inner is not None and (
                    inner in jit_names or inner.endswith("_device")
                ):
                    return (
                        f"{fn.attr}-{inner}",
                        f"{fn.value.id}.{fn.attr}({inner}(...))",
                    )
            return None
        # float(x) / int(x) — the implicit scalar sync
        if (
            isinstance(fn, ast.Name)
            and fn.id in _SYNC_BUILTINS
            and len(call.args) == 1
            and isinstance(call.args[0], ast.Name)
            and call.args[0].id in device_vars
        ):
            return (
                f"{fn.id}-{call.args[0].id}",
                f"{fn.id}({call.args[0].id})",
            )
        return None
