"""Exception hygiene: broad handlers must observe, not erase.

The reproduction had 10 ``except Exception: pass`` swallows out of ~80
handlers — each one a place where a real failure (a dead metrics registry,
a crashed heartbeat, a failed health callback) vanishes without a log
line, a counter, or a typed narrowing. The rule:

a handler for ``Exception``/``BaseException``/bare ``except`` whose body
neither raises, returns a value, logs (``*.info/warning/error/debug/
exception``), counts a metric (``counter_add``/``note_swallowed``), calls
any handler function, nor assigns state, is a silent swallow — a finding
unless the ``except`` line carries ``# analysis: allow(except-hygiene,
reason)``.

Handlers that do SOMETHING (even ``return None``, or setting a fallback
value) pass: the rule targets erasure, not tolerance. The fix applied
across the package routes these through
:func:`fisco_bcos_tpu.utils.log.note_swallowed`, which debug-logs and
bumps ``fisco_swallowed_errors_total{site=...}`` so operators can see
error mass even at INFO level.
"""

from __future__ import annotations

import ast
import hashlib

from ..core import Checker, Finding, Source, qualnames

BROAD = {"Exception", "BaseException"}


def _guarded_digest(try_node: ast.Try) -> str:
    """Short content hash of the guarded ``try`` body (no line numbers).

    Keys findings to WHAT the handler guards rather than to the handler's
    position: an index-based ``#i`` disambiguator would let a newly added
    swallow earlier in the same symbol inherit an existing baselined key
    (passing the gate) while shifting blame onto the accepted one.
    """
    body = "\n".join(ast.dump(stmt) for stmt in try_node.body)
    return hashlib.sha1(body.encode()).hexdigest()[:8]


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in BROAD for e in t.elts)
    return False


def _is_silent(handler: ast.ExceptHandler) -> bool:
    """True when the body observably does nothing with the error."""
    for stmt in handler.body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(
            stmt.value, ast.Constant
        ):
            continue  # docstring/ellipsis placeholder
        if isinstance(stmt, ast.Continue):
            continue  # loop-shaped pass
        return False
    return True


class ExceptionHygieneChecker(Checker):
    name = "except-hygiene"
    description = (
        "broad except handlers must observe the error (log / count / "
        "narrow / assign fallback) — silent pass/continue erasure fails"
    )

    def run(self, sources: list[Source]) -> list[Finding]:
        out: list[Finding] = []
        for src in sources:
            qn = qualnames(src.tree)
            digests: dict[ast.ExceptHandler, str] = {}
            for node in ast.walk(src.tree):
                if isinstance(node, ast.Try):
                    d = _guarded_digest(node)
                    for handler in node.handlers:
                        digests[handler] = d
            # identical guarded bodies in one symbol (rare) fall back to an
            # occurrence index — order-dependence is then confined to code
            # that is literally indistinguishable anyway
            per_key: dict[tuple[str, str], int] = {}
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if not _is_broad(node) or not _is_silent(node):
                    continue
                symbol = qn.get(node, "")
                if src.waived(node.lineno, self.name):
                    continue
                digest = digests.get(node, "orphan")
                i = per_key.get((symbol, digest), 0)
                per_key[(symbol, digest)] = i + 1
                detail = f"silent-swallow@{digest}" + (f"#{i}" if i else "")
                out.append(
                    self.finding(
                        src,
                        node,
                        symbol,
                        detail,
                        "broad except silently swallows the error — log it, "
                        "count fisco_swallowed_errors_total (utils.log."
                        "note_swallowed), narrow the type, or waive with "
                        "`# analysis: allow(except-hygiene, reason)`",
                    )
                )
        return out
