"""x64/object creep and weak-type widening around traced bodies.

The device kernels are uint32 limb arithmetic end to end: one stray
``float64``/``int64`` inside a traced body silently doubles a buffer and
splits a fused loop, and a weakly-typed Python scalar fed to a jitted
program widens its input signature (a NEW compile per literal dtype).
The jaxpr auditor pins the realized dtype histogram per program in
``tool/jaxpr_baseline.json``; this checker catches the SOURCE of drift
at the AST, before anyone traces.

Rule, inside jit-traced bodies (the :mod:`..jitmap` inventory):

- no ``np.float64``/``jnp.int64``/``uint64``/``complex128``/``object_``
  attribute loads, and no ``"float64"``-style dtype string literals;
- no ``astype(float)`` / ``dtype=float``/``int``/``object`` — Python
  builtin types resolve to x64 under ``jax_enable_x64`` and weak-type
  otherwise, both drift;

and at program boundaries: no bare Python float literals or
``float(...)``/``int(...)`` results as positional args in a CALL to a
jit-inventory name (weak-type widening at the input signature).

Host-side constant prep (``np.uint64`` tables built at import/trace time
outside traced defs) is deliberately out of scope — numpy on host
constants folds at trace time and never reaches a device buffer.
"""

from __future__ import annotations

import ast

from .. import jitmap
from ..core import Checker, Finding, Source, qualnames

_X64_ATTRS = {
    "float64", "int64", "uint64", "complex128", "float_", "object_",
    "longdouble", "float128",
}
_X64_STRINGS = {"float64", "int64", "uint64", "complex128", "object"}
_WEAK_BUILTINS = {"float", "int", "object"}


def _called_name(call: ast.Call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


class DtypeDriftChecker(Checker):
    name = "dtype-drift"
    description = (
        "x64/object dtypes inside traced bodies and weak-type scalars at "
        "program inputs double buffers and fork compiles"
    )

    def run(self, sources: list[Source]) -> list[Finding]:
        jits = jitmap.collect(sources)
        jit_names = jitmap.callable_names(jits)
        out: list[Finding] = []
        for src in sources:
            qn = qualnames(src.tree)
            traced = [j.node for j in jits if j.source is src]
            for body in traced:
                symbol = qn.get(body, body.name)
                out.extend(self._scan_traced(src, body, symbol))
            out.extend(self._scan_boundaries(src, qn, jit_names))
        return out

    def _scan_traced(
        self, src: Source, body: ast.FunctionDef, symbol: str
    ) -> list[Finding]:
        found: list[Finding] = []
        for node in ast.walk(body):
            if (
                isinstance(node, ast.Attribute)
                and node.attr in _X64_ATTRS
                and isinstance(node.ctx, ast.Load)
            ):
                if not src.waived(node.lineno, self.name):
                    found.append(
                        self.finding(
                            src, node, symbol, f"x64-{node.attr}",
                            f"`{node.attr}` inside a traced body — the "
                            "kernels are 32-bit limb planes; an x64 "
                            "buffer doubles bytes and splits fusion",
                        )
                    )
            elif isinstance(node, ast.keyword) and node.arg == "dtype":
                hit = self._dtype_value_drift(node.value)
                if hit and not src.waived(node.value.lineno, self.name):
                    found.append(
                        self.finding(
                            src, node.value, symbol, f"dtype-{hit}",
                            f"dtype={hit} inside a traced body drifts the "
                            "program off its 32-bit plane",
                        )
                    )
            elif isinstance(node, ast.Call):
                fn = node.func
                if (
                    isinstance(fn, ast.Attribute)
                    and fn.attr == "astype"
                    and node.args
                ):
                    hit = self._dtype_value_drift(node.args[0])
                    if hit and not src.waived(node.lineno, self.name):
                        found.append(
                            self.finding(
                                src, node, symbol, f"astype-{hit}",
                                f"astype({hit}) inside a traced body "
                                "drifts the program off its 32-bit plane",
                            )
                        )
        return found

    @staticmethod
    def _dtype_value_drift(value: ast.AST) -> str | None:
        if isinstance(value, ast.Constant) and value.value in _X64_STRINGS:
            return str(value.value)
        if isinstance(value, ast.Name) and value.id in _WEAK_BUILTINS:
            return value.id
        if isinstance(value, ast.Attribute) and value.attr in _X64_ATTRS:
            return value.attr
        return None

    def _scan_boundaries(
        self, src: Source, qn: dict, jit_names: set[str]
    ) -> list[Finding]:
        found: list[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            called = _called_name(node)
            if called not in jit_names:
                continue
            for arg in node.args:
                weak = None
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, float
                ):
                    weak = f"float-literal-{arg.value}"
                elif (
                    isinstance(arg, ast.Call)
                    and isinstance(arg.func, ast.Name)
                    and arg.func.id in ("float", "int")
                ):
                    weak = f"{arg.func.id}-call"
                if weak is None or src.waived(node.lineno, self.name):
                    continue
                found.append(
                    self.finding(
                        src, node, qn.get(node, ""),
                        f"weak-arg-{called}-{weak}",
                        f"weakly-typed scalar fed to jitted `{called}` "
                        "widens its input signature — one extra compile "
                        "per literal dtype; pass a typed array instead",
                    )
                )
        return found
