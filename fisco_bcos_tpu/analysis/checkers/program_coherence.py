"""Program-coherence: every jitted program declares its audit shape, and
nothing pads to a shape the bucket ladder never produces.

Three rules, all against the :mod:`..jitmap` inventory:

- **missing-spec**: a module defining a jit-traced program must carry a
  module-level ``PROGSPEC`` dict with an entry (inputs or an explicit
  skip reason) for every traced qualname — that declaration is what lets
  :mod:`..progaudit` abstract-eval the program without importing guesses,
  and what ``tool/jaxpr_baseline.json`` keys against.
- **stale-spec**: a ``PROGSPEC`` key naming no traced def in its module
  is a leftover from a deleted/renamed program; it would silently drop
  out of the audit.
- **pad-off-ladder**: ``pad_rows(x, LITERAL)`` where the literal is not a
  bucket-ladder rung (powers of two to 2048, then multiples of 2048 —
  mirrored from ``ops/hash_common._bucket`` WITHOUT the
  ``FISCO_TEST_BUCKET`` override, which is a test-only quantization):
  feeding an off-ladder shape compiles a program no warm cache ever
  holds.

Whether the declared shapes actually abstract-eval is checked at
``--jaxpr`` time by the engine (an AST checker cannot trace); a spec
whose shapes fail shows up there as an audit failure, not here.
"""

from __future__ import annotations

import ast

from .. import jitmap
from ..core import Checker, Finding, Source


def _ladder_bucket(n: int) -> int:
    if n <= 1:
        return 1
    if n <= 2048:
        return 1 << (n - 1).bit_length()
    return -(-n // 2048) * 2048


def _progspec_keys(tree: ast.Module) -> set[str] | None:
    """Keys of the module-level PROGSPEC dict; None when absent."""
    for node in tree.body:
        if isinstance(node, ast.Assign):
            targets = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            if "PROGSPEC" in targets and isinstance(node.value, ast.Dict):
                return {
                    k.value
                    for k in node.value.keys
                    if isinstance(k, ast.Constant) and isinstance(k.value, str)
                }
    return None


class ProgramCoherenceChecker(Checker):
    name = "program-coherence"
    description = (
        "every jitted program needs a PROGSPEC audit shape (no stale "
        "keys), and pad targets must sit on the bucket ladder"
    )

    def run(self, sources: list[Source]) -> list[Finding]:
        jits = jitmap.collect(sources)
        out: list[Finding] = []
        by_src: dict[int, list] = {}
        for j in jits:
            by_src.setdefault(id(j.source), []).append(j)
        for src in sources:
            mine = by_src.get(id(src), [])
            if mine:
                out.extend(self._check_specs(src, mine))
            out.extend(self._check_pads(src))
        return out

    def _check_specs(self, src: Source, mine: list) -> list[Finding]:
        found: list[Finding] = []
        keys = _progspec_keys(src.tree)
        traced = {j.qualname for j in mine}
        for j in mine:
            if keys is not None and j.qualname in keys:
                continue
            if src.waived(j.node.lineno, self.name):
                continue
            found.append(
                self.finding(
                    src, j.node, j.qualname,
                    f"missing-spec-{j.qualname}",
                    f"jitted `{j.qualname}` has no PROGSPEC entry — "
                    "declare its audit shapes (or a skip reason) so the "
                    "jaxpr baseline covers it",
                )
            )
        for key in sorted((keys or set()) - traced):
            found.append(
                self.finding(
                    src, src.tree, "PROGSPEC", f"stale-spec-{key}",
                    f"PROGSPEC entry `{key}` names no jit-traced def in "
                    "this module — deleted/renamed program leaves a dead "
                    "audit entry",
                )
            )
        return found

    def _check_pads(self, src: Source) -> list[Finding]:
        found: list[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (
                fn.id if isinstance(fn, ast.Name)
                else fn.attr if isinstance(fn, ast.Attribute) else None
            )
            if name not in ("pad_rows", "_pad_rows") or len(node.args) < 2:
                continue
            target = node.args[1]
            if not (
                isinstance(target, ast.Constant)
                and isinstance(target.value, int)
            ):
                continue
            n = target.value
            if n >= 1 and _ladder_bucket(n) == n:
                continue
            if src.waived(node.lineno, self.name):
                continue
            found.append(
                self.finding(
                    src, node, "", f"pad-off-ladder-{n}",
                    f"pad_rows(..., {n}) pads to a shape the bucket "
                    f"ladder never produces (nearest rung: "
                    f"{_ladder_bucket(max(n, 1))}) — that program misses "
                    "every warm cache",
                )
            )
        return found
