"""progaudit — jaxpr-level static analysis of the device-program inventory.

The AST checkers (:mod:`..checkers`) see Python source and the device
observatory (:mod:`...observability.device`) sees runtime phases; this
package closes the gap in between: it **abstract-evals** every program in
the :mod:`..jitmap` inventory under its declared bucket-ladder shape —
``jax.make_jaxpr`` only, no device, no compile (a tier-1 test pins the
compile ledger at zero entries during an audit) — and derives, per
program:

- a **canonical fingerprint** (:mod:`.fingerprint`): a stable hash of the
  normalized eqn graph (primitive names, shapes, dtypes; invariant to
  variable naming and to jit-wrapper renames), committed to
  ``tool/jaxpr_baseline.json`` and diffed like ``analysis_baseline.json``
  — new AND stale AND changed fingerprints fail, with a per-primitive
  eqn-count explanation for changes;
- a **static cost model** (:mod:`.costmodel`): device-op (flop) estimate,
  input/output/intermediate bytes, and a structural dtype histogram;
- the **fusion-edge report** (:mod:`.fusion`): static producer/consumer
  signatures joined with the DevicePlane's measured dispatch adjacency to
  rank mergeable program pairs by predicted saved transfer bytes — the
  work-list the ROADMAP's fused admission program starts from.

Program shapes come from ``PROGSPEC`` declarations next to the jitted
defs themselves (the `program-coherence` checker enforces that every
inventoried program has one); :mod:`.engine` joins inventory x specs,
runs the audit and owns the baseline diff.

Everything importable here defers ``import jax`` until an audit actually
runs, so :mod:`fisco_bcos_tpu.analysis` keeps its jax-free promise for
the AST-only paths.
"""

from __future__ import annotations

from .engine import (  # noqa: F401
    DEFAULT_JAXPR_BASELINE,
    audit,
    diff_audit,
    inventory_keys,
    load_jaxpr_baseline,
    save_jaxpr_baseline,
)
from .fingerprint import explain_change, fingerprint  # noqa: F401
from .fusion import ADMISSION_CHAIN, fusion_report  # noqa: F401
