"""Canonical jaxpr fingerprints.

A fingerprint is a sha256 over the **normalized** eqn graph of a traced
program: variables are renumbered in first-appearance order (so the hash
is invariant to variable naming and trace ordering accidents), literals
are reduced to (dtype, shape, value digest), avals to (dtype, shape,
weak-type flag), and eqn params are canonicalized recursively — nested
jaxprs (``pjit``/``scan``/``cond`` bodies) fold their own canonical form
in, while compiler bookkeeping params that do not change what the program
computes (shardings, layouts, donation masks, the jit wrapper's ``name``)
are dropped so a rename or a sharding annotation is not a semantic drift.

Alongside the hash, :func:`fingerprint` returns a flat **summary**
(eqn count, primitive histogram, output-dtype histogram) that the
baseline stores next to the hash; when a fingerprint CHANGES,
:func:`explain_change` diffs the stored summary against the fresh one to
say *which* primitives appeared/vanished — a per-eqn explanation instead
of "hash mismatch".

Duck-typed against jax's jaxpr objects (``.jaxpr``, ``.eqns``,
``.invars`` …) so no ``jax.core`` import is needed; jax itself is only
imported by the caller that built the jaxpr.
"""

from __future__ import annotations

import hashlib
import json

# compiler bookkeeping: irrelevant to WHAT the program computes. "name"
# is the jit wrapper's label — renaming a wrapper must not read as
# semantic drift (the baseline key already carries the qualname).
_PARAM_SKIP = {
    "name", "in_shardings", "out_shardings", "in_layouts", "out_layouts",
    "resource_env", "donated_invars", "keep_unused", "inline", "backend",
    "device", "compiler_options_kvs", "jaxpr_id",
}


def _is_jaxpr(v) -> bool:
    return hasattr(v, "eqns") and hasattr(v, "invars")


def _is_closed_jaxpr(v) -> bool:
    return hasattr(v, "jaxpr") and _is_jaxpr(getattr(v, "jaxpr", None))


def _aval_sig(aval) -> list:
    if hasattr(aval, "shape") and hasattr(aval, "dtype"):
        sig = [str(aval.dtype), [int(d) for d in aval.shape]]
        if getattr(aval, "weak_type", False):
            sig.append("weak")
        return sig
    return [type(aval).__name__]


def _literal_sig(lit) -> list:
    import numpy as np

    try:
        arr = np.asarray(lit.val)
        digest = hashlib.sha256(arr.tobytes()).hexdigest()[:12]
        return ["lit", str(arr.dtype), list(arr.shape), digest]
    except Exception:
        return ["lit", repr(lit.val)]


def _canon_param(v):
    if _is_closed_jaxpr(v):
        return {"closed_jaxpr": _canon_jaxpr(v.jaxpr)}
    if _is_jaxpr(v):
        return {"jaxpr": _canon_jaxpr(v)}
    if isinstance(v, (tuple, list)):
        return [_canon_param(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _canon_param(x) for k, x in sorted(v.items())}
    if isinstance(v, (int, float, bool, str)) or v is None:
        return v
    if isinstance(v, type):
        return v.__name__
    # dtypes stringify stably; callables/partials/objects reduce to a
    # stable name — their repr would leak memory addresses into the hash
    name = getattr(v, "__name__", None)
    if name:
        return f"<{name}>"
    if type(v).__module__ in ("numpy", "jax.numpy") or "dtype" in type(
        v
    ).__name__.lower():
        return str(v)
    return f"<{type(v).__name__}>"


def _canon_jaxpr(jaxpr) -> dict:
    ids: dict[int, str] = {}

    def vid(var) -> str:
        key = id(var)
        if key not in ids:
            ids[key] = f"v{len(ids)}"
        return ids[key]

    def atom(a) -> list:
        if hasattr(a, "val"):  # Literal
            return _literal_sig(a)
        return [vid(a)]

    for v in (*getattr(jaxpr, "constvars", ()), *jaxpr.invars):
        vid(v)
    eqns = []
    for eqn in jaxpr.eqns:
        eqns.append(
            {
                "p": eqn.primitive.name,
                "in": [atom(a) for a in eqn.invars],
                "out": [[vid(v)] + _aval_sig(v.aval) for v in eqn.outvars],
                "params": {
                    str(k): _canon_param(v)
                    for k, v in sorted(eqn.params.items())
                    if k not in _PARAM_SKIP
                },
            }
        )
    return {
        "in": [
            _aval_sig(v.aval)
            for v in (*getattr(jaxpr, "constvars", ()), *jaxpr.invars)
        ],
        "out": [atom(a) for a in jaxpr.outvars],
        "eqns": eqns,
    }


def _walk_eqns(jaxpr, prims: dict, dtypes: dict) -> int:
    """Flatten primitive/dtype histograms across nested jaxprs; returns
    the flat eqn count. Structural counts — a scan body counts once, not
    per iteration (the cost model applies trip counts, not this)."""
    n = 0
    for eqn in jaxpr.eqns:
        n += 1
        name = eqn.primitive.name
        prims[name] = prims.get(name, 0) + 1
        for v in eqn.outvars:
            if hasattr(v.aval, "dtype"):
                d = str(v.aval.dtype)
                dtypes[d] = dtypes.get(d, 0) + 1
        for pv in eqn.params.values():
            for sub in _sub_jaxprs(pv):
                n += _walk_eqns(sub, prims, dtypes)
    return n


def _sub_jaxprs(v):
    if _is_closed_jaxpr(v):
        yield v.jaxpr
    elif _is_jaxpr(v):
        yield v
    elif isinstance(v, (tuple, list)):
        for x in v:
            yield from _sub_jaxprs(x)


def fingerprint(closed_jaxpr) -> tuple[str, dict]:
    """(stable hash, summary) for a traced program.

    Accepts the ``jax.make_jaxpr`` result (ClosedJaxpr) or a raw jaxpr.
    The summary — ``{"eqns", "primitives", "dtypes"}`` with histograms
    flattened through nested jaxprs — is what the baseline stores to
    explain future changes.
    """
    jaxpr = closed_jaxpr.jaxpr if _is_closed_jaxpr(closed_jaxpr) else closed_jaxpr
    canon = _canon_jaxpr(jaxpr)
    blob = json.dumps(canon, sort_keys=True, separators=(",", ":"))
    digest = hashlib.sha256(blob.encode()).hexdigest()[:16]
    prims: dict[str, int] = {}
    dtypes: dict[str, int] = {}
    n = _walk_eqns(jaxpr, prims, dtypes)
    return digest, {"eqns": n, "primitives": prims, "dtypes": dtypes}


def explain_change(old_summary: dict, new_summary: dict) -> str:
    """Human-readable per-eqn diff between two fingerprint summaries:
    which primitives were added/removed/recounted, how the flat eqn count
    and output-dtype mix moved."""
    parts: list[str] = []
    old_n = old_summary.get("eqns", 0)
    new_n = new_summary.get("eqns", 0)
    if old_n != new_n:
        parts.append(f"eqns {old_n} -> {new_n}")
    for label, field in (("prim", "primitives"), ("dtype", "dtypes")):
        old_h = old_summary.get(field, {}) or {}
        new_h = new_summary.get(field, {}) or {}
        for key in sorted(set(old_h) | set(new_h)):
            a, b = old_h.get(key, 0), new_h.get(key, 0)
            if a != b:
                delta = b - a
                parts.append(f"{label} {key} {a} -> {b} ({delta:+d})")
    if not parts:
        parts.append(
            "same primitive/dtype mix — shapes, literals or params moved "
            "(re-audit with --update-jaxpr-baseline after review)"
        )
    return "; ".join(parts)
