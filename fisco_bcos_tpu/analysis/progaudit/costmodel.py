"""Static cost model over an abstract-eval'd jaxpr.

Estimates, WITHOUT executing anything:

- ``flops``: device-op count — one op per output element for elementwise
  primitives, ``2·N·K`` for ``dot_general`` (from its dimension numbers),
  input-size for reductions. The kernels here are integer limb
  arithmetic, so "flop" reads as "device lane op"; the number is a
  RELATIVE ranking signal for the fusion report, not a wall-clock
  prediction.
- ``bytes_in`` / ``bytes_out``: program boundary transfer — what a fused
  neighbor would save by never round-tripping through the host.
- ``bytes_intermediate``: sum of every eqn's output aval bytes, with
  ``scan`` bodies multiplied by their trip count — the live-buffer
  pressure a fusion would add to one program.
- ``dtypes``: structural output-dtype histogram (each eqn counted once,
  trip counts NOT applied) — pinned in the baseline so x64 creep inside
  a traced body is a red diff even when the eqn count is unchanged.

Deliberately ignored: fusion XLA already does within one program,
layout/padding overhead, and ``while_loop`` trip counts (unknowable
statically — bodies count once; the repo's kernels use ``scan`` with
static lengths everywhere it matters). ``cond`` branches count at the
max across branches.
"""

from __future__ import annotations

from .fingerprint import _is_closed_jaxpr, _sub_jaxprs


def _aval_bytes(aval) -> int:
    if not (hasattr(aval, "shape") and hasattr(aval, "dtype")):
        return 0
    n = 1
    for d in aval.shape:
        n *= int(d)
    return n * int(aval.dtype.itemsize)


def _out_elems(eqn) -> int:
    total = 0
    for v in eqn.outvars:
        if hasattr(v.aval, "shape"):
            n = 1
            for d in v.aval.shape:
                n *= int(d)
            total += n
    return total


def _in_elems(eqn) -> int:
    total = 0
    for a in eqn.invars:
        aval = getattr(a, "aval", None)
        if aval is not None and hasattr(aval, "shape"):
            n = 1
            for d in aval.shape:
                n *= int(d)
            total += n
    return total


_REDUCERS = {
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "reduce_xor", "argmax", "argmin", "cumsum", "cumlogsumexp",
    "cummax", "cummin", "cumprod", "sort",
}
# structural/zero-cost: data movement the compiler folds into layouts
_FREE = {
    "reshape", "squeeze", "broadcast_in_dim", "convert_element_type",
    "transpose", "slice", "concatenate", "pad", "rev", "copy",
    "stop_gradient", "bitcast_convert_type",
}


def _eqn_flops(eqn) -> int:
    name = eqn.primitive.name
    if name in _FREE:
        return 0
    if name == "dot_general":
        dn = eqn.params.get("dimension_numbers")
        contract = dn[0][0] if dn else ()
        lhs = eqn.invars[0].aval
        k = 1
        for axis in contract:
            k *= int(lhs.shape[axis])
        return 2 * k * _out_elems(eqn)
    if name in _REDUCERS:
        return _in_elems(eqn)
    return _out_elems(eqn)


def _trip_count(eqn) -> int:
    if eqn.primitive.name == "scan":
        return max(int(eqn.params.get("length", 1)), 1)
    return 1


def _walk(jaxpr, mult: int, acc: dict) -> None:
    for eqn in jaxpr.eqns:
        trip = _trip_count(eqn)
        subs = [
            s for pv in eqn.params.values() for s in _sub_jaxprs(pv)
        ]
        if eqn.primitive.name == "cond" and subs:
            # branches are alternatives: charge the worst one
            costs = []
            for s in subs:
                sub_acc = {"flops": 0, "bytes_intermediate": 0}
                _walk(s, mult, sub_acc)
                costs.append(sub_acc)
            worst = max(costs, key=lambda c: c["flops"])
            acc["flops"] += worst["flops"]
            acc["bytes_intermediate"] += worst["bytes_intermediate"]
        elif subs:
            for s in subs:
                _walk(s, mult * trip, acc)
        else:
            acc["flops"] += _eqn_flops(eqn) * mult
        acc["bytes_intermediate"] += (
            sum(_aval_bytes(v.aval) for v in eqn.outvars) * mult * trip
        )


def cost(closed_jaxpr) -> dict:
    """Static cost estimate for one traced program (see module doc)."""
    jaxpr = (
        closed_jaxpr.jaxpr if _is_closed_jaxpr(closed_jaxpr) else closed_jaxpr
    )
    acc = {"flops": 0, "bytes_intermediate": 0}
    _walk(jaxpr, 1, acc)
    out_bytes = 0
    for a in jaxpr.outvars:
        aval = getattr(a, "aval", None)
        if aval is not None:
            out_bytes += _aval_bytes(aval)
    return {
        "flops": int(acc["flops"]),
        "bytes_in": sum(_aval_bytes(v.aval) for v in jaxpr.invars),
        "bytes_out": int(out_bytes),
        "bytes_intermediate": int(acc["bytes_intermediate"]),
    }
