"""The audit engine: inventory × PROGSPEC → traced, fingerprinted,
cost-modeled programs, plus the ``tool/jaxpr_baseline.json`` diff.

Every module that defines a jitted program also declares a ``PROGSPEC``
dict next to it (enforced by the `program-coherence` checker): traced-def
qualname → either an input-shape declaration or a skip reason::

    PROGSPEC = {
        "keccak256_blocks": {
            "bucket": 256,
            "inputs": lambda b: [((b, 1, 17, 2), "uint32"), ((b,), "int32")],
        },
        "_device_root_fn.run": {
            "bucket": 256,
            "call": lambda b: _device_root_fn(b, 16),
            "inputs": lambda b: [((b, 32), "uint8")],
        },
        "maybe.run": {"skip": "pallas kernels are TPU-only"},
    }

``bucket`` is the canonical batch the program is audited at — an explicit
ladder rung, deliberately independent of ``FISCO_TEST_BUCKET`` so the
committed fingerprints do not depend on the environment. ``attr`` names
the module attribute to trace when it differs from the qualname; ``call``
builds the callable (program makers like ``merkle._device_root_fn``).
``slow: True`` marks programs whose *trace* alone is minutes-class (the
BLS pairing Miller loop unrolls ~100k limb eqns): they are fingerprinted
into the baseline by ``--update-jaxpr-baseline`` / ``--jaxpr-full`` and
skipped by default audits, which still verify their baseline PRESENCE
via the coverage check.

The audit never executes device code: ``jax.make_jaxpr`` over
``ShapeDtypeStruct`` inputs only. tests/test_progaudit.py pins the
compile ledger at zero entries during an audit.
"""

from __future__ import annotations

import importlib
import json
import os

from ..core import REPO_ROOT
from .fingerprint import explain_change, fingerprint

DEFAULT_JAXPR_BASELINE = os.path.join(REPO_ROOT, "tool", "jaxpr_baseline.json")


def _key(rec: dict) -> str:
    return f"{rec['file']}:{rec['qualname']}"


def inventory_keys(root: str | None = None) -> list[str]:
    """Sorted ``file:qualname`` keys of the FULL jitmap inventory — the
    universe the baseline must cover and may not exceed (stale guard).
    Pure AST: no imports of the subject modules, no jax."""
    from .. import jitmap

    return sorted(_key(rec) for rec in jitmap.inventory(root))


def _module_specs(relpath: str) -> tuple[dict, object]:
    mod_name = relpath[:-3].replace("/", ".")
    module = importlib.import_module(mod_name)
    return getattr(module, "PROGSPEC", {}) or {}, module


def _resolve_callable(module, qualname: str, spec: dict, bucket: int):
    if "call" in spec:
        return spec["call"](bucket)
    attr = spec.get("attr", qualname)
    fn = module
    for part in attr.split("."):
        fn = getattr(fn, part)
    return fn


def audit(
    root: str | None = None,
    programs: list[str] | None = None,
    include_slow: bool = False,
) -> dict:
    """Abstract-eval the inventory (or the ``programs`` subset, matched by
    ``file:qualname`` key or bare qualname) under each program's declared
    bucket. Returns::

        {"programs": {key: entry}, "failures": [{key, error}],
         "missing_spec": [key...], "inventory": [all keys],
         "not_traced": [keys skipped by slow/subset filtering]}

    A traced entry carries fingerprint + summary histograms + cost; a
    spec-skipped entry carries its reason. ``inventory`` always lists the
    FULL key set so the stale/coverage checks work on subset audits.
    """
    import jax

    from .. import jitmap
    from .costmodel import cost

    records = jitmap.inventory(root)
    all_keys = sorted(_key(r) for r in records)
    wanted = set(programs) if programs is not None else None

    out: dict = {
        "programs": {},
        "failures": [],
        "missing_spec": [],
        "inventory": all_keys,
        "not_traced": [],
    }
    spec_cache: dict[str, tuple[dict, object]] = {}
    for rec in sorted(records, key=_key):
        key = _key(rec)
        if wanted is not None and key not in wanted and rec[
            "qualname"
        ] not in wanted:
            out["not_traced"].append(key)
            continue
        relpath = rec["file"]
        if relpath not in spec_cache:
            try:
                spec_cache[relpath] = _module_specs(relpath)
            except Exception as e:
                out["failures"].append(
                    {"key": key, "error": f"import failed: {e}"}
                )
                continue
        specs, module = spec_cache[relpath]
        spec = specs.get(rec["qualname"])
        if spec is None:
            out["missing_spec"].append(key)
            continue
        if "skip" in spec:
            out["programs"][key] = {"skip": spec["skip"]}
            continue
        if spec.get("slow") and not include_slow and (
            wanted is None or key not in wanted
        ):
            out["not_traced"].append(key)
            continue
        bucket = int(spec["bucket"])
        try:
            fn = _resolve_callable(module, rec["qualname"], spec, bucket)
            avals = [
                jax.ShapeDtypeStruct(tuple(shape), dtype)
                for shape, dtype in spec["inputs"](bucket)
            ]
            closed = jax.make_jaxpr(fn)(*avals)
        except Exception as e:
            out["failures"].append(
                {
                    "key": key,
                    "error": f"abstract eval failed at bucket {bucket}: "
                    f"{type(e).__name__}: {e}",
                }
            )
            continue
        digest, summary = fingerprint(closed)
        entry = {"bucket": bucket, "fingerprint": digest}
        entry.update(summary)
        entry.update(cost(closed))
        if spec.get("slow"):
            entry["slow"] = True
        out["programs"][key] = entry
    return out


# -- baseline ---------------------------------------------------------------


def load_jaxpr_baseline(path: str | None = None) -> dict:
    path = path or DEFAULT_JAXPR_BASELINE
    if not os.path.exists(path):
        return {"programs": {}}
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def save_jaxpr_baseline(result: dict, path: str | None = None) -> None:
    path = path or DEFAULT_JAXPR_BASELINE
    data = {
        "_comment": "Canonical jaxpr fingerprints + static costs per "
        "inventoried device program (see docs/static_analysis.md). "
        "Regenerate with: python -m fisco_bcos_tpu.analysis "
        "--update-jaxpr-baseline (minutes-class: traces the BLS pairing "
        "programs). Review the diff — a changed fingerprint is a changed "
        "program.",
        "programs": {
            k: result["programs"][k] for k in sorted(result["programs"])
        },
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=False)
        f.write("\n")


def diff_audit(result: dict, baseline: dict) -> dict:
    """Audit result × baseline → the failure surface, all four ways:

    - ``new``: audited program with no baseline entry;
    - ``stale``: baseline entry whose program left the inventory (the
      stale-key guard — computed against the FULL inventory, so subset
      audits still catch deletions);
    - ``changed``: fingerprint / bucket / skip-status / dtype-histogram
      drift, each with a per-primitive explanation;
    - ``missing``: inventory program absent from the baseline (coverage
      gap — every program must be fingerprinted, slow ones included);

    plus the audit's own ``failures`` (abstract-eval errors) and
    ``missing_spec``. ``ok`` is True only when every list is empty.
    """
    base_progs = baseline.get("programs", {})
    inv = set(result["inventory"])
    audited = result["programs"]
    new = sorted(k for k in audited if k not in base_progs)
    stale = sorted(k for k in base_progs if k not in inv)
    missing = sorted(k for k in inv if k not in base_progs)
    changed: list[dict] = []
    for key in sorted(set(audited) & set(base_progs)):
        cur, old = audited[key], base_progs[key]
        if ("skip" in cur) != ("skip" in old):
            changed.append(
                {
                    "key": key,
                    "explanation": f"skip status changed: "
                    f"{old.get('skip')!r} -> {cur.get('skip')!r}",
                }
            )
            continue
        if "skip" in cur:
            continue
        if cur.get("bucket") != old.get("bucket"):
            changed.append(
                {
                    "key": key,
                    "explanation": f"audit bucket moved "
                    f"{old.get('bucket')} -> {cur.get('bucket')}",
                }
            )
        elif cur["fingerprint"] != old.get("fingerprint"):
            changed.append(
                {
                    "key": key,
                    "explanation": "fingerprint "
                    f"{old.get('fingerprint')} -> {cur['fingerprint']}: "
                    + explain_change(old, cur),
                }
            )
        elif cur.get("dtypes") != old.get("dtypes"):
            # unreachable when fingerprints match (dtypes hash in), but
            # the pin is explicit: histogram drift names itself
            changed.append(
                {
                    "key": key,
                    "explanation": "dtype histogram drift: "
                    + explain_change(old, cur),
                }
            )
    return {
        "ok": not (
            new or stale or missing or changed or result["failures"]
            or result["missing_spec"]
        ),
        "new": new,
        "stale": stale,
        "missing": missing,
        "changed": changed,
        "failures": result["failures"],
        "missing_spec": result["missing_spec"],
    }
