"""Fusion-edge report: which adjacent programs are worth merging.

Joins two sources:

- **static** producer/consumer signatures from the jaxpr baseline (each
  program's boundary ``bytes_in``/``bytes_out`` — what a merge would stop
  round-tripping through the host), plus the ADMISSION_CHAIN catalog: the
  dispatch sequence the DevicePlane runs per admission batch today
  (keccak digests → secp256k1 recover → secp256k1 verify → host dedup
  key), which is exactly the ROADMAP's fused-admission work-list;
- **measured** dispatch adjacency from the device observatory's ledger
  (``CompileLedger.adjacency()``: back-to-back op pairs counted at
  ``device_span`` exit and DevicePlane dispatch), when a live ledger or a
  saved ``bench_telemetry.*.device.json`` provides one — measured counts
  weight the static edges by how often they actually ran in the flood.

Per pair, the predicted saving is ``min(producer bytes_out, consumer
bytes_in)`` — the largest boundary transfer a merge can possibly remove
(the true overlap needs argument-level matching; this upper bound ranks
pairs the same way) — times the dispatch count, plus one saved dispatch
per occurrence. Rows sort by predicted total saved bytes.
"""

from __future__ import annotations

_KECCAK = "fisco_bcos_tpu/ops/keccak.py:keccak256_blocks"
_RECOVER = "fisco_bcos_tpu/ops/secp256k1.py:_recover_xla"
_VERIFY = "fisco_bcos_tpu/ops/secp256k1.py:_verify_xla"
_ADMISSION = "fisco_bcos_tpu/crypto/admission.py:_admission_packed"

# device_span / DevicePlane op label -> baseline program key. Plane labels
# ("hash.<Hasher>", "verify.<scheme>") and wrapper labels ("keccak256",
# "secp256k1_verify") both appear in adjacency streams.
OP_PROGRAMS = {
    "keccak256": _KECCAK,
    "hash.keccak256": _KECCAK,
    "sha256": "fisco_bcos_tpu/ops/sha256.py:sha256_blocks",
    "hash.sha256": "fisco_bcos_tpu/ops/sha256.py:sha256_blocks",
    "sm3": "fisco_bcos_tpu/ops/sm3.py:sm3_blocks",
    "hash.sm3": "fisco_bcos_tpu/ops/sm3.py:sm3_blocks",
    "poseidon": "fisco_bcos_tpu/ops/poseidon.py:poseidon_blocks",
    "hash.poseidon": "fisco_bcos_tpu/ops/poseidon.py:poseidon_blocks",
    "secp256k1_verify": _VERIFY,
    "verify.secp256k1": _VERIFY,
    "secp256k1_recover": _RECOVER,
    "recover.secp256k1": _RECOVER,
    "sm2_verify": "fisco_bcos_tpu/ops/sm2.py:_verify_xla",
    "verify.sm2": "fisco_bcos_tpu/ops/sm2.py:_verify_xla",
    "ed25519_verify": "fisco_bcos_tpu/ops/ed25519.py:_verify_xla",
    "verify.ed25519": "fisco_bcos_tpu/ops/ed25519.py:_verify_xla",
    "sender_address": "fisco_bcos_tpu/ops/address.py:sender_address_device",
    "merkle_root": "fisco_bcos_tpu/ops/merkle.py:_device_root_fn.run",
    "merkle_tree": "fisco_bcos_tpu/ops/merkle.py:_device_root_fn.run",
    "merkle_tree.keccak256": "fisco_bcos_tpu/ops/merkle.py:_device_root_fn.run",
    "admission": _ADMISSION,
    "admission_native": _ADMISSION,
    "admission_sharded": _ADMISSION,
}

# dedup is host code (txpool seen-set over the digest), not a program:
# model it as a pseudo-consumer whose bytes_in is the digest column it
# pulls off the device per lane.
_DEDUP = "host:dedup_key"
_DEDUP_BYTES_PER_LANE = 32  # keccak256 digest

# the per-batch dispatch sequence of today's UNFUSED admission path — the
# chain the ROADMAP's fused admission program collapses into one dispatch
ADMISSION_CHAIN = ("keccak256", "secp256k1_recover", "secp256k1_verify",
                   "dedup_key")


def _program_for(op: str):
    if op == "dedup_key":
        return _DEDUP
    return OP_PROGRAMS.get(op)


def _bytes(baseline_progs: dict, prog_key: str, field: str, bucket_hint: int):
    if prog_key == _DEDUP:
        return _DEDUP_BYTES_PER_LANE * bucket_hint if field == "bytes_in" else 0
    entry = baseline_progs.get(prog_key)
    if not entry or "skip" in entry:
        return None
    return entry.get(field)


def fusion_report(
    baseline: dict,
    adjacency: dict | None = None,
    top: int | None = None,
) -> dict:
    """Ranked mergeable program pairs (see module doc).

    ``baseline`` is the loaded ``tool/jaxpr_baseline.json``; ``adjacency``
    maps ``"op_a->op_b"`` to a measured dispatch count (from
    ``CompileLedger.adjacency()`` or a saved device artifact). The static
    ADMISSION_CHAIN edges are always present — with count 1 when the
    flood has not been measured — so the report is actionable from the
    committed baseline alone.
    """
    progs = baseline.get("programs", {})
    # op-pair -> measured count; seed the static chain at count>=1
    pairs: dict[tuple[str, str], dict] = {}
    for a, b in zip(ADMISSION_CHAIN, ADMISSION_CHAIN[1:]):
        pairs[(a, b)] = {"count": 1, "source": "static-chain"}
    for edge, count in (adjacency or {}).items():
        if "->" not in edge:
            continue
        a, b = edge.split("->", 1)
        if _program_for(a) is None or _program_for(b) is None:
            continue
        rec = pairs.get((a, b))
        if rec is None:
            pairs[(a, b)] = {"count": int(count), "source": "measured"}
        else:
            rec["count"] = max(int(count), rec["count"])
            rec["source"] = "static-chain+measured"

    rows: list[dict] = []
    for (a, b), rec in pairs.items():
        pa, pb = _program_for(a), _program_for(b)
        entry_a = progs.get(pa, {}) if pa != _DEDUP else {}
        bucket = entry_a.get("bucket", 256)
        out_a = _bytes(progs, pa, "bytes_out", bucket)
        in_b = _bytes(progs, pb, "bytes_in", bucket)
        if out_a is None or in_b is None:
            continue  # program not in the baseline (yet) — nothing to rank
        saved = min(out_a, in_b)
        rows.append(
            {
                "producer": a,
                "consumer": b,
                "producer_program": pa,
                "consumer_program": pb,
                "count": rec["count"],
                "source": rec["source"],
                "saved_bytes_per_dispatch": saved,
                "predicted_saved_bytes": saved * rec["count"],
                "dispatches_saved": rec["count"],
            }
        )
    rows.sort(
        key=lambda r: (-r["predicted_saved_bytes"], r["producer"],
                       r["consumer"])
    )
    if top is not None:
        rows = rows[:top]
    chain_rows = [
        r for r in rows
        if (r["producer"], r["consumer"])
        in set(zip(ADMISSION_CHAIN, ADMISSION_CHAIN[1:]))
    ]
    return {
        "pairs": rows,
        "admission_chain": {
            "ops": list(ADMISSION_CHAIN),
            "edges": chain_rows,
            "predicted_saved_bytes": sum(
                r["predicted_saved_bytes"] for r in chain_rows
            ),
            "dispatches_collapsed": len(ADMISSION_CHAIN) - 1,
        },
    }
