"""Interleaving harnesses — small concurrent scenarios over the REAL hot
classes, driven by the :mod:`.interleave` explorer.

Each harness builds the subsystem's real objects (stub collaborators, no
background threads — the determinism contract), runs 2–3 workers through
a genuinely contended sequence, and asserts the invariant that a lost
update / torn sequence would break. The explorer preempts at every lock
edge and watched-field access, so the schedules these harnesses survive
include exactly the interleavings production would need OS-scheduler bad
luck to hit.

The real harnesses (``HARNESSES``) ride ``tool/check_races.py``'s
seeded sweep; :class:`RacyCounterHarness` is the *injected race* — the
canary proving the explorer actually finds and shrinks a data race (it
must FAIL; the suite asserts it does within a bounded seed budget).
"""

from __future__ import annotations

import hashlib
import threading


# -- injected fixture race ----------------------------------------------------


class _RacyCounter:
    """The textbook lost update: read and write with no lock (the lock
    exists and is deliberately unused — raceguard sees the empty lockset,
    the check sees the lost increment)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def inc_racy(self) -> None:
        v = self.value  # preemption here is the lost-update window
        # analysis: allow(guarded-state, the injected race IS the fixture)
        self.value = v + 1

    def inc_guarded(self) -> None:
        with self._lock:
            v = self.value
            self.value = v + 1


class RacyCounterHarness:
    name = "racy-counter"

    def __init__(self, guarded: bool = False):
        self.guarded = guarded
        self.watch = [(_RacyCounter, ("value",))]

    def setup(self):
        return {"counter": _RacyCounter()}

    def threads(self, ctx):
        c = ctx["counter"]
        fn = c.inc_guarded if self.guarded else c.inc_racy

        def worker():
            fn()
            fn()

        return [("t1", worker), ("t2", worker)]

    def check(self, ctx):
        got = ctx["counter"].value
        assert got == 4, f"lost update: counter={got}, expected 4"


# -- DevicePlane coalescer ----------------------------------------------------


class DevicePlaneHarness:
    """Two submitters race the queue while a drainer runs the scheduler's
    pick/dispatch sequence — the stats counters, pending map and futures
    must stay coherent under any interleaving."""

    name = "device-plane"

    def __init__(self):
        from ..device.plane import DevicePlane

        self.watch = [(DevicePlane, (
            "requests", "dispatches", "merged_requests", "items", "_busy",
        ))]

    def setup(self):
        from ..device.plane import DevicePlane

        plane = DevicePlane(
            window_ms=0, high_water=64, starvation_ms=1e9, autostart=False
        )
        return {"plane": plane, "futs": []}

    def threads(self, ctx):
        import time

        plane = ctx["plane"]
        futs = ctx["futs"]

        def exec_fn(reqs):
            return [r.n for r in reqs]

        def submitter(n):
            def run():
                futs.append((n, plane.submit("verify", None, n, exec_fn)))

            return run

        def drainer():
            for _ in range(200):
                done = [f.done() for _, f in list(futs)]
                if len(done) == 2 and all(done):
                    return
                with plane._cv:
                    picked = plane._pick_ready_locked(time.perf_counter())
                if picked is not None:
                    op, reqs, deferred = picked
                    plane._note_deferred(op, deferred)
                    plane._dispatch(op, reqs)

        return [("sub1", submitter(1)), ("sub2", submitter(2)),
                ("drain", drainer)]

    def check(self, ctx):
        plane = ctx["plane"]
        futs = ctx["futs"]
        assert len(futs) == 2, f"submissions lost: {len(futs)}"
        for n, f in futs:
            assert f.done(), f"future for n={n} never resolved"
            assert f.result(timeout=0) == n, "result misrouted across slices"
        st = plane.stats()
        assert st["requests"] == 2 and st["items"] == 3, st
        assert st["queue_depth"] == 0, st
        assert 1 <= st["dispatches"] <= 2, st


# -- ProofPlane singleflight --------------------------------------------------


class _FakeReceipt:
    def __init__(self, number):
        self.block_number = number


class _FakeProofLedger:
    def __init__(self, tx_hashes):
        self.txs = list(tx_hashes)
        self.alive = True

    def receipt_by_hash(self, h):
        return _FakeReceipt(1) if self.alive and h in self.txs else None

    def block_hash_by_number(self, number):
        return b"B" * 32 if self.alive and number == 1 else None

    def tx_hashes_by_number(self, number):
        return list(self.txs) if self.alive and number == 1 else []


class _FakeTree:
    def __init__(self, leaves):
        self.levels = [list(leaves), [hashlib.sha256(b"".join(leaves)).digest()]]
        self.n = len(leaves)
        self.width = max(len(leaves), 2)


class _FakeProofSuite:
    def merkle_tree(self, arr):
        return _FakeTree([bytes(row) for row in arr])


class ProofPlaneHarness:
    """Concurrent cache misses for one height must coalesce on the
    singleflight future while an invalidator races evictions — every
    caller still gets a proof for the live identity, exactly one build
    per generation, and the hit/miss ledger stays consistent."""

    name = "proof-singleflight"

    def __init__(self):
        from ..proofs.plane import ProofPlane

        self.watch = [(ProofPlane, (
            "requests", "hits", "misses", "builds_lazy", "coalesced_builds",
        ))]

    def setup(self):
        from ..proofs.plane import ProofPlane

        h1, h2 = b"\x01" * 32, b"\x02" * 32
        ledger = _FakeProofLedger([h1, h2])
        plane = ProofPlane(ledger, _FakeProofSuite(), capacity=4)
        return {"plane": plane, "hashes": (h1, h2), "out": {}}

    def threads(self, ctx):
        plane = ctx["plane"]
        h1, h2 = ctx["hashes"]
        out = ctx["out"]

        def reader(name, h):
            def run():
                out[name] = plane.proof_batch([h])

            return run

        def invalidator():
            plane.invalidate(1, reason="rollback")

        return [("r1", reader("r1", h1)), ("r2", reader("r2", h2)),
                ("inval", invalidator)]

    def check(self, ctx):
        plane = ctx["plane"]
        out = ctx["out"]
        assert set(out) == {"r1", "r2"}, f"readers lost: {sorted(out)}"
        for name, expect_idx in (("r1", 0), ("r2", 1)):
            res = out[name][0]
            assert res is not None, f"{name}: proof missing for a live height"
            number, items, idx, n = res
            assert number == 1 and idx == expect_idx and n == 2, res
        st = plane.stats()
        assert st["hits"] + st["misses"] == st["requests"], st
        assert st["builds_lazy"] >= 1, st
        # generations: at most one build per eviction epoch (initial +
        # post-invalidate), never one per caller
        assert st["builds_lazy"] <= 2, st


# -- AdmissionQuotas strikes --------------------------------------------------


class AdmissionQuotasHarness:
    """Two sources of strikes race the demotion edge while a reader takes
    snapshots — strikes must not be lost (two strikes at limit 2 ⇒
    demoted), grants must match the bucket, and the shed ledger adds up."""

    name = "admission-quotas"

    def __init__(self):
        from ..txpool.quota import AdmissionQuotas

        self.watch = [(AdmissionQuotas, ("_groups",))]

    def setup(self):
        from ..txpool.quota import AdmissionQuotas

        quotas = AdmissionQuotas(
            default_rate=1000.0, default_burst=1000.0, strike_limit=2,
            strike_window_s=600.0, demote_s=600.0,
        )
        return {"q": quotas, "granted": []}

    def threads(self, ctx):
        q = ctx["q"]
        granted = ctx["granted"]

        def striker():
            granted.append(q.try_admit("g", 5))
            q.note_invalid("g", "spammer", 3)

        def reader():
            q.demoted("g", "spammer")
            q.snapshot()
            q.count_demoted_drop("g", 2)

        return [("s1", striker), ("s2", striker), ("read", reader)]

    def check(self, ctx):
        q = ctx["q"]
        assert sum(ctx["granted"]) == 10, ctx["granted"]
        assert q.demoted("g", "spammer"), "strike lost: source not demoted"
        snap = q.snapshot()["g"]
        assert snap["demote_drops"] == 2, snap
        assert snap["demoted_sources"] == ["spammer"], snap


# -- Scheduler commit markers -------------------------------------------------


class _FakeSchedHeader:
    def __init__(self, number):
        self.number = number
        self.state_root = b"\x00" * 32
        self.txs_root = b"\x00" * 32
        self.receipts_root = b"\x00" * 32

    def hash(self, _suite):
        return b"H%031d" % self.number

    def clear_hash_cache(self):
        pass


class _FakeSchedBlock:
    def __init__(self, header):
        self.header = header
        self.transactions = []
        self.tx_metadata = []
        self.receipts = []

    def tx_hashes(self, _suite):
        return []


class _FakeSchedLedger:
    def __init__(self):
        self.height = 0

    def block_number(self):
        return self.height

    def prewrite_block(self, block, writes):
        pass


class _FakeSchedExecutor:
    def __init__(self, ledger):
        self._ledger = ledger

    def prepare(self, params, extra_writes=None):
        pass

    def commit(self, params):
        self._ledger.height = params.number


class _InlineNotify:
    """Stands in for the commit-notify Worker: listeners run synchronously
    on the committing worker (no unmanaged thread may race a schedule)."""

    def start(self):
        pass

    def stop(self):
        pass

    def post(self, fn):
        fn()


class SchedulerHarness:
    """Two committers and a storage-term switcher race the in-flight
    commit marker and its condition variable — commits must land in
    height order, the marker must never leak, and switch_term must wait
    out (never deadlock against) an in-flight 2PC."""

    name = "scheduler-commit"

    def __init__(self):
        from ..scheduler.scheduler import Scheduler

        self.watch = [(Scheduler, ("term", "_committing_thread"))]

    def setup(self):
        from ..scheduler.scheduler import ExecutedBlock, Scheduler

        ledger = _FakeSchedLedger()
        executor = _FakeSchedExecutor(ledger)
        sched = Scheduler(
            executor, ledger, backend=None, suite=None,
            notify_worker=_InlineNotify(),
        )
        for n in (1, 2):
            header = _FakeSchedHeader(n)
            sched._executed[n] = ExecutedBlock(
                header, _FakeSchedBlock(header), tx_hashes=()
            )
        committed: list[int] = []
        sched.on_committed.append(lambda n, _b: committed.append(n))
        return {"sched": sched, "ledger": ledger, "committed": committed}

    def threads(self, ctx):
        from ..scheduler.scheduler import SchedulerError

        sched = ctx["sched"]

        def committer(number):
            header = _FakeSchedHeader(number)

            def run():
                for _ in range(50):
                    try:
                        sched.commit_block(header)
                        return
                    except SchedulerError:
                        # out of order (predecessor not booked) or dropped
                        # by a term switch: retry / give up respectively
                        if number not in sched._executed:
                            return
                return

            return run

        def switcher():
            sched.switch_term()

        return [("c1", committer(1)), ("c2", committer(2)),
                ("switch", switcher)]

    def check(self, ctx):
        sched = ctx["sched"]
        committed = ctx["committed"]
        assert sched.term == 1, f"term switch lost: {sched.term}"
        assert not sched._committing, f"marker leaked: {sched._committing}"
        assert sched._committing_thread is None, "committer identity leaked"
        # commits that happened landed in height order, and the ledger head
        # equals the highest booked height (nothing torn by the switch)
        assert committed == sorted(committed), committed
        assert ctx["ledger"].height == (committed[-1] if committed else 0)


# -- Pipelined commit: rollback edges ----------------------------------------


class _FlakyCommitExecutor(_FakeSchedExecutor):
    """Commit of a CHOSEN height fails exactly once (the async-commit
    rollback edge), then succeeds on the re-drive."""

    supports_preexec = True

    def __init__(self, ledger, fail_number: int):
        super().__init__(ledger)
        self.fail_number = fail_number
        self.failed_once = False

    def commit(self, params):
        if params.number == self.fail_number and not self.failed_once:
            self.failed_once = True
            raise ConnectionError("injected commit fault")
        super().commit(params)

    # speculative-execution stubs (the harness block carries no txs)
    def next_block_header(self, header, base=None):
        pass

    def get_hash_async(self):
        return lambda: b"\x00" * 32

    def block_state(self, number):
        return object()  # a chained overlay stand-in


class _FakePipelineBlock(_FakeSchedBlock):
    def calculate_txs_root_async(self, _suite):
        return lambda: b"\x00" * 32

    def calculate_receipts_root_async(self, _suite):
        return lambda: b"\x00" * 32


class PipelinedCommitHarness:
    """The flood-pipeline rollback edges (ISSUE 14): a committer whose 2PC
    fails once and re-drives, a committer for the NEXT height queued
    behind it, a speculative lazy-roots execution chained above both, and
    a storage-term switcher — the in-flight marker, the pending-root
    resolvers and the commit order must stay coherent under every
    interleaving (commit-failure of N with speculative N+1 executed, and
    a storage switch mid-pipeline)."""

    name = "pipelined-commit"

    def __init__(self):
        from ..scheduler.scheduler import Scheduler

        self.watch = [
            (Scheduler, ("term", "_committing_thread", "_commits_queued")),
        ]

    def setup(self):
        from ..scheduler.scheduler import ExecutedBlock, Scheduler

        ledger = _FakeSchedLedger()
        executor = _FlakyCommitExecutor(ledger, fail_number=1)
        sched = Scheduler(
            executor, ledger, backend=None, suite=None,
            notify_worker=_InlineNotify(), commit_worker=_InlineNotify(),
        )
        for n in (1, 2):
            header = _FakeSchedHeader(n)
            sched._executed[n] = ExecutedBlock(
                header, _FakePipelineBlock(header), tx_hashes=(),
                post_state=object(),
            )
        committed: list[int] = []
        outcomes: list[tuple[int, bool]] = []
        sched.on_committed.append(lambda n, _b: committed.append(n))
        return {
            "sched": sched, "ledger": ledger, "committed": committed,
            "outcomes": outcomes,
        }

    def threads(self, ctx):
        from ..scheduler.scheduler import SchedulerError

        sched = ctx["sched"]
        outcomes = ctx["outcomes"]

        def committer(number):
            header = _FakeSchedHeader(number)

            def run():
                for _ in range(50):
                    try:
                        sched.commit_block_async(
                            header,
                            on_done=lambda n, e: outcomes.append((n, e is None)),
                        )
                    except SchedulerError:
                        if number not in sched._executed:
                            return  # dropped by the term switch
                        continue
                    # inline worker: the 2PC already ran; re-drive until
                    # this height is durably booked or the switch drops it
                    if ctx["ledger"].height >= number:
                        return
                return

            return run

        def speculator():
            # lazy-roots speculative execution of N+2 chained on N+1's
            # post-state, racing the commits and the term switch
            header = _FakeSchedHeader(3)
            block = _FakePipelineBlock(header)
            try:
                sched.execute_block(block, lazy_roots=True)
            except SchedulerError:
                pass  # chain not ready / dropped mid-race: a legal outcome

        def switcher():
            sched.switch_term()

        return [
            ("c1", committer(1)), ("c2", committer(2)),
            ("spec", speculator), ("switch", switcher),
        ]

    def check(self, ctx):
        sched = ctx["sched"]
        committed = ctx["committed"]
        assert sched.term == 1, f"term switch lost: {sched.term}"
        assert not sched._committing, f"marker leaked: {sched._committing}"
        assert sched._commits_queued == 0, sched._commits_queued
        assert sched._committing_thread is None, "committer identity leaked"
        assert committed == sorted(committed), committed
        assert ctx["ledger"].height == (committed[-1] if committed else 0)
        # a lazily-executed speculation either resolved its roots, was
        # dropped by the switch, or still holds its resolvers — never a
        # half-resolved header
        eb = sched._executed.get(3)
        if eb is not None and eb.pending_roots is None:
            assert eb.header.state_root == b"\x00" * 32


# -- Pipeline observatory stage machine ---------------------------------------


class PipelineObsHarness:
    """Two pipeline workers drive busy/blocked transitions on ONE stage
    while a sampler thread takes snapshots and watermark sweeps — the
    interval counters must not lose updates, the thread counts must
    return to zero, and no snapshot may tear (ISSUE 9: the recorder is
    always-on shared state touched by every pipeline worker plus the
    background sampler)."""

    name = "pipeline-obs"

    def __init__(self):
        from ..observability.pipeline import PipelineRecorder, StageStats

        self.watch = [
            (PipelineRecorder, ("_stages", "_marks")),
            (StageStats, (
                "busy_ms", "intervals", "blocked_intervals", "n_busy",
                "n_blocked", "state",
            )),
        ]

    def setup(self):
        from ..observability.pipeline import PipelineRecorder

        # deterministic injected clock (the explorer forbids wall clocks);
        # monotone under any interleaving because += happens under the
        # recorder's (instrumented) lock or a worker-local read
        ticks = {"t": 0.0}
        lock = threading.Lock()

        def clock():
            with lock:
                ticks["t"] += 1.0
                return ticks["t"]

        rec = PipelineRecorder(clock=clock, enabled=True, emit_metrics=False)
        rec.add_probe("depth", lambda: 1)
        return {"rec": rec, "snaps": []}

    def threads(self, ctx):
        rec = ctx["rec"]
        snaps = ctx["snaps"]

        def worker():
            for _ in range(2):
                with rec.busy("stage"):
                    with rec.blocked("downstream"):
                        pass

        def sampler():
            rec.sample_once()
            snaps.append(rec.snapshot())

        return [("w1", worker), ("w2", worker), ("sample", sampler)]

    def check(self, ctx):
        rec = ctx["rec"]
        snap = rec.snapshot()["stage"]
        # the lost-update canaries: 2 workers x 2 intervals each
        assert snap["intervals"] == 4, snap
        assert snap["blocked_intervals"] == 4, snap
        assert snap["active_threads"] == 0, snap
        assert snap["blocked_threads"] == 0, snap
        assert snap["state"] == "idle", snap
        assert snap["busy_ms"] > 0 and snap["blocked_ms"]["downstream"] > 0, snap
        marks = rec.watermarks()
        assert marks["depth"]["n"] == 1, marks
        for s in ctx["snaps"]:
            st = s.get("stage")
            if st is not None:
                assert st["active_threads"] >= 0 and st["intervals"] <= 4, st


# -- QuorumCollector vote accumulator -----------------------------------------


class _StubQCScheme:
    """Deterministic, crypto-free QC scheme: the explorer needs pure
    control flow (a pairing check inside a schedule would swamp the
    preemption budget and add nothing — the contention is in the
    accumulator, not the algebra)."""

    name = "ed25519"  # a registered wire id so certs encode/decode
    pub_len = 4

    @staticmethod
    def _expect(pub: bytes, msg32: bytes) -> bytes:
        return b"sig:" + pub + msg32[:4]

    def verify_one(self, qc_pub, msg32, sig):
        return sig == self._expect(qc_pub, msg32)

    def build_cert(self, sig_by_idx, committee):
        from ..consensus.qc import QuorumCert

        idxs = sorted(sig_by_idx)
        return QuorumCert(
            scheme=self.name,
            committee=committee,
            bitmap=QuorumCert.make_bitmap(idxs, committee),
            agg_sig=b"".join(sig_by_idx[i] for i in idxs),
        )

    def verify_cert(self, cert, qc_pubs, msg32):
        want = b"".join(self._expect(qc_pubs[i], msg32) for i in cert.signers())
        return bool(cert.signers()) and cert.agg_sig == want


class QuorumCollectorHarness:
    """Concurrent vote arrival races quorum admission (aggregate verify +
    seal-once memo) and view-change/commit resets on the ISSUE 12 vote
    accumulator — votes must never be lost (the counter sees every add),
    whichever admit runs last must seal a quorum certificate, and the
    seal memo/pending map must stay coherent under any interleaving."""

    name = "qc-collector"

    def __init__(self):
        from ..consensus.qc import QuorumCollector

        self.watch = [(QuorumCollector, (
            "votes", "aggregates", "fallbacks", "bad_votes", "sealed",
            "_pending",
        ))]

    KEY = (1, 5, 0, b"\xaa" * 32)  # (phase, number, view, hash)
    MSG = b"\xbb" * 32

    def setup(self):
        from ..consensus.qc import QuorumCollector
        from ..txpool.quota import get_quotas

        get_quotas().reset()  # strikes from prior seeds must not leak in
        scheme = _StubQCScheme()
        col = QuorumCollector(suite=None, scheme=scheme)
        pubs = [b"pk_%d" % i for i in range(4)]
        return {"col": col, "pubs": pubs, "scheme": scheme, "out": {}}

    def threads(self, ctx):
        col = ctx["col"]
        pubs = ctx["pubs"]
        scheme = ctx["scheme"]
        out = ctx["out"]

        def voter(idxs, name):
            def run():
                for i in idxs:
                    col.add_vote(
                        self.KEY, i, scheme._expect(pubs[i], self.MSG)
                    )
                out[name] = col.admit(
                    self.KEY, self.MSG, None, pubs, lambda i: 1, 3
                )

            return run

        def resetter():
            # non-destructive passes over the shared maps: pure lock/state
            # contention (number 5 survives reset_below(4); view 0 keys
            # survive reset_view(0))
            col.reset_view(0)
            col.reset_below(4)

        return [
            ("v1", voter([0, 1], "v1")),
            ("v2", voter([2, 3], "v2")),
            ("reset", resetter),
        ]

    def check(self, ctx):
        col = ctx["col"]
        out = ctx["out"]
        st = col.stats()
        assert st["votes"] == 4, f"lost votes: {st}"
        assert set(out) == {"v1", "v2"}, f"admits lost: {sorted(out)}"
        # whichever admit serialized last saw all four votes: it must have
        # sealed (or reused the first seal's memo)
        certs = [r[2] for r in out.values() if r[2] is not None]
        assert certs, f"no quorum sealed: {out}"
        for cert in certs:
            assert len(cert.signers()) >= 3, cert.signers()
        assert st["sealed"] >= 1 and st["bad_votes"] == 0, st
        assert st["fallbacks"] == 0, st


# -- Fleet observatory: round ledger + flight ring -----------------------------


class FleetObsHarness:
    """Two engine-side writers drive the SAME round's edges and votes
    (plus younger rounds and a view change) while the federation
    aggregator snapshots the ledger and a crash-flush drains the flight
    ring to disk (ISSUE 16): first-wins edges must survive re-delivery
    races, quorum votes must never be lost, a snapshot must not tear
    mid-round, and the flushed black box must parse back whole."""

    name = "fleet-obs"

    def __init__(self):
        from ..observability.flight import FlightRecorder
        from ..observability.roundlog import RoundLedger

        self.watch = [
            (RoundLedger, ("_rounds", "_view_changes")),
            # "?": the ring rides lock-free GIL-atomic appends by design —
            # only a reassignment of the ring itself may flag
            (FlightRecorder, ("?_ring",)),
        ]

    def setup(self):
        import tempfile

        from ..observability.flight import FlightRecorder
        from ..observability.roundlog import RoundLedger

        # deterministic injected clock (the explorer forbids wall clocks)
        ticks = {"t": 0.0}
        lock = threading.Lock()

        def clock():
            with lock:
                ticks["t"] += 1.0
                return ticks["t"]

        led = RoundLedger(node_tag="h0", cap=8, clock=clock, emit_metrics=False)
        fr = FlightRecorder(cap=64, clock=clock, wallclock=clock, enabled=True)
        return {
            "led": led, "fr": fr, "snaps": [],
            "dir": tempfile.mkdtemp(prefix="fleet-obs-"),
        }

    def threads(self, ctx):
        led = ctx["led"]
        fr = ctx["fr"]
        snaps = ctx["snaps"]

        def engine():
            # the engine worker: round 5's own edges + its quorum votes
            led.note(5, 0, "pre_prepare")
            for i in range(3):
                led.vote(5, 0, "prepare", i)
            led.note(5, 0, "prepared")
            fr.record("engine", "prepared", scope="h0", height=5)

        def transport():
            # transport threads race the same round (re-delivery included)
            led.vote(5, 0, "prepare", 3)
            led.note(5, 0, "pre_prepare")  # re-delivered frame: first wins
            for h in (6, 7, 8):
                led.note(h, 0, "pre_prepare")
            led.view_change(6, 0, 1, "timeout")
            fr.record("engine", "pre_prepare", scope="h0", height=6)

        def aggregator():
            snaps.append(led.snapshot())
            snaps.append(led.snapshot(height=5))

        def flusher():
            # the crash-flush door: ring + embedded ledger to disk
            fr.record("halt", "stop", scope="h0")
            ctx["path"] = fr.flush(
                "h0", "crash:test", directory=ctx["dir"],
                rounds=led.snapshot(),
            )

        return [
            ("engine", engine), ("transport", transport),
            ("agg", aggregator), ("flush", flusher),
        ]

    def check(self, ctx):
        import json
        import shutil

        from ..observability.flight import post_mortem

        led = ctx["led"]
        final = led.snapshot()
        by_key = {(r["height"], r["view"]): r for r in final["rounds"]}
        # the lost-update canaries: every edge, every vote, the view change
        r5 = by_key[(5, 0)]
        assert {"pre_prepare", "prepared"} <= set(r5["events"]), r5
        assert set(r5["votes"]["prepare"]) == {"0", "1", "2", "3"}, r5
        for h in (6, 7, 8):
            assert (h, 0) in by_key, sorted(by_key)
        assert [vc["cause"] for vc in final["view_changes"]] == ["timeout"]
        # no torn snapshot: every observed round is structurally whole
        for snap in ctx["snaps"]:
            for r in snap["rounds"]:
                assert isinstance(r["events"], dict), r
                assert all(
                    isinstance(t, float) for vs in r["votes"].values()
                    for t in vs.values()
                ), r
        for r in ctx["snaps"][1::2]:  # the height-filtered snapshots
            assert all(x["height"] == 5 for x in r["rounds"]), r
        # the black box parses back whole, wherever the flush interleaved
        assert ctx.get("path"), "flight flush failed"
        with open(ctx["path"]) as f:
            doc = json.load(f)
        assert doc["reason"] == "crash:test", doc["reason"]
        names = {(e["category"], e["name"]) for e in doc["events"]}
        assert ("halt", "stop") in names, sorted(names)
        assert doc["rounds"]["node"] == "h0", doc["rounds"]
        pm = post_mortem(ctx["dir"])
        assert "h0" in pm["nodes"] and pm["timeline"], pm["nodes"]
        shutil.rmtree(ctx["dir"], ignore_errors=True)


# -- PBFT engine: off-lock QC admission (torn quorum) --------------------------


class _TornStubSig:
    """Deterministic outer-signature impl (the packet signature): pure
    string check, no crypto — the contention under test is the engine's
    verify queue, not the algebra."""

    @staticmethod
    def sign(kp, msg):
        return b"wire:" + kp.pub[:8] + msg[:8]

    @staticmethod
    def verify(pub, msg, sig):
        return sig == b"wire:" + pub[:8] + msg[:8]


class _TornStubSuite:
    name = "stub"
    signature_impl = _TornStubSig()

    @staticmethod
    def hash(data: bytes) -> bytes:
        return hashlib.sha256(data).digest()


class _TornKP:
    def __init__(self, pub: bytes, secret: int = 0):
        self.pub = pub
        self.secret = secret


class _TornQCScheme(_StubQCScheme):
    """The collector stub plus ``sign_vote`` (the engine signs its own
    votes through the scheme) and the registered ed25519 pub length so
    ``qc_ready()`` sees a fully-registered committee."""

    pub_len = 32

    def sign_vote(self, kp, msg32: bytes) -> bytes:
        return self._expect(kp.pub, msg32)


class TornQuorumHarness:
    """Concurrent PREPARE deliveries race the engine's OFF-LOCK aggregate
    QC admission (snapshot under the lock -> verify without it -> re-check
    the gate before completing) while a duplicate pre-prepare contends on
    the engine lock. A torn quorum — two completions, a completion against
    a stale snapshot, or a lost/duplicated verify job — is the bug class
    the double-gate re-check must exclude under EVERY interleaving."""

    name = "torn-quorum"

    def __init__(self):
        from ..consensus.engine import PBFTEngine, ProposalCache

        self.watch = [
            (PBFTEngine, ("_verify_jobs", "_verify_keys", "view")),
            (ProposalCache, ("prepared", "prepare_qc", "committed")),
        ]

    def setup(self):
        from ..consensus.audit import EVIDENCE
        from ..consensus.config import PBFTConfig
        from ..consensus.engine import PBFTEngine
        from ..consensus.messages import PacketType, PBFTMessage
        from ..consensus.qc import QuorumCollector, vote_preimage
        from ..front.front import FrontService
        from ..ledger.ledger import ConsensusNode
        from ..protocol.block import Block
        from ..protocol.block_header import BlockHeader
        from ..scheduler.scheduler import SchedulerError
        from ..txpool.quota import get_quotas

        get_quotas().reset()  # strikes from prior seeds must not leak in
        EVIDENCE.reset()
        suite = _TornStubSuite()
        scheme = _TornQCScheme()
        kps = [_TornKP(b"np_%d_" % i * 8, secret=i) for i in range(4)]
        qc_pubs = [bytes([0xA0 + i]) * 32 for i in range(4)]
        committee = [
            ConsensusNode(kp.pub, weight=1, qc_pub=qc_pubs[i])
            for i, kp in enumerate(kps)
        ]
        config = PBFTConfig(suite=suite, keypair=kps[0], nodes=committee)
        # pre-seed the QC keypair memo: the real derivation hashes the
        # consensus secret through the registered scheme — stubbed here
        config._qc_kp_cache = ("ed25519", _TornKP(qc_pubs[0]))

        class _Ledger:
            @staticmethod
            def block_number():
                return 0

            @staticmethod
            def block_hash_by_number(_n):
                return b"\x11" * 32

        class _Scheduler:
            @staticmethod
            def execute_block(_block, lazy_roots=False):
                from ..utils.error import ErrorCode

                raise SchedulerError(
                    ErrorCode.SCHEDULER_INVALID_BLOCK,
                    "stub: no execution in the harness",
                )

        class _TxPool:
            @staticmethod
            def mark_sealed(_hashes):
                pass

        eng = PBFTEngine(
            config, _Scheduler(), _TxPool(), _Ledger(), FrontService(kps[0].pub)
        )
        eng.qc = QuorumCollector(suite=None, scheme=scheme)
        eng.qc.strike_tagger = eng._qc_strike_tag

        completions = []
        real_complete = eng._complete_prepared

        def counting_complete(number, cache, agreeing, cert):
            completions.append(number)
            real_complete(number, cache, agreeing, cert)

        eng._complete_prepared = counting_complete

        # leader of (number=1, view=0) is index 1; this engine is index 0
        block = Block(header=BlockHeader(number=1))
        h = block.header.hash(suite)
        pp = PBFTMessage(
            packet_type=PacketType.PRE_PREPARE,
            view=0,
            number=1,
            proposal_hash=h,
            proposal_data=block.encode(),
        )
        pp.generated_from = 1
        pp.sign(suite, kps[1])

        def prepare_from(i):
            m = PBFTMessage(
                packet_type=PacketType.PREPARE, view=0, number=1,
                proposal_hash=h,
            )
            m.generated_from = i
            m.sign(suite, kps[i])
            m.qc_sig = scheme._expect(
                qc_pubs[i], vote_preimage(suite, PacketType.PREPARE, 0, 1, h)
            )
            return m

        # accept the proposal (our own PREPARE joins the cache) and bank
        # the leader's vote: 2 of quorum-3 in hand, the crossing vote
        # arrives on the contending threads
        eng.handle_message(pp)
        eng.handle_message(prepare_from(1))
        return {
            "eng": eng, "pp": pp, "completions": completions,
            "prepares": [prepare_from(2), prepare_from(3)],
        }

    def threads(self, ctx):
        eng = ctx["eng"]
        p2, p3 = ctx["prepares"]

        def deliver(m):
            def run():
                eng.handle_message(m)

            return run

        return [
            ("v2", deliver(p2)),
            ("v3", deliver(p3)),
            ("pp-dup", deliver(ctx["pp"])),
        ]

    def check(self, ctx):
        from ..consensus.audit import EVIDENCE

        eng = ctx["eng"]
        cache = eng._caches.get(1)
        assert cache is not None, "proposal cache vanished"
        assert ctx["completions"] == [1], (
            f"torn quorum: completions={ctx['completions']}"
        )
        assert cache.prepared, "quorum never admitted"
        assert cache.prepare_qc is not None, "no certificate sealed"
        assert len(cache.prepare_qc.signers()) >= 3, cache.prepare_qc.signers()
        assert 0 in cache.commits, "own COMMIT vote lost"
        assert not cache.committed, "committed on 1 commit vote"
        assert not eng._verify_jobs and not eng._verify_keys, (
            f"verify queue leaked: {list(eng._verify_jobs)}"
        )
        assert EVIDENCE.count() == 0, EVIDENCE.counts()


# -- Storage observatory recorder ---------------------------------------------


class StorageObsHarness:
    """A commit-window writer (the scheduler's serialized 2PC ledger path)
    races codec/copy note traffic from a worker thread and a snapshot
    reader — the global codec/copy counters must not lose updates, the
    per-block window must fold only commit-context bytes, and no snapshot
    may tear (ISSUE 19: the recorder is always-on shared state touched by
    every storage seam plus the /storage renderer)."""

    name = "storage-obs"

    def __init__(self):
        from ..observability.storagelog import StorageRecorder

        self.watch = [
            (StorageRecorder, (
                "_codec", "_copies", "_pages", "_blocks", "_cur",
                "_shard_ms", "_shard_totals",
            )),
        ]

    def setup(self):
        from ..observability.storagelog import StorageRecorder

        # deterministic injected clock (the explorer forbids wall clocks)
        ticks = {"t": 0.0}
        lock = threading.Lock()

        def clock():
            with lock:
                ticks["t"] += 1.0
                return ticks["t"]

        rec = StorageRecorder(clock=clock, emit_metrics=False, enabled=True)
        return {"rec": rec, "snaps": []}

    def threads(self, ctx):
        from ..observability.storagelog import CTX_COMMIT, codec_ctx

        rec = ctx["rec"]
        snaps = ctx["snaps"]

        def committer():
            # two serialized commit windows, as the scheduler runs them
            for h in (1, 2):
                rec.begin_commit(h)
                rec.note_commit_rows(h, 2)
                with codec_ctx(CTX_COMMIT, "t"):
                    rec.note_encode(10)
                    rec.note_encode(10)
                rec.note_copy("keypage.prepare", "t")
                rec.note_pages("t", 1)
                rec.end_prepare(h)
                rec.finish_commit(h)

        def worker():
            # read-path traffic landing DURING the windows: untagged
            # codec bytes, cache copy-outs, a remote shard's 2PC legs
            for _ in range(3):
                rec.note_encode(5)
            rec.note_copy("cache.get_row", "t")
            rec.note_copy("cache.get_row", "t")
            rec.shard_note("prepare", 0, 1.0, rows=1, n_bytes=5)
            rec.shard_note("prepare", 0, 2.0, rows=1, n_bytes=5)

        def reader():
            snaps.append(rec.snapshot(last_blocks=8))

        return [("commit", committer), ("worker", worker), ("snap", reader)]

    def check(self, ctx):
        rec = ctx["rec"]
        snap = rec.snapshot(last_blocks=8)
        # lost-update canaries: exact global counters whatever the schedule
        assert snap["codec"]["encode:commit:t"] == {"calls": 4, "bytes": 40}, snap
        assert snap["codec"]["encode:-:-"] == {"calls": 3, "bytes": 15}, snap
        assert snap["copies"]["keypage.prepare:t"] == 2, snap
        assert snap["copies"]["cache.get_row:t"] == 2, snap
        assert snap["pages_rewritten"] == {"t": 2}, snap
        assert rec.encode_bytes_now() == 55
        assert rec.commit_bytes_total() == 40
        # both windows closed; only commit-context bytes folded into them
        blocks = {b["height"]: b for b in snap["blocks"]}
        assert set(blocks) == {1, 2}, snap
        for b in blocks.values():
            assert not b["aborted"] and b["rows_written"] == 2, b
            assert b["bytes_encoded"] == 20 and b["encode_calls"] == 2, b
            assert b["pages_rewritten"] == 1, b
            # the committer's own copy always lands in its window; the
            # worker's cache copy-outs land in whichever window (or none)
            # was open — bounded either way
            assert 1 <= b["entries_copied"] <= 3, b
        shard = snap["shards"]["0"]["prepare"]
        assert shard["n"] == 2 and shard["rows"] == 2 and shard["bytes"] == 10
        # a mid-run snapshot must parse as a consistent document, never
        # exceed the final counters, and never show a torn codec cell
        for s in ctx["snaps"]:
            for cell in s["codec"].values():
                assert cell["calls"] >= 0 and cell["bytes"] >= 0, s
            assert len(s["blocks"]) <= 2, s
            assert sum(s["copies"].values()) <= 4, s


HARNESSES = {
    h.name: h
    for h in (DevicePlaneHarness, ProofPlaneHarness, AdmissionQuotasHarness,
              SchedulerHarness, PipelinedCommitHarness, PipelineObsHarness,
              QuorumCollectorHarness, FleetObsHarness, TornQuorumHarness,
              StorageObsHarness)
}

FIXTURE_HARNESSES = {RacyCounterHarness.name: RacyCounterHarness}
