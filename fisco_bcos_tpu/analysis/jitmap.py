"""Package-wide inventory of jit-traced functions (pure AST).

Three definition idioms exist in this codebase and all are collected:

- ``@jax.jit`` (or ``@jit``) decorated ``def`` — the ops kernels.
- ``name = jax.jit(func)`` module/class-level assignment — the admission
  steps (``admission_step = jax.jit(admission_core)``): BOTH the wrapper
  name and the wrapped function count as jitted.
- ``return jax.jit(f)`` over a local ``def f`` — the sharding makers and
  ``merkle._device_root_fn``: the local def's body is jit-traced.
- ``f = jax.shard_map(local, ...); return jax.jit(f)`` — one assignment of
  a wrapper call (shard_map/pmap/vmap/partial/checkpoint) between the def
  and the jit: the wrapped local def's body is what traces.

The inventory powers two checkers: jit-purity walks the traced bodies for
side effects, and shape-bucket treats any *call* to an inventoried name as
a device-program entry whose feeding shapes must be bucketed.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from .core import Source, qualnames


@dataclass(frozen=True)
class JitFunc:
    source: Source
    node: ast.FunctionDef
    qualname: str  # of the traced def itself
    names: tuple[str, ...]  # callable names referring to it (def + wrappers)


def _is_jit_call(node: ast.AST) -> bool:
    """``jax.jit(...)`` / ``jit(...)`` call expression."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr == "jit":
        return True
    return isinstance(fn, ast.Name) and fn.id == "jit"


def _is_jit_decorator(dec: ast.AST) -> bool:
    if isinstance(dec, ast.Attribute) and dec.attr == "jit":
        return True
    if isinstance(dec, ast.Name) and dec.id == "jit":
        return True
    # @partial(jax.jit, ...) / @jax.jit(static_argnums=...)
    if isinstance(dec, ast.Call):
        if _is_jit_call(dec):
            return True
        if any(_is_jit_call(a) or _is_jit_decorator(a) for a in dec.args):
            return True
    return False


_WRAPPERS = {"shard_map", "pmap", "vmap", "partial", "checkpoint"}


def _scope_pass(
    scope: ast.AST, found: dict[int, tuple[ast.FunctionDef, set[str]]]
) -> None:
    """Resolve the jit idioms with names bound in THIS scope's subtree.

    Scoped resolution matters for the sharding makers: eight functions each
    bind ``f = jax.shard_map(local, ...)`` over their own ``local`` def —
    a module-wide name map would collapse them onto one."""
    # name -> def node (first wins, matching Python's lookup of a shadowed
    # name being a bug we don't chase)
    defs: dict[str, ast.FunctionDef] = {}
    for node in ast.walk(scope):
        if isinstance(node, ast.FunctionDef) and node is not scope:
            defs.setdefault(node.name, node)
    # name -> wrapped def name, for `f = jax.shard_map(local, ...)`
    via_wrapper: dict[str, str] = {}
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            fn = node.value.func
            wname = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None
            )
            arg = node.value.args[0] if node.value.args else None
            if (
                wname in _WRAPPERS
                and isinstance(arg, ast.Name)
                and arg.id in defs
            ):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        via_wrapper[tgt.id] = arg.id

    def resolve(arg: ast.AST) -> str | None:
        """jit-call argument -> traced def name (direct or one wrapper)."""
        if not isinstance(arg, ast.Name):
            return None
        if arg.id in defs:
            return arg.id
        return via_wrapper.get(arg.id)

    def note(name: str, aliases: tuple[str, ...] = ()) -> None:
        node = defs[name]
        _node, names = found.setdefault(id(node), (node, set()))
        names.add(name)
        names.update(aliases)

    for node in ast.walk(scope):
        if (
            isinstance(node, ast.FunctionDef)
            and node is not scope
            and any(_is_jit_decorator(d) for d in node.decorator_list)
        ):
            note(node.name)
        elif isinstance(node, ast.Assign) and _is_jit_call(node.value):
            tgt_def = resolve(node.value.args[0] if node.value.args else None)
            if tgt_def is not None:
                note(
                    tgt_def,
                    tuple(
                        t.id
                        for t in node.targets
                        if isinstance(t, ast.Name)
                    ),
                )
        elif isinstance(node, ast.Return) and _is_jit_call(node.value):
            tgt_def = resolve(node.value.args[0] if node.value.args else None)
            if tgt_def is not None:
                note(tgt_def)


def collect(sources: list[Source]) -> list[JitFunc]:
    out: list[JitFunc] = []
    for src in sources:
        qn = qualnames(src.tree)
        found: dict[int, tuple[ast.FunctionDef, set[str]]] = {}
        _scope_pass(src.tree, found)
        for node in ast.walk(src.tree):
            if isinstance(node, ast.FunctionDef):
                _scope_pass(node, found)
        for node, names in found.values():
            out.append(
                JitFunc(src, node, qn.get(node, node.name), tuple(sorted(names)))
            )
    out.sort(key=lambda j: (j.source.relpath, j.node.lineno))
    return out


def callable_names(jits: list[JitFunc]) -> set[str]:
    """Every bare name a call site might use for a jitted function."""
    names: set[str] = set()
    for j in jits:
        names.update(j.names)
    return names


def inventory(root: str | None = None) -> list[dict]:
    """The package's jit-program inventory as plain records:
    ``{"file", "line", "qualname", "names"}`` per traced def, sorted by
    (file, line).

    This is the walkable form ``python -m fisco_bcos_tpu.analysis
    --list-jit`` prints and ``tool/warm_cache.py`` drives: every device
    program a node can compile at runtime — the ISSUE 12 BLS pairing
    program in ``ops/bls12_381.py`` included — appears here, so a
    pre-warmer that covers this list covers the node's whole compile
    surface (tests/test_static_analysis.py pins the count)."""
    from .core import load_sources

    return [
        {
            "file": j.source.relpath,
            "line": j.node.lineno,
            "qualname": j.qualname,
            "names": list(j.names),
        }
        for j in collect(load_sources(root))
    ]
