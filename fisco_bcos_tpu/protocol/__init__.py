"""Protocol objects: Transaction, Receipt, BlockHeader, Block.

The data-object layer the reference defines once as Tars structs and wraps
with framework interfaces (bcos-framework/protocol/*.h +
bcos-tars-protocol/protocol/*Impl.*). Canonical bytes come from codec.flat.
"""

from .transaction import Transaction, TransactionAttribute, TransactionFactory  # noqa: F401
from .receipt import LogEntry, TransactionReceipt, TransactionStatus  # noqa: F401
from .block_header import BlockHeader, ParentInfo, SignatureTuple  # noqa: F401
from .block import Block  # noqa: F401
