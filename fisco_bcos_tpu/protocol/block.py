"""Block: header + transactions (or tx-hash metadata) + receipts.

Mirrors bcos-framework/protocol/Block.h / Block.tars. A consensus proposal
carries only transaction *metadata* (hashes) — the pool fills full txs on
execution (asyncFillBlock, bcos-scheduler/BlockExecutive.cpp:301-357); a
synced/stored block carries everything. Tx/receipt merkle roots are built by
the wide device merkle (ops/merkle), hasher chosen by the crypto suite.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..codec.flat import FlatReader, FlatWriter
from ..crypto.suite import CryptoSuite
from .block_header import BlockHeader
from .receipt import TransactionReceipt
from .transaction import Transaction, hash_transactions_batch

_EMPTY_ROOT = b"\x00" * 32


@dataclass
class Block:
    header: BlockHeader = field(default_factory=BlockHeader)
    transactions: list[Transaction] = field(default_factory=list)
    tx_metadata: list[bytes] = field(default_factory=list)  # 32-byte tx hashes
    receipts: list[TransactionReceipt] = field(default_factory=list)

    # -- serialization ------------------------------------------------------

    def encode(self) -> bytes:
        w = FlatWriter()
        w.bytes_(self.header.encode())
        w.seq(self.transactions, lambda w2, t: w2.bytes_(t.encode()))
        w.seq(self.tx_metadata, lambda w2, h: w2.fixed(h, 32))
        w.seq(self.receipts, lambda w2, rc: w2.bytes_(rc.encode()))
        return w.out()

    @classmethod
    def decode(cls, buf: bytes) -> "Block":
        r = FlatReader(buf)
        blk = cls(header=BlockHeader.decode(r.bytes_()))
        blk.transactions = [
            Transaction.decode(b) for b in r.seq(lambda r2: r2.bytes_())
        ]
        blk.tx_metadata = r.seq(lambda r2: r2.fixed(32))
        blk.receipts = [
            TransactionReceipt.decode(b) for b in r.seq(lambda r2: r2.bytes_())
        ]
        r.done()
        return blk

    @classmethod
    def execution_view(cls, buf: bytes, transactions: list) -> "Block":
        """Run-isolated view of an accepted proposal for (speculative)
        execution: a PRIVATE header decoded from the accept-time snapshot
        (execution fills roots/gas/receipts in place while the certificate
        path serializes the cached original), sharing the already-decoded
        transaction objects — txs are immutable once signed, so re-decoding
        N of them per replica per block bought isolation nothing needs."""
        r = FlatReader(buf)
        return cls(
            header=BlockHeader.decode(r.bytes_()),
            transactions=list(transactions),
        )

    # -- content ------------------------------------------------------------

    @property
    def number(self) -> int:
        return self.header.number

    def tx_hashes(self, suite: CryptoSuite) -> list[bytes]:
        if self.transactions:
            return hash_transactions_batch(self.transactions, suite)
        return list(self.tx_metadata)

    def calculate_txs_root_async(self, suite: CryptoSuite):
        """Dispatch-now, sync-later (() -> bytes): see suite.merkle_root_async."""
        hashes = self.tx_hashes(suite)
        if not hashes:
            return lambda: _EMPTY_ROOT
        leaves = np.frombuffer(b"".join(hashes), dtype=np.uint8).reshape(-1, 32)
        return suite.merkle_root_async(leaves)

    def calculate_txs_root(self, suite: CryptoSuite) -> bytes:
        return self.calculate_txs_root_async(suite)()

    def calculate_receipts_root_async(self, suite: CryptoSuite):
        if not self.receipts:
            return lambda: _EMPTY_ROOT
        hashes = [rc.hash(suite) for rc in self.receipts]
        leaves = np.frombuffer(b"".join(hashes), dtype=np.uint8).reshape(-1, 32)
        return suite.merkle_root_async(leaves)

    def calculate_receipts_root(self, suite: CryptoSuite) -> bytes:
        return self.calculate_receipts_root_async(suite)()
