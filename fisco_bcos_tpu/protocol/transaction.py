"""Transaction protocol object.

Field set mirrors the reference's TransactionData/Transaction tars structs
(bcos-tars-protocol/tars/Transaction.tars) and the framework interface
(bcos-framework/protocol/Transaction.h): the *signed payload* is the encoded
TransactionData (version, chainID, groupID, blockLimit, nonce, to, input,
abi); the tx hash is hash(payload); `verify()` recovers the sender from the
signature over that hash (Transaction.h:64-84). Batch admission for whole
blocks lives in txpool (one fused device program) — this object's single-item
verify is the low-latency RPC path.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from enum import IntFlag

from ..codec.flat import FlatReader, FlatWriter
from ..crypto.suite import CryptoSuite, KeyPair


class TransactionAttribute(IntFlag):
    """bcos-framework/protocol/Transaction.h:45-51."""

    EVM_ABI_CODEC = 0x1
    LIQUID_SCALE_CODEC = 0x2
    DAG = 0x4
    LIQUID_CREATE = 0x8


@dataclass
class Transaction:
    version: int = 0
    chain_id: str = ""
    group_id: str = ""
    block_limit: int = 0
    nonce: str = ""
    to: bytes = b""  # 20-byte address, or b"" for create
    input: bytes = b""
    abi: str = ""
    # signature part
    signature: bytes = b""
    # mutable/annotation part (not hashed, not signed)
    attribute: int = 0
    import_time: int = 0
    extra_data: bytes = b""
    # caches
    _hash: bytes | None = field(default=None, repr=False)
    _data: bytes | None = field(default=None, repr=False)
    _wire: bytes | None = field(default=None, repr=False)
    sender: bytes = b""  # recovered 20-byte address ("forceSender" cache)

    # -- canonical bytes ----------------------------------------------------

    def encode_data(self) -> bytes:
        """The signed payload (TransactionData analog) — the hash preimage.

        Cached: the data fields are immutable once a tx is signed (only the
        signature/annotation section changes), and the block path encodes
        every tx three times (admission hash, sealing, ledger prewrite)."""
        if self._data is not None:
            return self._data
        w = FlatWriter()
        w.u32(self.version)
        w.str_(self.chain_id)
        w.str_(self.group_id)
        w.i64(self.block_limit)
        w.str_(self.nonce)
        w.bytes_(self.to)
        w.bytes_(self.input)
        w.str_(self.abi)
        self._data = w.out()
        return self._data

    def encode(self) -> bytes:
        """Full wire form: payload + signature + annotations. Cached: a tx
        is immutable once signed, and the block path serializes it again
        at pool persistence, gossip, and ledger prewrite — the zero-copy
        tx path decodes once at admission and every later stage reuses
        these exact bytes."""
        if self._wire is not None:
            return self._wire
        w = FlatWriter()
        w.bytes_(self.encode_data())
        w.bytes_(self.signature)
        w.u32(self.attribute)
        w.i64(self.import_time)
        w.bytes_(self.extra_data)
        self._wire = w.out()
        return self._wire

    @classmethod
    def decode(cls, buf: bytes) -> "Transaction":
        r = FlatReader(buf)
        data = r.bytes_()
        tx = cls._decode_data(data)
        # seed the payload cache with the EXACT bytes that were signed —
        # the first hash is free, and re-encoding canonicality never matters
        tx._data = data
        tx.signature = r.bytes_()
        tx.attribute = r.u32()
        tx.import_time = r.i64()
        tx.extra_data = r.bytes_()
        r.done()
        # the ingress bytes ARE the wire form: re-encoding a gossiped /
        # persisted tx is free from here on
        tx._wire = bytes(buf)
        return tx

    def invalidate_caches(self) -> None:
        """Drop the payload/hash/wire caches after mutating a data field
        (test fixtures forging variants; production txs are immutable once
        signed). One helper so no site can null one cache but not the
        others. Mutating only the signature/annotation section requires
        dropping just the wire cache — sign() does."""
        self._hash = None
        self._data = None
        self._wire = None

    @classmethod
    def _decode_data(cls, data: bytes) -> "Transaction":
        r = FlatReader(data)
        tx = cls(
            version=r.u32(),
            chain_id=r.str_(),
            group_id=r.str_(),
            block_limit=r.i64(),
            nonce=r.str_(),
            to=r.bytes_(),
            input=r.bytes_(),
            abi=r.str_(),
        )
        r.done()
        return tx

    # -- hashing / signing --------------------------------------------------

    def hash(self, suite: CryptoSuite) -> bytes:
        if self._hash is None:
            self._hash = suite.hash(self.encode_data())
        return self._hash

    def sign(self, kp: KeyPair, suite: CryptoSuite) -> "Transaction":
        self.signature = suite.signature_impl.sign(kp, self.hash(suite))
        self.sender = suite.calculate_address(kp.pub)
        self._wire = None  # the signature section changed under the cache
        return self

    def verify(self, suite: CryptoSuite) -> bool:
        """Single-item admission check (Transaction.h:64-84): recompute hash,
        recover the signer, cache the sender address. The batch path is
        txpool's fused device program."""
        try:
            pub = suite.signature_impl.recover(self.hash(suite), self.signature)
        except ValueError:
            return False
        self.sender = suite.calculate_address(pub)
        return True

    def force_sender(self, addr: bytes) -> None:
        self.sender = addr


def hash_transactions_batch(txs: list[Transaction], suite: CryptoSuite) -> list[bytes]:
    """Hash many txs in one device program and fill their caches — the batch
    form of Transaction.hash for sealing/verification paths (the reference
    hashes per-tx on tbb threads, TransactionImpl.cpp:43-66)."""
    missing = [t for t in txs if t._hash is None]
    if missing:
        digests = suite.hash_batch([t.encode_data() for t in missing])
        for t, d in zip(missing, digests):
            t._hash = bytes(d)
    return [t._hash for t in txs]  # type: ignore[misc]


class TransactionFactory:
    """Builds/decodes transactions bound to one crypto suite
    (reference: TransactionFactory.h / TransactionFactoryImpl)."""

    def __init__(self, suite: CryptoSuite):
        self.suite = suite

    def create(
        self,
        *,
        chain_id: str,
        group_id: str,
        block_limit: int,
        nonce: str,
        to: bytes = b"",
        input: bytes = b"",
        abi: str = "",
        attribute: int = 0,
        version: int = 1,
    ) -> Transaction:
        return Transaction(
            version=version,
            chain_id=chain_id,
            group_id=group_id,
            block_limit=block_limit,
            nonce=nonce,
            to=to,
            input=input,
            abi=abi,
            attribute=attribute,
            import_time=int(time.time() * 1000),
        )

    def create_signed(self, kp: KeyPair, **kwargs) -> Transaction:
        return self.create(**kwargs).sign(kp, self.suite)

    def decode(self, buf: bytes) -> Transaction:
        return Transaction.decode(buf)
