"""Transaction receipt + status codes.

Mirrors bcos-framework/protocol/TransactionReceipt.h and the tars struct
(bcos-tars-protocol/tars/TransactionReceipt.tars); status values from
bcos-protocol/TransactionStatus.h.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import IntEnum

from ..codec.flat import FlatReader, FlatWriter
from ..crypto.suite import CryptoSuite


class TransactionStatus(IntEnum):
    """Values match bcos-protocol/TransactionStatus.h:32-63 exactly — they
    are visible through receipts and the RPC API."""

    NONE = 0
    UNKNOWN = 1
    OUT_OF_GAS_LIMIT = 2
    NOT_ENOUGH_CASH = 7
    BAD_INSTRUCTION = 10
    BAD_JUMP_DESTINATION = 11
    OUT_OF_GAS = 12
    OUT_OF_STACK = 13
    STACK_UNDERFLOW = 14
    PRECOMPILED_ERROR = 15
    REVERT_INSTRUCTION = 16
    CONTRACT_ADDRESS_ALREADY_USED = 17
    PERMISSION_DENIED = 18
    CALL_ADDRESS_ERROR = 19
    GAS_OVERFLOW = 20
    CONTRACT_FROZEN = 21
    ACCOUNT_FROZEN = 22
    ACCOUNT_ABOLISHED = 23
    # WASM engine statuses (TransactionStatus.h:48-53)
    WASM_VALIDATION_FAILURE = 32
    WASM_ARGUMENT_OUT_OF_RANGE = 33
    WASM_UNREACHABLE_INSTRUCTION = 34
    WASM_TRAP = 35
    # txpool admission errors (TransactionStatus.h:54-63)
    NONCE_CHECK_FAIL = 10000
    BLOCK_LIMIT_CHECK_FAIL = 10001
    TXPOOL_IS_FULL = 10002
    MALFORM = 10003
    ALREADY_IN_TXPOOL = 10004
    TX_ALREADY_IN_CHAIN = 10005
    INVALID_CHAIN_ID = 10006
    INVALID_GROUP_ID = 10007
    INVALID_SIGNATURE = 10008


@dataclass
class LogEntry:
    address: bytes = b""
    topics: list[bytes] = field(default_factory=list)
    data: bytes = b""

    def encode_into(self, w: FlatWriter) -> None:
        w.bytes_(self.address)
        w.seq(self.topics, lambda w2, t: w2.fixed(t, 32))
        w.bytes_(self.data)

    @classmethod
    def decode_from(cls, r: FlatReader) -> "LogEntry":
        return cls(
            address=r.bytes_(),
            topics=r.seq(lambda r2: r2.fixed(32)),
            data=r.bytes_(),
        )


@dataclass
class TransactionReceipt:
    version: int = 0
    gas_used: int = 0
    contract_address: bytes = b""
    status: int = 0
    output: bytes = b""
    log_entries: list[LogEntry] = field(default_factory=list)
    block_number: int = 0
    effective_gas_price: str = ""
    _hash: bytes | None = field(default=None, repr=False)
    _enc: bytes | None = field(default=None, repr=False)

    def encode(self) -> bytes:
        """Cached after first call (same invariant as ``_hash``: the
        executor builds a receipt fully before anything encodes it; the
        block path then encodes twice — receipts root and ledger prewrite)."""
        if self._enc is not None:
            return self._enc
        w = FlatWriter()
        w.u32(self.version)
        w.u64(self.gas_used)
        w.bytes_(self.contract_address)
        w.u32(self.status)
        w.bytes_(self.output)
        w.seq(self.log_entries, lambda w2, e: e.encode_into(w2))
        w.i64(self.block_number)
        w.str_(self.effective_gas_price)
        self._enc = w.out()
        return self._enc

    @classmethod
    def decode(cls, buf: bytes) -> "TransactionReceipt":
        r = FlatReader(buf)
        rc = cls(
            version=r.u32(),
            gas_used=r.u64(),
            contract_address=r.bytes_(),
            status=r.u32(),
            output=r.bytes_(),
            log_entries=r.seq(LogEntry.decode_from),
            block_number=r.i64(),
            effective_gas_price=r.str_(),
        )
        r.done()
        rc._enc = bytes(buf)  # seed the wire-form cache with the exact bytes
        return rc

    def invalidate_caches(self) -> None:
        """Drop the wire-form/hash caches after mutating a field (mirrors
        Transaction.invalidate_caches so mutation sites have one correct
        idiom; a stale ``_enc`` would re-serialize pre-mutation bytes into
        the receipts root)."""
        self._enc = None
        self._hash = None

    def hash(self, suite: CryptoSuite) -> bytes:
        if self._hash is None:
            self._hash = suite.hash(self.encode())
        return self._hash
