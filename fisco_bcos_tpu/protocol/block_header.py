"""Block header.

Mirrors bcos-framework/protocol/BlockHeader.h and the BlockHeader tars struct:
the header hash is computed over the encoded header *without* the signature
list (signatures sign the header hash — that's what PBFT's QC is), matching
the reference's hash/signature split. The QC check over `signature_list` is
the #2 batch-verify hot loop (bcos-pbft/core/BlockValidator.cpp:141-177) and
goes to the device in consensus code.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..codec.flat import FlatReader, FlatWriter
from ..crypto.suite import CryptoSuite


@dataclass
class ParentInfo:
    number: int
    hash: bytes  # 32


@dataclass
class SignatureTuple:
    index: int  # sealer index in sealer_list
    signature: bytes


@dataclass
class BlockHeader:
    version: int = 0
    parent_info: list[ParentInfo] = field(default_factory=list)
    txs_root: bytes = b"\x00" * 32
    receipts_root: bytes = b"\x00" * 32
    state_root: bytes = b"\x00" * 32
    number: int = 0
    gas_used: int = 0
    timestamp: int = 0
    sealer: int = 0  # proposer index
    sealer_list: list[bytes] = field(default_factory=list)  # node pubkeys (64B)
    extra_data: bytes = b""
    consensus_weights: list[int] = field(default_factory=list)
    signature_list: list[SignatureTuple] = field(default_factory=list)
    # encoded consensus/qc.QuorumCert (opaque at this layer — the protocol
    # package must not import consensus): the constant-size replacement for
    # signature_list when aggregate QCs are active. Like signature_list it
    # sits OUTSIDE the hash preimage (it IS the signature over the hash),
    # and it encodes only when present, so FISCO_QC=0 headers stay
    # byte-identical to the pre-QC build.
    qc: bytes = b""
    # succinct state-plane commitment (merkle over the KeyPage state) — part
    # of the hash preimage, but encoded only when present so
    # FISCO_STATE_PROOF=0 headers stay byte-identical to the pre-succinct
    # build (the same optional-trailing-section pattern as `qc`)
    state_commitment: bytes = b""
    _hash: bytes | None = field(default=None, repr=False)

    def encode_hash_fields(self) -> bytes:
        """Everything except signature_list — the hash/sign preimage."""
        w = FlatWriter()
        w.u32(self.version)
        w.seq(
            self.parent_info,
            lambda w2, p: (w2.i64(p.number), w2.fixed(p.hash, 32)),
        )
        w.fixed(self.txs_root, 32)
        w.fixed(self.receipts_root, 32)
        w.fixed(self.state_root, 32)
        w.i64(self.number)
        w.u64(self.gas_used)
        w.i64(self.timestamp)
        w.i64(self.sealer)
        w.seq(self.sealer_list, lambda w2, s: w2.bytes_(s))
        w.bytes_(self.extra_data)
        w.seq(self.consensus_weights, lambda w2, x: w2.u64(x))
        if self.state_commitment:
            w.bytes_(self.state_commitment)
        return w.out()

    def encode(self) -> bytes:
        w = FlatWriter()
        w.bytes_(self.encode_hash_fields())
        w.seq(
            self.signature_list,
            lambda w2, s: (w2.i64(s.index), w2.bytes_(s.signature)),
        )
        if self.qc:
            w.bytes_(self.qc)
        return w.out()

    @classmethod
    def decode(cls, buf: bytes) -> "BlockHeader":
        r = FlatReader(buf)
        h = cls._decode_hash_fields(r.bytes_())
        h.signature_list = r.seq(
            lambda r2: SignatureTuple(r2.i64(), r2.bytes_())
        )
        if not r.at_end():
            h.qc = r.bytes_()
        r.done()
        return h

    @classmethod
    def _decode_hash_fields(cls, data: bytes) -> "BlockHeader":
        r = FlatReader(data)
        h = cls(
            version=r.u32(),
            parent_info=r.seq(lambda r2: ParentInfo(r2.i64(), r2.fixed(32))),
            txs_root=r.fixed(32),
            receipts_root=r.fixed(32),
            state_root=r.fixed(32),
            number=r.i64(),
            gas_used=r.u64(),
            timestamp=r.i64(),
            sealer=r.i64(),
            sealer_list=r.seq(lambda r2: r2.bytes_()),
            extra_data=r.bytes_(),
            consensus_weights=r.seq(lambda r2: r2.u64()),
        )
        if not r.at_end():
            h.state_commitment = r.bytes_()
        r.done()
        return h

    def hash(self, suite: CryptoSuite) -> bytes:
        if self._hash is None:
            self._hash = suite.hash(self.encode_hash_fields())
        return self._hash

    def clear_hash_cache(self) -> None:
        self._hash = None
