"""Device verification plane — shared, shape-bucketed batch scheduling for
all device crypto (see :mod:`.plane` and docs/device_plane.md)."""

from .plane import (  # noqa: F401
    DEFAULT_LANE,
    LANES,
    DevicePlane,
    PlaneRequest,
    current_lane,
    device_lane,
    get_plane,
    in_plane_executor,
    plane_enabled,
    plane_route,
)
