"""DevicePlane — the shared, shape-bucketed batch scheduler for all device
crypto dispatch.

Before this layer, every caller — txpool admission (txpool/txpool.py
submit_batch), proposal verification (consensus/engine.py _verify_and_fill,
consensus/block_validator.py check_block) and tx sync (sync/tx_sync.py
_on_push) — ran its own synchronous device batch, so arbitrary per-caller
batch shapes caused recompile churn (visible in the compile-vs-cached
counters) and the device plane never saturated: the FPGA-ECDSA engine and
EdDSA/BLS committee-consensus studies (PAPERS.md, arxiv 2112.02229 /
2302.00418) both get their wins from ONE saturated verification engine fed
by a request queue, not from per-caller batches.

The plane is that engine's scheduler:

- **Per-op request queue, future-based results.** Callers submit
  (op, payload, item-count, executor) and get a ``concurrent.futures.Future``
  back; the crypto seams (crypto/suite.py batch methods,
  crypto/admission.admit_batch) block on it, so caller APIs are unchanged.
- **Micro-batch coalescer.** A single worker drains each op's queue after a
  bounded window (``FISCO_DEVICE_WINDOW_MS``, default 2 ms) or when the
  queued item count crosses the high-water mark
  (``FISCO_DEVICE_HIGH_WATER``, default 4096) — concurrent
  admission/consensus/sync requests merge into one device program.
- **Shape bucketing.** Merged batches dispatch through the existing
  bucket-padded host wrappers (ops/hash_common._bucket ladder), so the jit
  cache converges to ladder-many compiled programs instead of one per batch
  size; ``fisco_device_compile_total`` stays ≤ the ladder size
  (tool/check_device_plane.py asserts it).
- **Priority lanes.** consensus > admission > sync > proof among
  dispatch-ready op groups, with starvation-free draining: any group whose
  oldest request
  has waited past ``FISCO_DEVICE_STARVATION_MS`` (default 50 ms) preempts
  lane order, oldest first — a gossip flood cannot park a QC check, and a
  stream of QC checks cannot park gossip forever.
- **Group-fair selection (multi-tenant isolation).** Every request carries
  the chain group that produced it (``device_group``, tagged by each
  group's txpool). When a dispatch-ready op queue holds traffic from MORE
  than one tenant group, the dispatch is assembled by deficit-weighted
  round-robin across groups *within* each priority lane: each group earns
  ``FISCO_DEVICE_GROUP_QUANTUM`` items (x its
  ``FISCO_DEVICE_GROUP_WEIGHTS`` weight) per round and spends its deficit
  on its oldest requests, and the merged batch is capped at the high-water
  mark — so one group flooding admission batches cannot fill every device
  program while another group's batch sits queued behind the backlog.
  Deferred requests keep their enqueue time (aging still applies) and
  count into ``fisco_device_plane_deferred_total{op,group}``. Single-group
  queues take the exact pre-fairness path: everything merges, no cap.
- **Passthrough mode.** ``FISCO_DEVICE_PLANE=0`` disables routing entirely:
  every seam takes its exact pre-plane dispatch path (per-caller batches,
  no coalescing, no fan-out) — the escape hatch the smoke tool exercises.

Executors run ON the worker thread with a thread-local marker set;
``plane_route()`` returns False there, so an executor calling back into a
plane-routed seam (e.g. ed25519 batch_recover → batch_verify) takes the
direct path instead of deadlocking the single worker against itself.
Results are bit-identical to the direct path by construction: executors
call the same merged-batch implementations the direct path uses, and
invalid rows lower validity-lane bits — they never raise.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Callable

# dispatch priority per lane, lower = sooner (consensus is on the critical
# path of block time; admission feeds the next proposal; sync is gossip;
# proof is the read path — light-client proof storms must never starve the
# write path, so their tree builds rank below everything, bounded only by
# the starvation aging like every other lane)
LANES = {"consensus": 0, "admission": 1, "sync": 2, "proof": 3}
DEFAULT_LANE = "admission"

_tls = threading.local()


def plane_enabled() -> bool:
    """The master switch, read per call so tool/check_device_plane.py can
    flip passthrough mode mid-process."""
    return os.environ.get("FISCO_DEVICE_PLANE", "1") != "0"


def in_plane_executor() -> bool:
    return bool(getattr(_tls, "in_exec", False))


def plane_route() -> bool:
    """True when a batch call should enqueue into the shared plane: the
    plane is enabled AND this is not already a plane executor (an executor
    re-entering the queue would deadlock the single worker, so nested seam
    calls take the direct path)."""
    return plane_enabled() and not in_plane_executor()


def current_lane() -> str:
    return getattr(_tls, "lane", DEFAULT_LANE)


def current_group() -> str:
    """The tenant (chain group) this thread's device batches belong to;
    "" = ungrouped (single-group deployments, internal callers)."""
    return getattr(_tls, "group", "")


@contextmanager
def device_group(name: str):
    """Tag device-crypto calls in this thread with their tenant group, the
    unit the plane's deficit-round-robin arbitrates between. Same contract
    as :func:`device_lane`: the txpool wraps its batch calls, everything
    submitted underneath inherits the tag."""
    prev = getattr(_tls, "group", "")
    _tls.group = name
    try:
        yield
    finally:
        _tls.group = prev


@contextmanager
def device_lane(name: str):
    """Tag device-crypto calls in this thread with a priority lane.

    Callers keep their APIs (the issue's seam contract): the consensus
    engine / block validator / tx sync wrap their verification calls in
    ``with device_lane("consensus"/"sync")`` and every batch submitted
    underneath inherits the lane; untagged callers default to "admission".
    """
    prev = getattr(_tls, "lane", DEFAULT_LANE)
    _tls.lane = name
    try:
        yield
    finally:
        _tls.lane = prev


@dataclass
class PlaneRequest:
    """One queued batch: op key, op-specific payload, item count, lane.
    ``ctx`` is the submitting caller's trace context — the merged dispatch
    span links back to it, and the caller's trace gets a retroactive
    ``device.plane.wait`` span carrying the batch's span id."""

    op: str
    payload: object
    n: int
    lane: str
    t_enq: float
    future: Future
    ctx: object = None
    group: str = ""  # tenant group (deficit-round-robin arbitration unit)


# wait-time buckets: the window is ~2 ms, starvation trips at ~50 ms, and
# anything past a few hundred ms means the plane is the bottleneck
WAIT_BUCKETS_MS = (1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 1000.0)
OCCUPANCY_BUCKETS = (0.25, 0.5, 0.75, 0.9, 1.0)


class DevicePlane:
    """The coalescing scheduler. One process-wide instance (``get_plane``)
    serves every crypto seam; standalone instances exist only in tests.

    Executors are bound lazily at submit time (first one registered per op
    wins) — the plane imports nothing from the crypto layer, so there is no
    import cycle and no registration ordering to get wrong.
    An executor receives the request list and returns one result per
    request, in order; it runs with the in-executor marker set.
    """

    def __init__(
        self,
        window_ms: float | None = None,
        high_water: int | None = None,
        starvation_ms: float | None = None,
        autostart: bool = True,
    ):
        from ..utils import env_float as _env

        if window_ms is not None:
            self.window_ms = float(window_ms)
        elif os.environ.get("FISCO_DEVICE_WINDOW_MS"):
            self.window_ms = _env("FISCO_DEVICE_WINDOW_MS", 2.0)
        else:
            self.window_ms = self._default_window_ms()
        self.high_water = (
            int(_env("FISCO_DEVICE_HIGH_WATER", 4096.0))
            if high_water is None
            else int(high_water)
        )
        self.starvation_ms = (
            _env("FISCO_DEVICE_STARVATION_MS", 50.0)
            if starvation_ms is None
            else float(starvation_ms)
        )
        # group-fair selection: items each tenant group earns per DRR round,
        # scaled by its weight (FISCO_DEVICE_GROUP_WEIGHTS="g0=2,g1=1");
        # deficits persist across dispatches while a group has backlog and
        # reset when it drains (classic DRR)
        self.group_quantum = max(1, int(_env("FISCO_DEVICE_GROUP_QUANTUM", 256.0)))
        self.group_weights: dict[str, float] = {}
        for part in os.environ.get("FISCO_DEVICE_GROUP_WEIGHTS", "").split(","):
            name, _, w = part.strip().partition("=")
            if name and w:
                try:
                    self.group_weights[name] = max(float(w), 1e-6)
                except ValueError:
                    pass
        self._deficit: dict[str, float] = {}
        self._drr_rotor = 0  # rotates the serving order across dispatches
        self._autostart = autostart
        # Condition over an EXPLICIT package-created RLock: a bare
        # Condition() allocates its lock inside threading.py, which the
        # lock-order factory filter skips — this way the plane's guard
        # participates in runtime lock-order recording and the raceguard
        # lockset, like every other package lock
        self._cv = threading.Condition(threading.RLock())
        self._pending: dict[str, list[PlaneRequest]] = {}
        self._exec_fns: dict[str, Callable] = {}
        self._thread: threading.Thread | None = None
        self._busy = False
        # stats (mutated under _cv; snapshot via stats())
        self.requests = 0
        self.dispatches = 0
        self.merged_requests = 0  # requests that shared a dispatch with others
        self.items = 0
        self._wait_ms: deque[float] = deque(maxlen=4096)

    @staticmethod
    def _default_window_ms() -> float:
        """2 ms on accelerator backends (noise against a tunneled device's
        ~100 ms round trip, and every merged straggler is a round trip
        saved); 0 on CPU-XLA backends, where dispatches are sub-ms native
        host loops and an idle-queue wait would tax every sequential batch
        call for nothing — bursts still coalesce while the worker is busy."""
        try:
            from ..crypto.suite import device_backend_is_cpu

            return 0.0 if device_backend_is_cpu() else 2.0
        except Exception:
            return 2.0

    # -- submission ----------------------------------------------------------

    def submit(self, op: str, payload, n: int, exec_fn: Callable) -> Future:
        """Queue one batch for op; returns a Future of the executor's
        per-request result. The caller's current lane — and trace context —
        are captured here."""
        from ..observability.tracer import TRACER

        req = PlaneRequest(
            op, payload, int(n), current_lane(), time.perf_counter(), Future(),
            ctx=TRACER.current_context() if TRACER.enabled else None,
            group=current_group(),
        )
        with self._cv:
            self._exec_fns.setdefault(op, exec_fn)
            self._pending.setdefault(op, []).append(req)
            self.requests += 1
            self.items += req.n
            if self._autostart:
                self._ensure_thread_locked()
            self._cv.notify_all()
        from ..utils.metrics import REGISTRY

        REGISTRY.counter_add(
            f'fisco_device_plane_requests_total{{op="{op}",lane="{req.lane}"}}',
            1.0,
            help="batches submitted to the device plane by op and lane",
        )
        return req.future

    # -- scheduler -----------------------------------------------------------

    def _ensure_thread_locked(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="device-plane", daemon=True
            )
            self._thread.start()

    def _group_ready(self, reqs: list[PlaneRequest], now: float) -> bool:
        age_ms = (now - reqs[0].t_enq) * 1e3
        return age_ms >= self.window_ms or sum(r.n for r in reqs) >= self.high_water

    def _pick_ready_locked(self, now: float):
        """Pop the dispatch-ready op group with the best claim, or None.

        Ready = window elapsed since the group's oldest request, or item
        count at/over high water. Among ready groups: starved groups (oldest
        request past starvation_ms) first, oldest first — the aging bound
        that makes draining starvation-free; then by best lane priority
        present in the group; ties to the oldest group.

        Returns ``(op, taken, deferred)``: multi-tenant queues are trimmed
        by :meth:`_select_fair`; requests it defers go back to the FRONT of
        the op's queue (enqueue times intact, so aging and window readiness
        survive) and are reported for the deferred counter."""
        best_op = None
        best_key = None
        for op, reqs in self._pending.items():
            if not reqs or not self._group_ready(reqs, now):
                continue
            age_ms = (now - reqs[0].t_enq) * 1e3
            if age_ms >= self.starvation_ms:
                key = (0, -age_ms, reqs[0].t_enq)
            else:
                lane_rank = min(LANES.get(r.lane, 1) for r in reqs)
                key = (1, lane_rank, reqs[0].t_enq)
            if best_key is None or key < best_key:
                best_key, best_op = key, op
        if best_op is None:
            return None
        taken, deferred = self._select_fair(self._pending.pop(best_op))
        if deferred:
            self._pending[best_op] = deferred
        return best_op, taken, deferred

    def _weight(self, group: str) -> float:
        return self.group_weights.get(group, 1.0)

    def _select_fair(self, reqs: list[PlaneRequest]):
        """Deficit-weighted round-robin across tenant groups within each
        priority lane: assemble one merged dispatch of at most
        ``high_water`` items (a single oversized request still dispatches
        whole — requests are indivisible), leaving the surplus queued.

        Single-tenant queues (the common case, and every pre-multi-group
        deployment) take the exact legacy path: all requests merge, no cap.
        Returns ``(taken, deferred)`` with FIFO order preserved inside each
        (lane, group); ``taken`` is never empty."""
        all_groups = {r.group for r in reqs}
        if len(all_groups) <= 1:
            return reqs, []
        cap = self.high_water
        # per-round quantum scaled so one round across n groups roughly
        # fills the cap — an unscaled quantum >= cap would let whichever
        # group serves first spend the whole dispatch before the others'
        # turns, which is exactly the monopoly DRR exists to prevent
        base_q = max(1, min(self.group_quantum, cap // len(all_groups)))
        by_lane: dict[int, dict[str, deque]] = {}
        for r in reqs:
            lane_q = by_lane.setdefault(LANES.get(r.lane, 1), {})
            lane_q.setdefault(r.group, deque()).append(r)
        taken: list[PlaneRequest] = []
        taken_ids: set[int] = set()
        total = 0
        rotor = self._drr_rotor
        self._drr_rotor += 1
        for rank in sorted(by_lane):
            queues = by_lane[rank]
            # rotate the serving order across dispatches so no group is
            # structurally first every time
            order = list(queues)
            start = rotor % len(order)
            order = order[start:] + order[:start]
            while total < cap and any(queues.values()):
                # one DRR round: every backlogged group earns one quantum,
                # then spends its deficit on its oldest requests — a huge
                # request accumulates rounds until funded, so nothing
                # starves, it just waits its proportional turn
                for g in order:
                    q = queues[g]
                    if not q:
                        continue
                    self._deficit[g] = (
                        self._deficit.get(g, 0.0) + base_q * self._weight(g)
                    )
                    while q and total < cap and self._deficit[g] >= q[0].n:
                        r = q.popleft()
                        self._deficit[g] -= r.n
                        taken.append(r)
                        taken_ids.add(id(r))
                        total += r.n
                    if total >= cap:
                        break
            if total >= cap:
                break
        deferred = [r for r in reqs if id(r) not in taken_ids]
        # classic DRR: a group that drained its backlog forfeits its credit
        # (deficits only persist across dispatches while traffic is queued)
        still_backlogged = {r.group for r in deferred}
        for g in {r.group for r in reqs} - still_backlogged:
            self._deficit.pop(g, None)
        return taken, deferred

    def _note_deferred(self, op: str, deferred: list[PlaneRequest]) -> None:
        """Export fairness decisions (called OUTSIDE the scheduler lock)."""
        from ..utils.metrics import REGISTRY

        if not deferred or not REGISTRY.enabled:
            return
        per_group: dict[str, int] = {}
        for r in deferred:
            per_group[r.group] = per_group.get(r.group, 0) + 1
        for g, n in per_group.items():
            REGISTRY.counter_add(
                f'fisco_device_plane_deferred_total{{group="{g}",op="{op}"}}',
                float(n),
                help="requests deferred to a later dispatch by group-fair "
                "deficit-round-robin (the multi-tenant backpressure signal)",
            )

    def _next_timeout_s(self, now: float) -> float | None:
        """Seconds until the earliest group becomes window-ready; None when
        the queue is empty (sleep until notified)."""
        deadlines = [
            reqs[0].t_enq + self.window_ms / 1e3
            for reqs in self._pending.values()
            if reqs
        ]
        if not deadlines:
            return None
        return max(min(deadlines) - now, 0.0)

    def _run(self) -> None:
        while True:
            with self._cv:
                picked = None
                while picked is None:
                    picked = self._pick_ready_locked(time.perf_counter())
                    if picked is None:
                        self._cv.wait(self._next_timeout_s(time.perf_counter()))
                op, reqs, deferred = picked
                self._busy = True
            try:
                from ..observability.pipeline import PIPELINE

                self._note_deferred(op, deferred)
                with PIPELINE.busy("device_plane"):
                    self._dispatch(op, reqs)
            finally:
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()

    def _dispatch(self, op: str, reqs: list[PlaneRequest]) -> None:
        # Once a group is popped from _pending, its futures exist only here:
        # EVERYTHING (telemetry included) runs under the catch-all so that no
        # failure mode can drop them unresolved — a lost future wedges a
        # caller blocked in .result() forever.
        try:
            from ..observability.tracer import TRACER

            # the merged-batch span: parented to the first absorbed caller,
            # LINKED to every caller it coalesced — the Perfetto view of N
            # transactions converging into one device program. Entering it
            # on this worker thread also hands the trace context to the
            # executor, so the device.<op> spans inside nest under it.
            # SAMPLED callers only: an unsampled first caller would noop
            # the whole batch span (suppressing every sampled caller's wait
            # record), and links to unsampled ctxs would dangle.
            ctxs = [
                r.ctx for r in reqs if r.ctx is not None and r.ctx.sampled
            ]
            span = TRACER.span(
                "device.plane.dispatch",
                parent=ctxs[0] if ctxs else None,
                links=ctxs,
                op=op,
                requests=len(reqs),
                items=sum(r.n for r in reqs),
            )
            with span:
                self._record_dispatch(op, reqs, getattr(span, "ctx", None))
                _tls.in_exec = True
                try:
                    results = self._exec_fns[op](reqs)
                finally:
                    _tls.in_exec = False
            if len(results) != len(reqs):
                raise RuntimeError(
                    f"plane executor for {op} returned {len(results)} results"
                    f" for {len(reqs)} requests"
                )
            for r, res in zip(reqs, results):
                r.future.set_result(res)
        except BaseException as e:  # noqa: BLE001 — futures must never wedge
            for r in reqs:
                if not r.future.done():
                    r.future.set_exception(e)

    def _record_dispatch(
        self, op: str, reqs: list[PlaneRequest], batch_ctx=None
    ) -> None:
        from ..observability.tracer import TRACER
        from ..utils.metrics import REGISTRY

        now = time.perf_counter()
        total = sum(r.n for r in reqs)
        with self._cv:
            self.dispatches += 1
            if len(reqs) > 1:
                self.merged_requests += len(reqs)
            for r in reqs:
                self._wait_ms.append((now - r.t_enq) * 1e3)
        if batch_ctx is not None:
            # close the loop from the caller side: each absorbed caller's
            # trace gets its queue wait as a span naming the merged batch's
            # span id (the fan-in edge, readable from either end)
            for r in reqs:
                if r.ctx is not None and r.ctx.sampled:
                    TRACER.record(
                        "device.plane.wait",
                        t0=r.t_enq,
                        dur=now - r.t_enq,
                        parent_ctx=r.ctx,
                        op=op,
                        lane=r.lane,
                        batch_span=f"{batch_ctx.span_id:016x}",
                    )
        from ..observability.device import (
            DEVICE_PHASE_BUCKETS_MS,
            LEDGER,
            device_obs_enabled,
        )

        # ledger attribution rides FISCO_DEVICE_OBS alone — it must keep
        # working with the metrics registry off (the telemetry A/B leg),
        # so it runs BEFORE the registry early-return. The queue segment
        # is labeled with the plane's dispatch op; the kernel spans inside
        # the executor carry compile/transfer/execute under their program
        # op names (ISSUE 13 phase decomposition).
        obs = device_obs_enabled()
        if obs:
            t_obs = time.perf_counter()
            LEDGER.note_phases(
                op, {"queue": sum((now - r.t_enq) * 1e3 for r in reqs)}
            )
            # fusion-frontier evidence (ISSUE 20): count the (prev, op)
            # dispatch edge — what --fusion-report joins with the static
            # per-program boundary costs
            LEDGER.note_adjacency(op)
            LEDGER.add_overhead(time.perf_counter() - t_obs)
        if not REGISTRY.enabled:
            return
        for r in reqs:
            wait_ms = (now - r.t_enq) * 1e3
            REGISTRY.observe(
                "fisco_device_plane_wait_ms",
                wait_ms,
                buckets=WAIT_BUCKETS_MS,
                help="queue wait from submit to dispatch, per lane",
                lane=r.lane,
            )
            if obs:
                REGISTRY.observe(
                    "fisco_device_phase_ms",
                    wait_ms,
                    buckets=DEVICE_PHASE_BUCKETS_MS,
                    help="device-plane time attribution per op: "
                    "queue / compile / transfer / execute segments",
                    op=op,
                    phase="queue",
                )
        REGISTRY.counter_add(
            f'fisco_device_plane_dispatch_total{{op="{op}"}}',
            1.0,
            help="merged device dispatches by op (requests/dispatches = "
            "coalesce ratio)",
        )
        if len(reqs) > 1:
            REGISTRY.counter_add(
                f'fisco_device_plane_coalesced_total{{op="{op}"}}',
                float(len(reqs)),
                help="requests that shared a merged dispatch with others",
            )
        from ..observability import BATCH_BUCKETS
        from ..ops.hash_common import bucket_batch

        REGISTRY.observe(
            "fisco_device_plane_batch_items",
            total,
            buckets=BATCH_BUCKETS,
            help="merged batch sizes dispatched by the plane",
            op=op,
        )
        bucket = bucket_batch(max(total, 1))
        REGISTRY.observe(
            "fisco_device_plane_bucket_occupancy",
            total / bucket if bucket else 0.0,
            buckets=OCCUPANCY_BUCKETS,
            help="real rows / bucket-padded rows per dispatch (batch dim"
            " only; pad waste = 1 - occupancy)",
            op=op,
        )

    # -- introspection -------------------------------------------------------

    def _depth(self) -> int:
        with self._cv:
            return sum(sum(r.n for r in reqs) for reqs in self._pending.values())

    def lane_depths(self) -> dict[str, int]:
        """Queued items by priority lane — the pipeline observatory's
        per-lane backpressure watermark (one probe, one lock round)."""
        with self._cv:
            out: dict[str, int] = {}
            for reqs in self._pending.values():
                for r in reqs:
                    out[r.lane] = out.get(r.lane, 0) + r.n
        for lane in LANES:
            out.setdefault(lane, 0)
        return out

    def coalesce_ratio(self) -> float:
        """Requests per device dispatch (≥ 1.0; 1.0 = no coalescing won)."""
        with self._cv:
            return self.requests / self.dispatches if self.dispatches else 1.0

    def wait_p99_ms(self) -> float:
        with self._cv:
            waits = sorted(self._wait_ms)
        if not waits:
            return 0.0
        return waits[min(len(waits) - 1, int(0.99 * len(waits)))]

    def stats(self) -> dict:
        with self._cv:
            return {
                "requests": self.requests,
                "dispatches": self.dispatches,
                "merged_requests": self.merged_requests,
                "items": self.items,
                "queue_depth": sum(
                    sum(r.n for r in reqs) for reqs in self._pending.values()
                ),
            }

    def drain(self, timeout: float = 60.0) -> bool:
        """Block until the queue is empty and no dispatch is in flight
        (bench/smoke hook); False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while any(self._pending.values()) or self._busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(min(remaining, 0.05))
        return True

    def _register_gauges(self) -> None:
        """Register the queue-depth gauge. Called for the process singleton
        only (get_plane) — the registry holds a strong ref to the closure
        and last-registration-wins, so a throwaway instance registering
        would hijack the metric and pin itself in memory."""
        try:
            from ..utils.metrics import REGISTRY

            REGISTRY.gauge_fn(
                "fisco_device_plane_queue_depth",
                lambda: float(self._depth()),
                help="items currently queued in the device plane",
            )
        except Exception as e:  # metrics layer disabled/unavailable — plane works
            from ..utils.log import note_swallowed

            note_swallowed("device.plane.gauge_register", e)


def plane_wait(fut: Future):
    """Block on a plane future, attributing the wait to the calling
    thread's ambient pipeline stage (``<stage> blocked_on=device_plane`` —
    the edge that says the admission/consensus/execute worker was parked
    behind the shared crypto engine, not doing its own work). Every crypto
    seam that queues into the plane resolves its future through here."""
    from ..observability.pipeline import PIPELINE

    with PIPELINE.blocked("device_plane"):
        return fut.result()


def plane_wait_deferred(fut: Future):
    """:func:`plane_wait` for two-phase hash futures whose resolved value
    is a deferred-sync callable: BOTH the queue wait and the device sync
    are the caller blocked behind the plane, so both run inside the one
    blocked attribution — otherwise the sync (the expensive half on a
    tunneled device) would count as the caller's busy time."""
    from ..observability.pipeline import PIPELINE

    with PIPELINE.blocked("device_plane"):
        return fut.result()()


_PLANE: DevicePlane | None = None
_PLANE_LOCK = threading.Lock()


def get_plane() -> DevicePlane:
    """The process-wide plane every crypto seam shares (coalescing across
    callers is the whole point — per-caller planes would recreate the
    per-caller batch problem)."""
    global _PLANE
    if _PLANE is None:
        with _PLANE_LOCK:
            if _PLANE is None:
                _PLANE = DevicePlane()
                _PLANE._register_gauges()
    return _PLANE
