"""Lease-based leader election over a shared SQLite coordination file.

Reference: bcos-leader-election/src/LeaderElection.cpp — etcd campaign with
a TTL lease, keepalive renewals, and a watcher that fires on leadership
change (Max-mode SchedulerManager/ExecutorManager failover).  No etcd exists
in this image; a shared SQLite file gives the same primitives to co-located
processes (BEGIN IMMEDIATE = the atomic compare-and-swap), and this module
is the seam where an etcd/consul client would plug in for multi-host.

Semantics preserved from the reference:
- `campaign()` claims the key iff it is unowned or its lease expired;
- a keepalive thread renews at ttl/3 (CampaignConfig keep-alive);
- losing the lease (e.g. the process stalls past the TTL) demotes the node
  and fires `on_change(False)`; a new leader fires its own `on_change(True)`;
- `resign()` releases immediately (LeaderElection::deregister).
"""

from __future__ import annotations

import sqlite3
import threading
import time
from typing import Callable

from ..utils.log import get_logger

_log = get_logger("election")


class LeaderElection:
    def __init__(
        self,
        path: str,
        key: str,
        member_id: str,
        lease_ttl: float = 3.0,
    ):
        self.path = path
        self.key = key
        self.member_id = member_id
        self.lease_ttl = lease_ttl
        self._conn = sqlite3.connect(path, check_same_thread=False, timeout=10)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS election ("
            " k TEXT PRIMARY KEY, leader TEXT NOT NULL, expiry REAL NOT NULL)"
        )
        self._conn.commit()
        self._lock = threading.RLock()
        self._leader = False
        self._lease_expiry = 0.0  # last CONFIRMED lease expiry
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.on_change: Callable[[bool], None] | None = None

    # -- campaign --------------------------------------------------------------

    def _try_claim(self) -> bool:
        now = time.time()
        with self._lock:
            try:
                self._conn.execute("BEGIN IMMEDIATE")
                row = self._conn.execute(
                    "SELECT leader, expiry FROM election WHERE k=?", (self.key,)
                ).fetchone()
                if row is None or row[1] < now or row[0] == self.member_id:
                    self._conn.execute(
                        "INSERT OR REPLACE INTO election (k, leader, expiry)"
                        " VALUES (?, ?, ?)",
                        (self.key, self.member_id, now + self.lease_ttl),
                    )
                    self._conn.commit()
                    self._lease_expiry = now + self.lease_ttl
                    return True
                self._conn.commit()
                return False
            except sqlite3.OperationalError:
                try:
                    self._conn.rollback()
                except sqlite3.Error:
                    pass
                # renewal unconfirmed: leadership only holds while the LAST
                # CONFIRMED lease is still live — acting on stale belief past
                # the TTL is split-brain (another member may have claimed)
                return self._leader and time.time() < self._lease_expiry

    def campaign(self) -> bool:
        """Start campaigning; returns current leadership immediately and
        keeps renewing/retrying on the keepalive thread."""
        self._set_leader(self._try_claim())
        # spawn under the lock: two concurrent campaign() calls must not
        # each start a keepalive thread (double renewals would hammer the
        # store and fight over the lease)
        with self._lock:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._keepalive, name=f"election-{self.key}",
                    daemon=True,
                )
                self._thread.start()
        return self._leader

    def _keepalive(self) -> None:
        interval = max(0.05, self.lease_ttl / 3)
        while not self._stop.wait(interval):
            self._set_leader(self._try_claim())

    def _set_leader(self, now_leader: bool) -> None:
        with self._lock:
            changed = now_leader != self._leader
            self._leader = now_leader
        if changed:
            _log.info(
                "%s %s leadership of %s",
                self.member_id,
                "acquired" if now_leader else "lost",
                self.key,
            )
            if self.on_change is not None:
                try:
                    self.on_change(now_leader)
                except Exception:
                    _log.exception("leadership-change callback failed")

    # -- queries / teardown ----------------------------------------------------

    def is_leader(self) -> bool:
        with self._lock:
            return self._leader

    def current_leader(self) -> str | None:
        with self._lock:
            row = self._conn.execute(
                "SELECT leader, expiry FROM election WHERE k=?", (self.key,)
            ).fetchone()
        if row is None or row[1] < time.time():
            return None
        return row[0]

    def resign(self) -> None:
        with self._lock:
            if self._leader:
                self._conn.execute(
                    "DELETE FROM election WHERE k=? AND leader=?",
                    (self.key, self.member_id),
                )
                self._conn.commit()
        self._set_leader(False)

    def stop(self) -> None:
        self._stop.set()
        # claim the thread under the lock, join OUTSIDE it — the keepalive
        # thread takes self._lock in _try_claim, so joining under the lock
        # would deadlock against the very thread being joined
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)
        self.resign()
        with self._lock:
            self._conn.close()
