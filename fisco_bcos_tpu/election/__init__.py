"""Leader election — master failover coordination.

Reference: bcos-leader-election (ElectionConfig.h:26-47, LeaderElection.cpp:
etcd campaign + lease keepalive + watcher).
"""

from .leader_election import LeaderElection

__all__ = ["LeaderElection"]
