"""In-memory transactional backend (tests + fakers; FakeKVStorage analog)."""

from __future__ import annotations

import threading
from typing import Iterator

from .entry import Entry
from .interfaces import TransactionalStorage, TraversableStorage, TwoPCParams


class MemoryStorage(TransactionalStorage):
    def __init__(self) -> None:
        self._data: dict[tuple[str, bytes], Entry] = {}
        self._pending: dict[int, dict[tuple[str, bytes], Entry]] = {}
        self._lock = threading.RLock()

    def get_row(self, table: str, key: bytes) -> Entry | None:
        with self._lock:
            e = self._data.get((table, bytes(key)))
            return None if e is None or e.deleted else e.copy()

    def set_row(self, table: str, key: bytes, entry: Entry) -> None:
        with self._lock:
            self._data[(table, bytes(key))] = entry.copy()

    def get_primary_keys(self, table: str) -> list[bytes]:
        with self._lock:
            return sorted(
                k for (t, k), e in self._data.items() if t == table and not e.deleted
            )

    def traverse(self) -> Iterator[tuple[str, bytes, Entry]]:
        with self._lock:
            items = list(self._data.items())
        for (t, k), e in items:
            yield t, k, e.copy()

    # -- 2PC ------------------------------------------------------------

    def prepare(self, params: TwoPCParams, writes: TraversableStorage) -> None:
        """Stage writes for `number`. PER-KEY MERGE, not slot replacement:
        a Max-form block is prepared by several executor participants, each
        staging its own (disjoint) dirty set into the same number — TiKV's
        multi-participant prewrite semantics. Re-preparing the same key
        (block re-execution after a term switch) overwrites per key."""
        with self._lock:
            slot = self._pending.setdefault(params.number, {})
            for t, k, e in writes.traverse():
                slot[(t, bytes(k))] = e.copy()

    def commit(self, params: TwoPCParams) -> None:
        with self._lock:
            for (t, k), e in self._pending.pop(params.number, {}).items():
                self._data[(t, k)] = e

    def rollback(self, params: TwoPCParams) -> None:
        with self._lock:
            self._pending.pop(params.number, None)

    def pending_numbers(self) -> list[int]:
        with self._lock:
            return sorted(self._pending)
