"""Storage interfaces.

Reference: bcos-framework/storage/StorageInterface.h — read/write interface
plus the transactional (2PC) extension implemented by the durable backends
(RocksDBStorage.cpp asyncPrepare/asyncCommit/asyncRollback) and driven by the
scheduler's commit (TwoPCParams). Python methods are synchronous; async
orchestration happens at the node layer.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Iterator

from .entry import Entry


@dataclass
class TwoPCParams:
    """bcos-framework/storage/StorageInterface.h TwoPCParams analog."""

    number: int = 0
    primary_key: str = ""
    timestamp: int = 0


class StorageInterface:
    def get_row(self, table: str, key: bytes) -> Entry | None:
        raise NotImplementedError

    def get_rows(self, table: str, keys: Iterable[bytes]) -> list[Entry | None]:
        return [self.get_row(table, k) for k in keys]

    def set_row(self, table: str, key: bytes, entry: Entry) -> None:
        raise NotImplementedError

    def set_rows(self, table: str, items: list[tuple[bytes, Entry]]) -> None:
        """Bulk write; durable backends commit all rows in one transaction
        (hot paths like pool persistence write thousands of rows per block)."""
        for key, entry in items:
            self.set_row(table, key, entry)

    def get_primary_keys(self, table: str) -> list[bytes]:
        raise NotImplementedError


class TraversableStorage(StorageInterface):
    def traverse(self) -> Iterator[tuple[str, bytes, Entry]]:
        """Yield (table, key, entry) for every locally-held row."""
        raise NotImplementedError


class TransactionalStorage(StorageInterface):
    """Durable backend with two-phase commit."""

    def prepare(self, params: TwoPCParams, writes: TraversableStorage) -> None:
        raise NotImplementedError

    def commit(self, params: TwoPCParams) -> None:
        raise NotImplementedError

    def rollback(self, params: TwoPCParams) -> None:
        raise NotImplementedError

    def pending_numbers(self) -> list[int]:
        """Block numbers with a prepared-but-unresolved 2PC slot.

        Part of the interface because the distributed recovery plane
        (DistributedStorage.recover_in_flight) DEPENDS on every backend
        answering truthfully — a backend silently reporting [] would make
        recovery skip its stuck slots forever."""
        raise NotImplementedError
