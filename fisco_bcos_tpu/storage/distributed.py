"""Distributed storage — sharded KV over multiple storage service processes.

Reference: bcos-storage/bcos-storage/TiKVStorage.{h,cpp}: the Pro/Max
deployments back the chain on a distributed KV store (TiKV regions +
two-phase commit via a primary lock, connection-loss switch handler :582).
This analog reaches the same capability TPU-natively cheap: N independent
StorageService processes are the "regions", a deterministic hash partition
(table, key) → shard replaces PD placement, and the chain's own block-number
2PC (prepare/commit/rollback fan-out, primary-first) replaces Percolator.

Semantics:
- `get_row`/`set_row` route by ``shard_of(table, key)``; whole-table scans
  (`get_primary_keys`) fan out and merge.
- `prepare(params, writes)` partitions the write set and prepares every
  shard — shard 0 is the PRIMARY (TiKV's primary-lock role): it is prepared
  first and committed first; a crash between phases leaves secondaries
  recoverable by re-driving the same block number (prepare is idempotent,
  keyed on number).
- Any transport loss fires ``switch_handler`` (once per outage episode)
  before the error propagates — the same scheduler term-switch seam as
  :class:`fisco_bcos_tpu.service.storage_service.RemoteStorage`.

System tables (s_*) are small and hot; they shard like any other row — reads
are one round trip either way, and one routing rule means a restarted node
finds every row exactly where it wrote it (placement is per-node plumbing;
consensus state roots are computed from overlay contents upstream of this
layer, so shard layout never leaks into them).
"""

from __future__ import annotations

import hashlib
from typing import Iterator

from ..service.rpc import ServiceConnectionError
from ..service.storage_service import RemoteStorage
from ..storage.entry import Entry
from ..storage.interfaces import (
    TransactionalStorage,
    TraversableStorage,
    TwoPCParams,
)
from ..utils.log import get_logger

_log = get_logger("dist-storage")


class _RowsView(TraversableStorage):
    def __init__(self, rows):
        self._rows = rows

    def traverse(self) -> Iterator:
        yield from self._rows


class DistributedStorage(TransactionalStorage):
    """TransactionalStorage over N sharded StorageService endpoints."""

    def __init__(self, endpoints: list[tuple[str, int]], timeout: float = 60.0):
        if not endpoints:
            raise ValueError("DistributedStorage needs at least one endpoint")
        self.shards = [RemoteStorage(h, p, timeout) for h, p in endpoints]
        self.switch_handler = None
        for sh in self.shards:
            # every shard loss funnels into ONE switch seam; RemoteStorage
            # dedups per-shard episodes, this layer just forwards
            sh.set_switch_handler(self._on_shard_loss)

    def set_switch_handler(self, fn) -> None:
        self.switch_handler = fn

    def _on_shard_loss(self) -> None:
        handler = self.switch_handler
        if handler is not None:
            handler()

    # -- routing ------------------------------------------------------------

    def shard_of(self, table: str, key: bytes) -> int:
        """Deterministic placement: blake2b of (table, key) mod N — stable
        across restarts for a fixed shard count (resharding is a migration,
        not a runtime event; TiKV's PD does it live, out of scope)."""
        h = hashlib.blake2b(
            table.encode() + b"\x00" + bytes(key), digest_size=8
        ).digest()
        return int.from_bytes(h, "big") % len(self.shards)

    # -- KV surface ---------------------------------------------------------

    def get_row(self, table: str, key: bytes) -> Entry | None:
        return self.shards[self.shard_of(table, key)].get_row(table, key)

    def set_row(self, table: str, key: bytes, entry: Entry) -> None:
        self.shards[self.shard_of(table, key)].set_row(table, key, entry)

    def set_rows(self, table: str, items) -> None:
        by_shard: dict[int, list] = {}
        for k, e in items:
            by_shard.setdefault(self.shard_of(table, k), []).append((k, e))
        for idx, part in by_shard.items():
            self.shards[idx].set_rows(table, part)

    def get_primary_keys(self, table: str) -> list[bytes]:
        keys: list[bytes] = []
        for sh in self.shards:
            keys.extend(sh.get_primary_keys(table))
        return sorted(set(keys))

    # -- 2PC (TiKVStorage asyncPrepare/asyncCommit/asyncRollback) -----------

    def prepare(self, params: TwoPCParams, writes: TraversableStorage) -> None:
        parts: dict[int, list] = {i: [] for i in range(len(self.shards))}
        for t, k, e in writes.traverse():
            parts[self.shard_of(t, k)].append((t, k, e))
        # primary (shard 0) first — its prepared marker is the commit
        # point-of-no-return witness, like TiKV's primary lock
        for idx in range(len(self.shards)):
            self.shards[idx].prepare(params, _RowsView(parts[idx]))

    def commit(self, params: TwoPCParams) -> None:
        for idx in range(len(self.shards)):  # primary first
            self.shards[idx].commit(params)

    def rollback(self, params: TwoPCParams) -> None:
        errs = 0
        for sh in self.shards:
            try:
                sh.rollback(params)
            except ServiceConnectionError:
                errs += 1  # a dead shard has nothing durable to roll back
        if errs:
            _log.warning("rollback skipped %d unreachable shards", errs)

    def close(self) -> None:
        for sh in self.shards:
            sh.close()
