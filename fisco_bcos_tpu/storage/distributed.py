"""Distributed storage — sharded KV over multiple storage service processes.

Reference: bcos-storage/bcos-storage/TiKVStorage.{h,cpp}: the Pro/Max
deployments back the chain on a distributed KV store (TiKV regions +
two-phase commit via a primary lock, connection-loss switch handler :582).
This analog reaches the same capability TPU-natively cheap: N independent
StorageService processes are the "regions", a deterministic hash partition
(table, key) → shard replaces PD placement, and the chain's own block-number
2PC (prepare/commit/rollback fan-out, primary-first) replaces Percolator.

Semantics:
- `get_row`/`set_row` route by ``shard_of(table, key)``; whole-table scans
  (`get_primary_keys`) fan out and merge.
- `prepare(params, writes)` partitions the write set and prepares every
  shard — shard 0 is the PRIMARY (TiKV's primary-lock role): it is prepared
  first and committed first; a crash between phases leaves secondaries
  recoverable by re-driving the same block number (prepare is idempotent,
  keyed on number).
- Any transport loss fires ``switch_handler`` (once per outage episode)
  before the error propagates — the same scheduler term-switch seam as
  :class:`fisco_bcos_tpu.service.storage_service.RemoteStorage`.

System tables (s_*) are small and hot; they shard like any other row — reads
are one round trip either way, and one routing rule means a restarted node
finds every row exactly where it wrote it (placement is per-node plumbing;
consensus state roots are computed from overlay contents upstream of this
layer, so shard layout never leaks into them).
"""

from __future__ import annotations

import hashlib
from typing import Iterator

from ..service.rpc import ServiceConnectionError
from ..service.storage_service import RemoteStorage
from ..storage.entry import Entry
from ..storage.interfaces import (
    TransactionalStorage,
    TraversableStorage,
    TwoPCParams,
)
from ..utils.log import get_logger

_log = get_logger("dist-storage")


class _RowsView(TraversableStorage):
    def __init__(self, rows):
        self._rows = rows

    def traverse(self) -> Iterator:
        yield from self._rows


class DistributedStorage(TransactionalStorage):
    """TransactionalStorage over N sharded StorageService endpoints."""

    def __init__(self, endpoints: list[tuple[str, int]], timeout: float = 60.0):
        if not endpoints:
            raise ValueError("DistributedStorage needs at least one endpoint")
        self.shards = [RemoteStorage(h, p, timeout) for h, p in endpoints]
        self.switch_handler = None
        for sh in self.shards:
            # every shard loss funnels into ONE switch seam; RemoteStorage
            # dedups per-shard episodes, this layer just forwards
            sh.set_switch_handler(self._on_shard_loss)

    def set_switch_handler(self, fn) -> None:
        self.switch_handler = fn

    def _on_shard_loss(self) -> None:
        # an outage can strand prepared-but-unresolved slots: arm the
        # recovery pass so the next 2PC op resolves them before new work
        self.mark_needs_recovery()
        handler = self.switch_handler
        if handler is not None:
            handler()

    # -- routing ------------------------------------------------------------

    def shard_of(self, table: str, key: bytes) -> int:
        """Deterministic placement: blake2b of (table, key) mod N — stable
        across restarts for a fixed shard count (resharding is a migration,
        not a runtime event; TiKV's PD does it live, out of scope)."""
        h = hashlib.blake2b(
            table.encode() + b"\x00" + bytes(key), digest_size=8
        ).digest()
        return int.from_bytes(h, "big") % len(self.shards)

    # -- KV surface ---------------------------------------------------------

    def get_row(self, table: str, key: bytes) -> Entry | None:
        return self.shards[self.shard_of(table, key)].get_row(table, key)

    def set_row(self, table: str, key: bytes, entry: Entry) -> None:
        self.shards[self.shard_of(table, key)].set_row(table, key, entry)

    def set_rows(self, table: str, items) -> None:
        by_shard: dict[int, list] = {}
        for k, e in items:
            by_shard.setdefault(self.shard_of(table, k), []).append((k, e))
        for idx, part in by_shard.items():
            self.shards[idx].set_rows(table, part)

    def get_primary_keys(self, table: str) -> list[bytes]:
        keys: list[bytes] = []
        for sh in self.shards:
            keys.extend(sh.get_primary_keys(table))
        return sorted(set(keys))

    # -- 2PC (TiKVStorage asyncPrepare/asyncCommit/asyncRollback) -----------

    # the primary's commit WITNESS row: staged with the primary's slot so it
    # lands atomically with the primary commit; recovery reads it to decide
    # roll-forward vs roll-back (TiKV: secondary locks resolve by checking
    # the primary lock/commit record)
    _WITNESS_TABLE = "s_2pc_witness"

    @staticmethod
    def _witness_key(number: int) -> bytes:
        return b"commit-%d" % number

    def prepare(self, params: TwoPCParams, writes: TraversableStorage) -> None:
        # recovery may freely resolve params.number here: we are about to
        # RE-stage it, so an abandoned old slot rolling back is the point
        self.recover_in_flight_if_needed()
        parts: dict[int, list] = {i: [] for i in range(len(self.shards))}
        for t, k, e in writes.traverse():
            parts[self.shard_of(t, k)].append((t, k, e))
        # primary (shard 0) first — its prepared slot carries the commit
        # witness, so the witness becomes durable exactly when the primary
        # commits (the point of no return, like TiKV's primary lock)
        parts[0].append(
            (
                self._WITNESS_TABLE,
                self._witness_key(params.number),
                Entry().set(b"1"),
            )
        )
        for idx in range(len(self.shards)):
            self.shards[idx].prepare(params, _RowsView(parts[idx]))

    def commit(self, params: TwoPCParams) -> None:
        # NEVER let recovery touch the number being committed: its slot is
        # legitimately pending RIGHT NOW and has no witness yet — an armed
        # recovery pass would roll it back and this commit would "succeed"
        # with empty slots, silently losing the block's writes
        self.recover_in_flight_if_needed(exclude=params.number)
        for idx in range(len(self.shards)):  # primary first
            self.shards[idx].commit(params)
        # retire the PREVIOUS block's witness: a commit of N proves N-1 is
        # fully resolved, so at most one live witness row remains instead
        # of one per block forever
        if params.number > 0:
            from .entry import EntryStatus

            self.shards[0].set_row(
                self._WITNESS_TABLE,
                self._witness_key(params.number - 1),
                Entry(status=EntryStatus.DELETED),
            )

    # -- in-flight 2PC recovery (the re-replay across a switch) -------------

    def mark_needs_recovery(self) -> None:
        """Arm a recovery pass for the next 2PC operation — wired to the
        same outage episodes that fire the switch handler."""
        self._needs_recovery = True

    def recover_in_flight_if_needed(self, exclude: int | None = None) -> None:
        if getattr(self, "_needs_recovery", False):
            self._needs_recovery = False
            try:
                self.recover_in_flight(exclude=exclude)
            except ServiceConnectionError:
                # a shard is still down: stay armed, retry on next 2PC op
                self._needs_recovery = True
                raise

    def recover_in_flight(self, exclude: int | None = None) -> None:
        """Resolve prepared-but-unresolved slots left by a crash/outage
        between phases: a slot whose number has the primary's commit
        witness rolls FORWARD (the coordinator had passed the point of no
        return), anything else rolls back — then consensus re-drives the
        block (TiKVStorage.cpp:582's switch handler + lock resolution)."""
        pending: set[int] = set()
        for sh in self.shards:
            pending.update(sh.pending_numbers())
        pending.discard(exclude)  # the caller owns that number's decision
        for n in sorted(pending):
            witness = self.shards[0].get_row(
                self._WITNESS_TABLE, self._witness_key(n)
            )
            params = TwoPCParams(number=n)
            if witness is not None:
                _log.warning("2PC recovery: rolling FORWARD block %d", n)
                for sh in self.shards:
                    sh.commit(params)
            else:
                _log.warning("2PC recovery: rolling back block %d", n)
                for sh in self.shards:
                    sh.rollback(params)

    def rollback(self, params: TwoPCParams) -> None:
        errs = 0
        for sh in self.shards:
            try:
                sh.rollback(params)
            except ServiceConnectionError:
                errs += 1  # a dead shard has nothing durable to roll back
        # an explicit rollback declares the number DEAD: retire any witness
        # a partial commit attempt may have left, or a later crash would
        # roll a never-decided re-prepare forward off the stale marker
        try:
            from .entry import EntryStatus

            self.shards[0].set_row(
                self._WITNESS_TABLE,
                self._witness_key(params.number),
                Entry(status=EntryStatus.DELETED),
            )
        except ServiceConnectionError:
            errs += 1
        if errs:
            _log.warning("rollback skipped %d unreachable shards", errs)

    def pending_numbers(self) -> list[int]:
        out: set[int] = set()
        for sh in self.shards:
            out.update(sh.pending_numbers())
        return sorted(out)

    def close(self) -> None:
        for sh in self.shards:
            sh.close()
