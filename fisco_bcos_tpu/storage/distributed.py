"""Distributed storage — sharded KV over multiple storage service processes.

Reference: bcos-storage/bcos-storage/TiKVStorage.{h,cpp}: the Pro/Max
deployments back the chain on a distributed KV store (TiKV regions +
two-phase commit via a primary lock, connection-loss switch handler :582).
This analog reaches the same capability TPU-natively cheap: N independent
StorageService processes are the "regions", a deterministic hash partition
(table, key) → shard replaces PD placement, and the chain's own block-number
2PC (prepare/commit/rollback fan-out, primary-first) replaces Percolator.

Semantics:
- `get_row`/`set_row` route by ``shard_of(table, key)``; whole-table scans
  (`get_primary_keys`) fan out and merge.
- `prepare(params, writes)` partitions the write set and prepares every
  shard — shard 0 is the PRIMARY (TiKV's primary-lock role): it is prepared
  first and committed first; a crash between phases leaves secondaries
  recoverable by re-driving the same block number (prepare is idempotent,
  keyed on number).
- Any transport loss fires ``switch_handler`` (once per outage episode)
  before the error propagates — the same scheduler term-switch seam as
  :class:`fisco_bcos_tpu.service.storage_service.RemoteStorage`.

System tables (s_*) are small and hot; they shard like any other row — reads
are one round trip either way, and one routing rule means a restarted node
finds every row exactly where it wrote it (placement is per-node plumbing;
consensus state roots are computed from overlay contents upstream of this
layer, so shard layout never leaks into them).
"""

from __future__ import annotations

import hashlib
from typing import Iterator

from ..observability.storagelog import STORAGE as _OBS
from ..resilience import HEALTH
from ..service.rpc import ServiceConnectionError, ServiceRemoteError
from ..service.storage_service import RemoteStorage
from ..storage.entry import Entry
from ..storage.interfaces import (
    TransactionalStorage,
    TraversableStorage,
    TwoPCParams,
)
from ..utils.log import get_logger

_log = get_logger("dist-storage")


class _RowsView(TraversableStorage):
    def __init__(self, rows):
        self._rows = rows

    def traverse(self) -> Iterator:
        yield from self._rows


class DistributedStorage(TransactionalStorage):
    """TransactionalStorage over N sharded StorageService endpoints."""

    # health-registry component for the whole backend (GET /health)
    _COMPONENT = "storage"

    def __init__(self, endpoints: list[tuple[str, int]], timeout: float = 60.0):
        if not endpoints:
            raise ValueError("DistributedStorage needs at least one endpoint")
        self.shards = [RemoteStorage(h, p, timeout) for h, p in endpoints]
        self.switch_handler = None
        self._down: set[int] = set()  # shard idxs in a live outage episode
        # rollback re-drive ledger: number -> task idxs that could not be
        # reached when the number was declared dead (shard idx, or -1 for
        # the primary's witness retirement). A revived shard must re-run
        # these before any witness-based roll-forward, or it could
        # resurrect a dead block number.
        self._rolled_back: dict[int, set[int]] = {}
        # rollback listeners: cb(number) fired on EVERY rollback attempt of
        # a declared-dead number — initial drive and re-drives alike — so
        # read-side caches (the ProofPlane's frozen trees) evict the height
        # eagerly instead of waiting for their serve-time identity checks
        self.on_rollback: list = []
        for i, sh in enumerate(self.shards):
            # every shard loss funnels into ONE switch seam; RemoteStorage
            # dedups per-shard episodes, this layer scopes them by index
            sh.set_switch_handler(lambda i=i: self._on_shard_loss(i))
            sh.set_heal_handler(lambda i=i: self._on_shard_heal(i))

    def set_switch_handler(self, fn) -> None:
        self.switch_handler = fn

    def _on_shard_loss(self, idx: int) -> None:
        self._down.add(idx)
        HEALTH.degrade(
            self._COMPONENT,
            f"shard {idx} unreachable ({len(self.shards) - len(self._down)}"
            f"/{len(self.shards)} up)",
        )
        # an outage can strand prepared-but-unresolved slots: arm the
        # recovery pass so the next 2PC op resolves them before new work
        self.mark_needs_recovery()
        handler = self.switch_handler
        if handler is not None:
            handler()

    def _on_shard_heal(self, idx: int) -> None:
        self._down.discard(idx)
        if not self._down:
            HEALTH.ok(self._COMPONENT, f"shard {idx} back, all shards up")

    # -- routing ------------------------------------------------------------

    def shard_of(self, table: str, key: bytes) -> int:
        """Deterministic placement: blake2b of (table, key) mod N — stable
        across restarts for a fixed shard count (resharding is a migration,
        not a runtime event; TiKV's PD does it live, out of scope)."""
        h = hashlib.blake2b(
            table.encode() + b"\x00" + bytes(key), digest_size=8
        ).digest()
        return int.from_bytes(h, "big") % len(self.shards)

    # -- KV surface ---------------------------------------------------------

    def get_row(self, table: str, key: bytes) -> Entry | None:
        return self.shards[self.shard_of(table, key)].get_row(table, key)

    def set_row(self, table: str, key: bytes, entry: Entry) -> None:
        self.shards[self.shard_of(table, key)].set_row(table, key, entry)

    def set_rows(self, table: str, items) -> None:
        by_shard: dict[int, list] = {}
        for k, e in items:
            by_shard.setdefault(self.shard_of(table, k), []).append((k, e))
        for idx, part in by_shard.items():
            self.shards[idx].set_rows(table, part)

    def get_primary_keys(self, table: str) -> list[bytes]:
        keys: list[bytes] = []
        for sh in self.shards:
            keys.extend(sh.get_primary_keys(table))
        return sorted(set(keys))

    # -- 2PC (TiKVStorage asyncPrepare/asyncCommit/asyncRollback) -----------

    # the primary's commit WITNESS row: staged with the primary's slot so it
    # lands atomically with the primary commit; recovery reads it to decide
    # roll-forward vs roll-back (TiKV: secondary locks resolve by checking
    # the primary lock/commit record)
    _WITNESS_TABLE = "s_2pc_witness"

    @staticmethod
    def _witness_key(number: int) -> bytes:
        return b"commit-%d" % number

    def prepare(self, params: TwoPCParams, writes: TraversableStorage) -> None:
        # recovery may freely resolve params.number here: we are about to
        # RE-stage it, so an abandoned old slot rolling back is the point
        self.recover_in_flight_if_needed()
        # a re-prepare supersedes an earlier dead-number declaration: the
        # slot (and witness) about to be staged belong to the NEW decision,
        # so a leftover re-drive task must not kill them later
        self._rolled_back.pop(params.number, None)
        parts: dict[int, list] = {i: [] for i in range(len(self.shards))}
        for t, k, e in writes.traverse():
            parts[self.shard_of(t, k)].append((t, k, e))
        # primary (shard 0) first — its prepared slot carries the commit
        # witness, so the witness becomes durable exactly when the primary
        # commits (the point of no return, like TiKV's primary lock)
        parts[0].append(
            (
                self._WITNESS_TABLE,
                self._witness_key(params.number),
                Entry().set(b"1"),
            )
        )
        for idx in range(len(self.shards)):
            if not _OBS.enabled:
                self.shards[idx].prepare(params, _RowsView(parts[idx]))
                continue
            # staged-byte attribution by encode-delta across the leg: the
            # RemoteStorage client encodes every row for the wire inside
            # this call, so the codec counter's movement IS the shard's
            # staged payload — no second encode pass
            t0 = _OBS.clock()
            b0 = _OBS.encode_bytes_now()
            self.shards[idx].prepare(params, _RowsView(parts[idx]))
            _OBS.shard_note(
                "prepare",
                idx,
                (_OBS.clock() - t0) * 1e3,
                rows=len(parts[idx]),
                n_bytes=_OBS.encode_bytes_now() - b0,
            )

    def commit(self, params: TwoPCParams) -> None:
        # NEVER let recovery touch the number being committed: its slot is
        # legitimately pending RIGHT NOW and has no witness yet — an armed
        # recovery pass would roll it back and this commit would "succeed"
        # with empty slots, silently losing the block's writes
        self.recover_in_flight_if_needed(exclude=params.number)
        for idx in range(len(self.shards)):  # primary first
            if not _OBS.enabled:
                self.shards[idx].commit(params)
                continue
            t0 = _OBS.clock()
            self.shards[idx].commit(params)
            _OBS.shard_note("commit", idx, (_OBS.clock() - t0) * 1e3)
        # retire the PREVIOUS block's witness: a commit of N proves N-1 is
        # fully resolved, so at most one live witness row remains instead
        # of one per block forever
        if params.number > 0:
            from .entry import EntryStatus

            self.shards[0].set_row(
                self._WITNESS_TABLE,
                self._witness_key(params.number - 1),
                Entry(status=EntryStatus.DELETED),
            )

    # -- in-flight 2PC recovery (the re-replay across a switch) -------------

    def mark_needs_recovery(self) -> None:
        """Arm a recovery pass for the next 2PC operation — wired to the
        same outage episodes that fire the switch handler."""
        self._needs_recovery = True

    def recover_in_flight_if_needed(self, exclude: int | None = None) -> None:
        if getattr(self, "_needs_recovery", False):
            self._needs_recovery = False
            try:
                self.recover_in_flight(exclude=exclude)
            except ServiceConnectionError:
                # a shard is still down: stay armed, retry on next 2PC op
                self._needs_recovery = True
                raise
            if self._rolled_back:
                # some dead-number re-drives still face unreachable shards:
                # stay armed so the next 2PC op tries again
                self._needs_recovery = True

    def recover_in_flight(self, exclude: int | None = None) -> None:
        """Resolve prepared-but-unresolved slots left by a crash/outage
        between phases: a slot whose number has the primary's commit
        witness rolls FORWARD (the coordinator had passed the point of no
        return), anything else rolls back — then consensus re-drives the
        block (TiKVStorage.cpp:582's switch handler + lock resolution).

        Numbers explicitly declared dead by :meth:`rollback` while some
        shards were unreachable are re-driven FIRST and never roll forward
        off a stale witness — a revived shard cannot resurrect them."""
        self._retry_unresolved_rollbacks(exclude=exclude)
        pending: set[int] = set()
        for sh in self.shards:
            pending.update(sh.pending_numbers())
        pending.discard(exclude)  # the caller owns that number's decision
        for n in sorted(pending):
            if n in self._rolled_back:
                continue  # declared dead; its re-drive is still unreachable
            witness = self.shards[0].get_row(
                self._WITNESS_TABLE, self._witness_key(n)
            )
            params = TwoPCParams(number=n)
            if witness is not None:
                _log.warning("2PC recovery: rolling FORWARD block %d", n)
                for sh in self.shards:
                    sh.commit(params)
            else:
                _log.warning("2PC recovery: rolling back block %d", n)
                for sh in self.shards:
                    sh.rollback(params)

    def _retry_unresolved_rollbacks(self, exclude: int | None = None) -> None:
        """Re-drive rollbacks that skipped unreachable shards (the recorded
        skip set), so a revived shard's stale slot/witness dies before it
        can influence witness-based recovery."""
        for n in sorted(self._rolled_back):
            if n == exclude:
                continue  # the caller is re-deciding this number right now
            _log.warning("re-driving rollback of block %d on revived shards", n)
            self.rollback(TwoPCParams(number=n))

    def rollback(self, params: TwoPCParams) -> None:
        number = params.number
        # resume from the recorded skip set when this is a re-drive; task
        # -1 is the primary's witness retirement, ordered FIRST so the
        # number loses roll-forward eligibility before anything else. The
        # record is only REPLACED at the end, never popped up front: an
        # unexpected exception mid-loop must not lose the dead-number
        # declaration (the whole point of recording it)
        todo = self._rolled_back.get(number)
        if todo is None:
            todo = {-1} | set(range(len(self.shards)))
        failed: set[int] = set()
        for idx in sorted(todo):
            try:
                if idx < 0:
                    # an explicit rollback declares the number DEAD: retire
                    # any witness a partial commit attempt may have left, or
                    # a later crash would roll a never-decided re-prepare
                    # forward off the stale marker
                    from .entry import EntryStatus

                    self.shards[0].set_row(
                        self._WITNESS_TABLE,
                        self._witness_key(number),
                        Entry(status=EntryStatus.DELETED),
                    )
                else:
                    self.shards[idx].rollback(params)
            except (ServiceRemoteError, OSError):
                # unreachable OR erroring shard (handler error, corrupt
                # reply): either way the task did not land — keep it
                failed.add(idx)
        if failed:
            # remember the skip set (was: logged and forgotten — a revived
            # shard could then resurrect the dead number via its stale
            # witness/slot) and arm recovery to re-drive it
            self._rolled_back[number] = failed
            self.mark_needs_recovery()
            _log.warning(
                "rollback of block %d skipped unreachable shard tasks %s — "
                "recorded for re-drive on recovery", number, sorted(failed),
            )
        else:
            self._rolled_back.pop(number, None)
        # fire AFTER the drive attempt: listeners see the number already
        # declared dead (witness retired first), and they fire again on
        # every re-drive — idempotent evictions by contract
        for cb in list(self.on_rollback):
            try:
                cb(number)
            except Exception as e:  # a listener must not break the 2PC
                from ..utils.log import note_swallowed

                note_swallowed("storage.distributed.on_rollback", e)

    def unresolved_rollbacks(self) -> dict[int, set[int]]:
        """Observability/test surface: numbers declared dead whose rollback
        has not yet reached every shard (task -1 = witness retirement)."""
        return {n: set(s) for n, s in self._rolled_back.items()}

    def pending_numbers(self) -> list[int]:
        out: set[int] = set()
        for sh in self.shards:
            out.update(sh.pending_numbers())
        return sorted(out)

    def close(self) -> None:
        for sh in self.shards:
            sh.close()
