"""Durable KV backend on sqlite3 (stdlib) with two-phase commit.

Plays the role of bcos-storage's RocksDBStorage.cpp (574 lines: asyncPrepare
stages a WriteBatch, asyncCommit writes it atomically, asyncRollback drops
it). Sqlite gives us the same contract — single-writer atomic batches with
WAL journaling — without a non-baked-in rocksdb dependency; the storage seam
(interfaces.TransactionalStorage) is what the rest of the stack codes
against, so swapping in a native engine later is a constructor change.
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Iterator

from ..observability.storagelog import CTX_INGRESS, codec_ctx
from .entry import Entry
from .interfaces import TransactionalStorage, TraversableStorage, TwoPCParams


class SQLiteStorage(TransactionalStorage):
    def __init__(self, path: str = ":memory:") -> None:
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.RLock()
        # durable-write ground truth (tool/check_storage.py reconciles the
        # storage observatory's codec ledger against these): value bytes
        # staged by 2PC prepare, and value bytes applied to `kv` by commit
        self.bytes_staged = 0
        self.bytes_written = 0
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv ("
                " tbl TEXT NOT NULL, k BLOB NOT NULL, v BLOB NOT NULL,"
                " PRIMARY KEY (tbl, k))"
            )
            # prepared-but-uncommitted 2PC slots are DURABLE (TiKV persists
            # prewrite locks): a participant that crashes between prepare
            # and commit must still roll FORWARD after restart when the
            # coordinator's primary commit witness exists
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS pending_2pc ("
                " num INTEGER NOT NULL, tbl TEXT NOT NULL, k BLOB NOT NULL,"
                " v BLOB NOT NULL, PRIMARY KEY (num, tbl, k))"
            )
            self._conn.commit()

    def get_row(self, table: str, key: bytes) -> Entry | None:
        with self._lock:
            row = self._conn.execute(
                "SELECT v FROM kv WHERE tbl=? AND k=?", (table, bytes(key))
            ).fetchone()
        if row is None:
            return None
        with codec_ctx(CTX_INGRESS, table):
            e = Entry.decode(row[0])
        return None if e.deleted else e

    def set_row(self, table: str, key: bytes, entry: Entry) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO kv (tbl, k, v) VALUES (?, ?, ?)",
                (table, bytes(key), entry.encode()),
            )
            self._conn.commit()

    def set_rows(self, table: str, items) -> None:
        with self._lock:
            self._conn.executemany(
                "INSERT OR REPLACE INTO kv (tbl, k, v) VALUES (?, ?, ?)",
                [(table, bytes(k), e.encode()) for k, e in items],
            )
            self._conn.commit()

    def get_primary_keys(self, table: str) -> list[bytes]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT k, v FROM kv WHERE tbl=? ORDER BY k", (table,)
            ).fetchall()
        return [bytes(k) for k, v in rows if not Entry.decode(v).deleted]

    def traverse(self) -> Iterator[tuple[str, bytes, Entry]]:
        with self._lock:
            rows = self._conn.execute("SELECT tbl, k, v FROM kv").fetchall()
        for t, k, v in rows:
            with codec_ctx(CTX_INGRESS, t):
                e = Entry.decode(v)
            yield t, bytes(k), e

    # -- 2PC ------------------------------------------------------------

    def prepare(self, params: TwoPCParams, writes: TraversableStorage) -> None:
        """Durably stage writes for `number`. Per-key merge, not slot
        replacement (multi-participant 2PC: several Max executors prepare
        the same block; see MemoryStorage.prepare)."""
        with self._lock:
            rows = [
                (params.number, t, bytes(k), e.encode())
                for t, k, e in writes.traverse()
            ]
            self.bytes_staged += sum(len(r[3]) for r in rows)
            self._conn.executemany(
                "INSERT OR REPLACE INTO pending_2pc (num, tbl, k, v)"
                " VALUES (?, ?, ?, ?)",
                rows,
            )
            self._conn.commit()

    def commit(self, params: TwoPCParams) -> None:
        with self._lock:
            # apply + clear the slot in ONE sqlite transaction: a crash
            # mid-commit leaves either the staged slot (re-commit resolves)
            # or the applied state, never half of each
            staged = self._conn.execute(
                "SELECT COALESCE(SUM(LENGTH(v)), 0) FROM pending_2pc"
                " WHERE num=?",
                (params.number,),
            ).fetchone()
            self.bytes_written += int(staged[0])
            self._conn.execute(
                "INSERT OR REPLACE INTO kv (tbl, k, v)"
                " SELECT tbl, k, v FROM pending_2pc WHERE num=?",
                (params.number,),
            )
            self._conn.execute(
                "DELETE FROM pending_2pc WHERE num=?", (params.number,)
            )
            self._conn.commit()

    def rollback(self, params: TwoPCParams) -> None:
        with self._lock:
            self._conn.execute(
                "DELETE FROM pending_2pc WHERE num=?", (params.number,)
            )
            self._conn.commit()

    def pending_numbers(self) -> list[int]:
        """Block numbers with a durable prepared-but-unresolved slot
        (the recovery scan's input — TiKV's leftover prewrite locks)."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT DISTINCT num FROM pending_2pc ORDER BY num"
            ).fetchall()
        return [int(r[0]) for r in rows]

    def close(self) -> None:
        with self._lock:
            self._conn.close()
