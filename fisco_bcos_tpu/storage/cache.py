"""LRU read-cache over a transactional backend.

Reference: bcos-table/src/CacheStorageFactory.cpp + the LRU cache layer the
reference stacks over RocksDB (StateStorageFactory with cache enabled).
Write-through: set_row updates backend then cache; 2PC commits invalidate
the written keys (the staged write-set goes to the backend, so cached
pre-images must drop).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Iterator

from ..observability.storagelog import STORAGE as _OBS
from .entry import Entry
from .interfaces import (
    TransactionalStorage,
    TraversableStorage,
    TwoPCParams,
)


class CacheStorage(TransactionalStorage):
    def __init__(self, inner: TransactionalStorage, capacity: int = 64 * 1024):
        self.inner = inner
        self.capacity = capacity
        self._cache: OrderedDict[tuple[str, bytes], Entry | None] = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self._gen = 0  # bumped on every commit (miss-fill TOCTOU guard)
        # staged write-sets by 2PC batch, to invalidate on commit
        self._staged_keys: dict[int, list[tuple[str, bytes]]] = {}

    # -- reads ----------------------------------------------------------------

    def get_row(self, table: str, key: bytes) -> Entry | None:
        k = (table, bytes(key))
        with self._lock:
            if k in self._cache:
                self.hits += 1
                self._cache.move_to_end(k)
                e = self._cache[k]
                if e is None:
                    return None
                if _OBS.enabled:
                    _OBS.note_copy("cache.get_row", table)
                return e.copy()
            self.misses += 1
            gen = self._gen
        e = self.inner.get_row(table, key)
        with self._lock:
            # TOCTOU guard: a commit() invalidation between the backend read
            # and this fill means `e` may be a pre-commit value — caching it
            # would serve stale state indefinitely. The generation counter
            # bumps on every commit; only same-generation reads may fill.
            if gen == self._gen:
                if e is not None and _OBS.enabled:
                    _OBS.note_copy("cache.fill", table)
                self._cache[k] = None if e is None else e.copy()
                while len(self._cache) > self.capacity:
                    self._cache.popitem(last=False)
        return e

    def get_primary_keys(self, table: str) -> list[bytes]:
        return self.inner.get_primary_keys(table)

    def traverse(self) -> Iterator[tuple[str, bytes, Entry]]:
        traverse = getattr(self.inner, "traverse", None)
        if traverse is None:
            return iter(())
        return traverse()

    # -- writes (write-through) ------------------------------------------------

    def set_row(self, table: str, key: bytes, entry: Entry) -> None:
        self.inner.set_row(table, key, entry)
        self._fill(table, key, entry)

    def set_rows(self, table: str, items) -> None:
        self.inner.set_rows(table, items)  # one backend transaction
        for key, entry in items:
            self._fill(table, key, entry)

    def _fill(self, table: str, key: bytes, entry: Entry) -> None:
        k = (table, bytes(key))
        if not entry.deleted and _OBS.enabled:
            _OBS.note_copy("cache.fill", table)
        with self._lock:
            self._cache[k] = None if entry.deleted else entry.copy()
            self._cache.move_to_end(k)
            while len(self._cache) > self.capacity:
                self._cache.popitem(last=False)

    # -- 2PC -------------------------------------------------------------------

    def prepare(self, params: TwoPCParams, writes: TraversableStorage) -> None:
        staged = [(t, bytes(k)) for t, k, _ in writes.traverse()]
        with self._lock:
            self._staged_keys[params.number] = staged
        self.inner.prepare(params, writes)

    def commit(self, params: TwoPCParams) -> None:
        self.inner.commit(params)
        with self._lock:
            self._gen += 1
            for k in self._staged_keys.pop(params.number, []):
                self._cache.pop(k, None)

    def rollback(self, params: TwoPCParams) -> None:
        self.inner.rollback(params)
        with self._lock:
            self._staged_keys.pop(params.number, None)

    def pending_numbers(self) -> list[int]:
        return self.inner.pending_numbers()

    def close(self) -> None:
        close = getattr(self.inner, "close", None)
        if close is not None:
            close()
