"""KeyPageStorage — page-packed key layout over a KV backend.

Reference: bcos-table/src/KeyPageStorage.cpp (1,051 lines): instead of one
backend row per (table, key), rows are packed into pages holding up to
`page_size` sorted keys; a per-table meta row tracks page split points.
Point reads fetch one page instead of one row (amortizing backend seeks),
range scans fetch contiguous pages, and small values share pages — the
reference's biggest storage win for state tables with many tiny entries.

Layout in the inner storage:
    table "__kp_meta__",  key <table>           -> sorted list of page-start keys
    table "__kp_page__",  key <table>\\x00<start> -> serialized page (sorted items)

Pages split at `page_size` entries.  2PC: `prepare` repacks the row-level
write-set into page-level writes and forwards to the inner backend, so the
atomic-commit contract is preserved.
"""

from __future__ import annotations

import bisect
import threading
from typing import Iterator

from ..codec.flat import FlatReader, FlatWriter
from ..observability.storagelog import (
    CTX_COMMIT,
    CTX_INGRESS,
    STORAGE as _OBS,
    codec_ctx,
)
from .entry import Entry, EntryStatus
from .interfaces import (
    TransactionalStorage,
    TraversableStorage,
    TwoPCParams,
)

META_TABLE = "__kp_meta__"
PAGE_TABLE = "__kp_page__"


def _encode_page(items: list[tuple[bytes, Entry]]) -> bytes:
    w = FlatWriter()
    w.seq(items, lambda w2, kv: (w2.bytes_(kv[0]), w2.bytes_(kv[1].encode())))
    return w.out()


def _decode_page(buf: bytes) -> list[tuple[bytes, Entry]]:
    r = FlatReader(buf)
    out = r.seq(lambda r2: (r2.bytes_(), Entry.decode(r2.bytes_())))
    r.done()
    return out


def _encode_meta(starts: list[bytes]) -> bytes:
    w = FlatWriter()
    w.seq(starts, lambda w2, s: w2.bytes_(s))
    return w.out()


def _decode_meta(buf: bytes) -> list[bytes]:
    r = FlatReader(buf)
    out = r.seq(lambda r2: r2.bytes_())
    r.done()
    return out


class KeyPageStorage(TransactionalStorage):
    # decoded-page cache bound: ~page_size entries per page, so 1024 pages
    # ≈ 256k cached rows — cleared wholesale when exceeded (reads repopulate)
    _CACHE_MAX_PAGES = 1024

    def __init__(self, inner: TransactionalStorage, page_size: int = 256):
        self.inner = inner
        self.page_size = page_size
        self._lock = threading.RLock()
        # decoded caches (the reference's KeyPageStorage likewise keeps
        # decoded PageData in memory; re-decoding a 256-entry page per row
        # read is what the page layout exists to avoid)
        self._page_cache: dict[tuple[str, bytes], list[tuple[bytes, Entry]]] = {}
        self._meta_cache: dict[str, list[bytes]] = {}

    # -- page plumbing --------------------------------------------------------

    def _meta_locked(self, table: str) -> list[bytes]:
        cached = self._meta_cache.get(table)
        if cached is not None:
            return list(cached)
        e = self.inner.get_row(META_TABLE, table.encode())
        starts = _decode_meta(e.get()) if e is not None else []
        if len(self._meta_cache) >= self._CACHE_MAX_PAGES:
            self._meta_cache.clear()
        self._meta_cache[table] = list(starts)
        return starts

    def _save_meta_locked(self, table: str, starts: list[bytes]) -> None:
        self._meta_cache[table] = list(starts)
        self.inner.set_row(META_TABLE, table.encode(), Entry({"value": _encode_meta(starts)}))

    @staticmethod
    def _page_key(table: str, start: bytes) -> bytes:
        return table.encode() + b"\x00" + start

    def _load_page_locked(self, table: str, start: bytes) -> list[tuple[bytes, Entry]]:
        pk = (table, start)
        cached = self._page_cache.get(pk)
        if cached is not None:
            return list(cached)  # shallow copy: callers mutate the list
        e = self.inner.get_row(PAGE_TABLE, self._page_key(table, start))
        if e is not None and not e.deleted:
            with codec_ctx(CTX_INGRESS, table):
                items = _decode_page(e.get())
        else:
            items = []
        if len(self._page_cache) >= self._CACHE_MAX_PAGES:
            self._page_cache.clear()
        self._page_cache[pk] = list(items)
        return items

    def _save_page_locked(self, table: str, start: bytes, items: list[tuple[bytes, Entry]]) -> None:
        if len(self._page_cache) >= self._CACHE_MAX_PAGES:
            self._page_cache.clear()
        self._page_cache[(table, start)] = list(items)
        self.inner.set_row(
            PAGE_TABLE, self._page_key(table, start), Entry({"value": _encode_page(items)})
        )

    def _page_for(self, starts: list[bytes], key: bytes) -> int | None:
        """Index of the page whose range contains `key` (None if no pages)."""
        if not starts:
            return None
        i = bisect.bisect_right(starts, key) - 1
        return max(i, 0)

    def _delete_page_row_locked(self, table: str, start: bytes) -> None:
        self._page_cache.pop((table, start), None)
        self.inner.set_row(
            PAGE_TABLE,
            self._page_key(table, start),
            Entry(status=EntryStatus.DELETED),
        )

    def _chunk_page(
        self,
        start: bytes,
        merged: list[tuple[bytes, Entry]],
        starts: list[bytes],
    ) -> tuple[list[tuple[bytes, list[tuple[bytes, Entry]] | None]], bool]:
        """Split the merged (sorted) content of the page registered at
        ``start`` into page_size chunks and assign each its registration
        key. Returns (ops, meta_dirty): ops is [(cstart, items)] with
        items=None meaning "tombstone the page row at cstart".

        Invariant maintained: every registered start ≤ its page's min key.
        Only the table-head page can accumulate keys below its registered
        start (reads clamp to page 0) — splitting such a page without
        rekeying would register later chunks at starts that sort BELOW the
        head page's own key, sending reads of the head page's rows to the
        wrong page (rows silently unreadable). The head page is therefore
        rekeyed to its true min key before chunk registration."""
        ops: list[tuple[bytes, list[tuple[bytes, Entry]] | None]] = []
        dirty = False
        head = start
        if merged and merged[0][0] < start:
            ops.append((start, None))  # tombstone the old page row
            starts.remove(start)
            head = merged[0][0]
            bisect.insort(starts, head)
            dirty = True
        chunks = [
            merged[i : i + self.page_size]
            for i in range(0, len(merged), self.page_size)
        ] or [[]]
        for chunk in chunks:
            cstart = head if chunk is chunks[0] else chunk[0][0]
            ops.append((cstart, chunk))
            if cstart not in starts:
                bisect.insort(starts, cstart)
                dirty = True
        return ops, dirty

    # -- StorageInterface -----------------------------------------------------

    def get_row(self, table: str, key: bytes) -> Entry | None:
        key = bytes(key)
        with self._lock:
            starts = self._meta_locked(table)
            idx = self._page_for(starts, key)
            if idx is None:
                return None
            for k, e in self._load_page_locked(table, starts[idx]):
                if k == key:
                    if e.deleted:
                        return None
                    if _OBS.enabled:
                        _OBS.note_copy("keypage.get_row", table)
                    return e.copy()
        return None

    def set_row(self, table: str, key: bytes, entry: Entry) -> None:
        self.set_rows(table, [(key, entry)])

    def set_rows(self, table: str, items) -> None:
        """Bulk write with one decode/encode per TOUCHED page (the same
        page-grouping the 2PC prepare path uses) — a per-row path would
        re-codec a whole page per row, ~1000x slower for bulk loads."""
        with self._lock:
            starts = self._meta_locked(table)
            meta_dirty = False
            # per-page pending writes as a dict (last write wins), merged
            # into the decoded page ONCE at write-out — per-item list
            # surgery on a deferred-split page would be quadratic
            staged: dict[bytes, dict[bytes, Entry]] = {}
            for key, entry in items:
                key = bytes(key)
                if not starts:
                    starts.append(key)
                    meta_dirty = True
                start = starts[self._page_for(starts, key)]
                if _OBS.enabled:
                    _OBS.note_copy("keypage.set_rows", table)
                staged.setdefault(start, {})[key] = entry.copy()
            pages_written = 0
            for start, pending in staged.items():
                merged = {k: e for k, e in self._load_page_locked(table, start)}
                merged.update(pending)
                ops, dirty = self._chunk_page(start, sorted(merged.items()), starts)
                meta_dirty |= dirty
                for cstart, chunk in ops:
                    if chunk is None:
                        self._delete_page_row_locked(table, cstart)
                    else:
                        self._save_page_locked(table, cstart, chunk)
                        pages_written += 1
            if _OBS.enabled:
                _OBS.note_pages(table, pages_written)
            if meta_dirty:
                self._save_meta_locked(table, starts)

    def get_primary_keys(self, table: str) -> list[bytes]:
        out: list[bytes] = []
        with self._lock:
            for start in self._meta_locked(table):
                out.extend(
                    k for k, e in self._load_page_locked(table, start) if not e.deleted
                )
        return out

    def traverse(self) -> Iterator[tuple[str, bytes, Entry]]:
        traverse = getattr(self.inner, "traverse", None)
        if traverse is None:
            return
        for t, k, e in traverse():
            if t == PAGE_TABLE:
                if e.deleted:
                    continue  # tombstoned page row (rekeyed head page)
                table, _, _start = k.partition(b"\x00")
                for key, entry in _decode_page(e.get()):
                    yield table.decode(), key, entry
            elif t != META_TABLE:
                yield t, k, e

    # -- 2PC: repack the row write-set into page writes ------------------------

    class _PageView(TraversableStorage):
        def __init__(self, rows: list[tuple[str, bytes, Entry]]):
            self._rows = rows

        def traverse(self):
            yield from self._rows

    def prepare(self, params: TwoPCParams, writes: TraversableStorage) -> None:
        with self._lock:
            staged: dict[tuple[str, bytes], dict[bytes, Entry]] = {}
            metas: dict[str, list[bytes]] = {}
            for table, key, entry in writes.traverse():
                key = bytes(key)
                if table not in metas:  # setdefault would re-copy per row
                    metas[table] = self._meta_locked(table)
                starts = metas[table]
                idx = self._page_for(starts, key)
                if idx is None:
                    starts.append(key)
                    idx = 0
                start = starts[idx]
                # pending writes as a dict (last wins), merged into the
                # decoded page once — per-item list surgery is quadratic
                # on a 2000-row block write-set
                if _OBS.enabled:
                    _OBS.note_copy("keypage.prepare", table)
                staged.setdefault((table, start), {})[key] = entry.copy()
            rows: list[tuple[str, bytes, Entry]] = []
            for (table, start), pending in staged.items():
                starts = metas[table]
                merged = {k: e for k, e in self._load_page_locked(table, start)}
                merged.update(pending)
                ops, _dirty = self._chunk_page(start, sorted(merged.items()), starts)
                pages_written = 0
                for cstart, chunk in ops:
                    if chunk is None:
                        rows.append(
                            (
                                PAGE_TABLE,
                                self._page_key(table, cstart),
                                Entry(status=EntryStatus.DELETED),
                            )
                        )
                    else:
                        with codec_ctx(CTX_COMMIT, table):
                            page = _encode_page(chunk)
                        rows.append(
                            (
                                PAGE_TABLE,
                                self._page_key(table, cstart),
                                Entry({"value": page}),
                            )
                        )
                        pages_written += 1
                if _OBS.enabled:
                    _OBS.note_pages(table, pages_written)
            for table, starts in metas.items():
                rows.append(
                    (
                        META_TABLE,
                        table.encode(),
                        Entry({"value": _encode_meta(starts)}),
                    )
                )
            self.inner.prepare(params, self._PageView(rows))

    def commit(self, params: TwoPCParams) -> None:
        # the 2PC write-set lands through inner.prepare/commit, bypassing
        # _save_page — drop decoded caches so reads see the committed pages.
        # The lock spans inner.commit so no reader can serve a stale cached
        # page in the window after the data is durable but before the clear.
        with self._lock:
            self.inner.commit(params)
            self._page_cache.clear()
            self._meta_cache.clear()

    def rollback(self, params: TwoPCParams) -> None:
        self.inner.rollback(params)

    def pending_numbers(self) -> list[int]:
        return self.inner.pending_numbers()


    def close(self) -> None:
        close = getattr(self.inner, "close", None)
        if close is not None:
            close()
