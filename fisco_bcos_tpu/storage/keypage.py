"""KeyPageStorage — page-packed key layout over a KV backend.

Reference: bcos-table/src/KeyPageStorage.cpp (1,051 lines): instead of one
backend row per (table, key), rows are packed into pages holding up to
`page_size` sorted keys; a per-table meta row tracks page split points.
Point reads fetch one page instead of one row (amortizing backend seeks),
range scans fetch contiguous pages, and small values share pages — the
reference's biggest storage win for state tables with many tiny entries.

Layout in the inner storage:
    table "__kp_meta__",  key <table>           -> sorted list of page-start keys
    table "__kp_page__",  key <table>\\x00<start> -> serialized page (sorted items)

Pages split at `page_size` entries.  2PC: `prepare` repacks the row-level
write-set into page-level writes and forwards to the inner backend, so the
atomic-commit contract is preserved.
"""

from __future__ import annotations

import bisect
import threading
from typing import Iterator

from ..codec.flat import FlatReader, FlatWriter
from .entry import Entry, EntryStatus
from .interfaces import (
    TransactionalStorage,
    TraversableStorage,
    TwoPCParams,
)

META_TABLE = "__kp_meta__"
PAGE_TABLE = "__kp_page__"


def _encode_page(items: list[tuple[bytes, Entry]]) -> bytes:
    w = FlatWriter()
    w.seq(items, lambda w2, kv: (w2.bytes_(kv[0]), w2.bytes_(kv[1].encode())))
    return w.out()


def _decode_page(buf: bytes) -> list[tuple[bytes, Entry]]:
    r = FlatReader(buf)
    out = r.seq(lambda r2: (r2.bytes_(), Entry.decode(r2.bytes_())))
    r.done()
    return out


def _encode_meta(starts: list[bytes]) -> bytes:
    w = FlatWriter()
    w.seq(starts, lambda w2, s: w2.bytes_(s))
    return w.out()


def _decode_meta(buf: bytes) -> list[bytes]:
    r = FlatReader(buf)
    out = r.seq(lambda r2: r2.bytes_())
    r.done()
    return out


class KeyPageStorage(TransactionalStorage):
    def __init__(self, inner: TransactionalStorage, page_size: int = 256):
        self.inner = inner
        self.page_size = page_size
        self._lock = threading.RLock()

    # -- page plumbing --------------------------------------------------------

    def _meta(self, table: str) -> list[bytes]:
        e = self.inner.get_row(META_TABLE, table.encode())
        return _decode_meta(e.get()) if e is not None else []

    def _save_meta(self, table: str, starts: list[bytes]) -> None:
        self.inner.set_row(META_TABLE, table.encode(), Entry({"value": _encode_meta(starts)}))

    @staticmethod
    def _page_key(table: str, start: bytes) -> bytes:
        return table.encode() + b"\x00" + start

    def _load_page(self, table: str, start: bytes) -> list[tuple[bytes, Entry]]:
        e = self.inner.get_row(PAGE_TABLE, self._page_key(table, start))
        return _decode_page(e.get()) if e is not None else []

    def _save_page(self, table: str, start: bytes, items: list[tuple[bytes, Entry]]) -> None:
        self.inner.set_row(
            PAGE_TABLE, self._page_key(table, start), Entry({"value": _encode_page(items)})
        )

    def _page_for(self, starts: list[bytes], key: bytes) -> int | None:
        """Index of the page whose range contains `key` (None if no pages)."""
        if not starts:
            return None
        i = bisect.bisect_right(starts, key) - 1
        return max(i, 0)

    # -- StorageInterface -----------------------------------------------------

    def get_row(self, table: str, key: bytes) -> Entry | None:
        key = bytes(key)
        with self._lock:
            starts = self._meta(table)
            idx = self._page_for(starts, key)
            if idx is None:
                return None
            for k, e in self._load_page(table, starts[idx]):
                if k == key:
                    return None if e.deleted else e.copy()
        return None

    def set_row(self, table: str, key: bytes, entry: Entry) -> None:
        with self._lock:
            self._set_locked(table, bytes(key), entry)

    def _set_locked(self, table: str, key: bytes, entry: Entry) -> None:
        starts = self._meta(table)
        idx = self._page_for(starts, key)
        if idx is None:
            # first page of the table
            self._save_page(table, key, [(key, entry.copy())])
            self._save_meta(table, [key])
            return
        start = starts[idx]
        items = self._load_page(table, start)
        keys = [k for k, _ in items]
        j = bisect.bisect_left(keys, key)
        if j < len(items) and items[j][0] == key:
            items[j] = (key, entry.copy())
        else:
            items.insert(j, (key, entry.copy()))
        if len(items) > self.page_size:
            # split: upper half becomes a new page (KeyPageStorage::split)
            mid = len(items) // 2
            lower, upper = items[:mid], items[mid:]
            self._save_page(table, start, lower)
            new_start = upper[0][0]
            self._save_page(table, new_start, upper)
            starts.insert(idx + 1, new_start)
            self._save_meta(table, starts)
        else:
            self._save_page(table, start, items)

    def set_rows(self, table: str, items) -> None:
        with self._lock:
            for key, entry in items:
                self._set_locked(table, bytes(key), entry)

    def get_primary_keys(self, table: str) -> list[bytes]:
        out: list[bytes] = []
        with self._lock:
            for start in self._meta(table):
                out.extend(
                    k for k, e in self._load_page(table, start) if not e.deleted
                )
        return out

    def traverse(self) -> Iterator[tuple[str, bytes, Entry]]:
        traverse = getattr(self.inner, "traverse", None)
        if traverse is None:
            return
        for t, k, e in traverse():
            if t == PAGE_TABLE:
                table, _, _start = k.partition(b"\x00")
                for key, entry in _decode_page(e.get()):
                    yield table.decode(), key, entry
            elif t != META_TABLE:
                yield t, k, e

    # -- 2PC: repack the row write-set into page writes ------------------------

    class _PageView(TraversableStorage):
        def __init__(self, rows: list[tuple[str, bytes, Entry]]):
            self._rows = rows

        def traverse(self):
            yield from self._rows

    def prepare(self, params: TwoPCParams, writes: TraversableStorage) -> None:
        with self._lock:
            staged: dict[tuple[str, bytes], list[tuple[bytes, Entry]]] = {}
            metas: dict[str, list[bytes]] = {}
            for table, key, entry in writes.traverse():
                key = bytes(key)
                starts = metas.setdefault(table, self._meta(table))
                idx = self._page_for(starts, key)
                if idx is None:
                    starts.append(key)
                    starts.sort()
                    idx = self._page_for(starts, key)
                start = starts[idx]
                pk = (table, start)
                if pk not in staged:
                    staged[pk] = self._load_page(table, start)
                items = staged[pk]
                keys = [k for k, _ in items]
                j = bisect.bisect_left(keys, key)
                if j < len(items) and items[j][0] == key:
                    items[j] = (key, entry.copy())
                else:
                    items.insert(j, (key, entry.copy()))
            rows: list[tuple[str, bytes, Entry]] = []
            for (table, start), items in staged.items():
                # split oversized staged pages before write-out
                chunks = [
                    items[i : i + self.page_size]
                    for i in range(0, len(items), self.page_size)
                ] or [[]]
                starts = metas[table]
                for chunk in chunks:
                    if not chunk:
                        continue
                    # first chunk keeps the existing page key (its range may
                    # begin below any staged key); later chunks start fresh
                    cstart = start if chunk is chunks[0] else chunk[0][0]
                    rows.append(
                        (
                            PAGE_TABLE,
                            self._page_key(table, cstart),
                            Entry({"value": _encode_page(chunk)}),
                        )
                    )
                    if cstart not in starts:
                        starts.append(cstart)
                        starts.sort()
            for table, starts in metas.items():
                rows.append(
                    (
                        META_TABLE,
                        table.encode(),
                        Entry({"value": _encode_meta(starts)}),
                    )
                )
            self.inner.prepare(params, self._PageView(rows))

    def commit(self, params: TwoPCParams) -> None:
        self.inner.commit(params)

    def rollback(self, params: TwoPCParams) -> None:
        self.inner.rollback(params)

    def close(self) -> None:
        close = getattr(self.inner, "close", None)
        if close is not None:
            close()
