"""Table entry — one row of state.

Reference: bcos-framework/storage/Entry.h (status + field values; small-value
inline optimization is a C++ concern we don't need). Canonical bytes are the
flat-codec encoding over sorted field names — deterministic, because entry
bytes feed the state-root hash.
"""

from __future__ import annotations

from enum import IntEnum

from ..codec.flat import FlatReader, FlatWriter
from ..observability.storagelog import STORAGE as _OBS


class EntryStatus(IntEnum):
    NORMAL = 0
    DELETED = 1


class Entry:
    __slots__ = ("fields", "status")

    def __init__(
        self,
        fields: dict[str, bytes] | None = None,
        status: EntryStatus = EntryStatus.NORMAL,
    ):
        self.fields: dict[str, bytes] = dict(fields) if fields else {}
        self.status = status

    # single-value convenience (KV tables store one "value" field)
    def get(self, name: str = "value") -> bytes:
        return self.fields.get(name, b"")

    def set(self, name_or_value, value: bytes | None = None) -> "Entry":
        """entry.set(b"v") sets the default field; entry.set("f", b"v") named."""
        if value is None:
            self.fields["value"] = bytes(name_or_value)
        else:
            self.fields[str(name_or_value)] = bytes(value)
        return self

    @property
    def deleted(self) -> bool:
        return self.status == EntryStatus.DELETED

    def copy(self) -> "Entry":
        return Entry(dict(self.fields), self.status)

    def encode(self) -> bytes:
        w = FlatWriter()
        w.u8(int(self.status))
        names = sorted(self.fields)
        w.seq(names, lambda w2, n: (w2.str_(n), w2.bytes_(self.fields[n])))
        out = w.out()
        if _OBS.enabled:
            _OBS.note_encode(len(out))
        return out

    @classmethod
    def decode(cls, buf: bytes) -> "Entry":
        if _OBS.enabled:
            _OBS.note_decode(len(buf))
        r = FlatReader(buf)
        status = EntryStatus(r.u8())
        fields: dict[str, bytes] = {}
        for _ in range(r.u32()):
            n = r.str_()
            fields[n] = r.bytes_()
        r.done()
        return cls(fields, status)

    def __repr__(self) -> str:
        return f"Entry(status={self.status.name}, fields={self.fields!r})"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Entry)
            and self.status == other.status
            and self.fields == other.fields
        )
