"""Table abstraction over a storage layer.

Reference: bcos-framework/storage/Table.h + bcos-table/src/Table.cpp; table
metadata lives in the s_tables system table (TableManagerPrecompiled creates
user tables there at runtime).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .entry import Entry
from .interfaces import StorageInterface

SYS_TABLES = "s_tables"


@dataclass(frozen=True)
class TableInfo:
    name: str
    key_field: str = "key"
    value_fields: tuple[str, ...] = ("value",)

    def encode(self) -> bytes:
        return ",".join([self.key_field, *self.value_fields]).encode()

    @classmethod
    def decode(cls, name: str, buf: bytes) -> "TableInfo":
        parts = buf.decode().split(",")
        return cls(name=name, key_field=parts[0], value_fields=tuple(parts[1:]))


@dataclass
class Table:
    info: TableInfo
    storage: StorageInterface = field(repr=False)

    def get_row(self, key: bytes) -> Entry | None:
        return self.storage.get_row(self.info.name, key)

    def get_rows(self, keys) -> list[Entry | None]:
        return self.storage.get_rows(self.info.name, keys)

    def set_row(self, key: bytes, entry: Entry) -> None:
        self.storage.set_row(self.info.name, key, entry)

    def remove(self, key: bytes) -> None:
        remove = getattr(self.storage, "remove_row", None)
        if remove is None:
            from .entry import EntryStatus

            self.storage.set_row(
                self.info.name, key, Entry(status=EntryStatus.DELETED)
            )
        else:
            remove(self.info.name, key)

    def new_entry(self) -> Entry:
        return Entry()


def open_table(storage: StorageInterface, name: str) -> Table | None:
    meta = storage.get_row(SYS_TABLES, name.encode())
    if meta is None:
        return None
    return Table(TableInfo.decode(name, meta.get()), storage)


def create_table(
    storage: StorageInterface,
    name: str,
    key_field: str = "key",
    value_fields: tuple[str, ...] = ("value",),
) -> Table:
    info = TableInfo(name, key_field, value_fields)
    existing = storage.get_row(SYS_TABLES, name.encode())
    if existing is not None:
        raise ValueError(f"table exists: {name}")
    storage.set_row(SYS_TABLES, name.encode(), Entry().set(info.encode()))
    return Table(info, storage)
