"""Storage: entry/table model, overlay state, durable backends, 2PC.

Reference counterparts: bcos-framework/storage/{StorageInterface,Entry,Table}.h,
bcos-table (StateStorage/KeyPageStorage), bcos-storage (RocksDB/TiKV 2PC).
"""

from .entry import Entry, EntryStatus  # noqa: F401
from .table import Table, TableInfo  # noqa: F401
from .memory_storage import MemoryStorage  # noqa: F401
from .sqlite_storage import SQLiteStorage  # noqa: F401
from .state_storage import StateStorage  # noqa: F401
