"""StateStorage — the in-memory overlay state with a device-hashed root.

Reference: bcos-table/src/StateStorage.h (685 lines; bucketed tbb-parallel
overlay). Reads fall through to the previous layer; writes stay local until
the scheduler commits them down. The state root
(StateStorage.h:457-486) is the XOR-fold of per-dirty-entry digests — XOR
makes it order-independent, which is exactly what makes it batchable: here
all dirty entries are hashed in ONE device program (hot spot #3; the
reference uses tbb::parallel_for + per-entry CPU hashes) and XOR-folded with
numpy. Digest layout: H(flat(table) ‖ flat(key) ‖ entry.encode()) — one hash
per entry instead of the reference's hash(table)^hash(key)^hash(entry) triple
(same order-independence, one device pass, and immune to the triple's
component-swapping collisions).
"""

from __future__ import annotations

import threading
from typing import Iterator

import numpy as np

from ..codec.flat import FlatWriter
from ..crypto.suite import CryptoSuite
from ..observability.storagelog import STORAGE as _OBS, codec_ctx
from .entry import Entry, EntryStatus
from .interfaces import StorageInterface, TraversableStorage

_ZERO32 = b"\x00" * 32


class StateStorage(TraversableStorage):
    def __init__(self, prev: StorageInterface | None = None):
        self.prev = prev
        self._data: dict[tuple[str, bytes], Entry] = {}
        self._lock = threading.RLock()
        # when set to a set(), fall-through reads (keys this layer depends on
        # from BELOW) are recorded — the DAG runner's read-set for runtime
        # conflict validation (executor.dag_execute_transactions)
        self.read_track: set | None = None

    # -- reads --------------------------------------------------------------

    def get_row(self, table: str, key: bytes) -> Entry | None:
        key = bytes(key)
        with self._lock:
            e = self._data.get((table, key))
        if e is not None:
            if e.deleted:
                return None
            if _OBS.enabled:
                _OBS.note_copy("state.get_row", table)
            return e.copy()
        if self.read_track is not None:
            self.read_track.add((table, key))
        return self.prev.get_row(table, key) if self.prev else None

    def get_primary_keys(self, table: str) -> list[bytes]:
        keys: set[bytes] = set()
        if self.prev:
            keys.update(self.prev.get_primary_keys(table))
        with self._lock:
            for (t, k), e in self._data.items():
                if t != table:
                    continue
                if e.deleted:
                    keys.discard(k)
                else:
                    keys.add(k)
        return sorted(keys)

    # -- writes -------------------------------------------------------------

    def set_row(self, table: str, key: bytes, entry: Entry) -> None:
        if _OBS.enabled:
            _OBS.note_copy("state.set_row", table)
        with self._lock:
            self._data[(table, bytes(key))] = entry.copy()

    def remove_row(self, table: str, key: bytes) -> None:
        self.set_row(table, key, Entry(status=EntryStatus.DELETED))

    # -- commit support -----------------------------------------------------

    def traverse(self) -> Iterator[tuple[str, bytes, Entry]]:
        with self._lock:
            items = list(self._data.items())
        track = _OBS.enabled
        for (t, k), e in items:
            if track:
                _OBS.note_copy("state.traverse", t)
            yield t, k, e.copy()

    def dirty_count(self) -> int:
        with self._lock:
            return len(self._data)

    def merge_into_prev(self) -> None:
        """Push local writes down one layer (scheduler commit path).

        Entries MOVE rather than copy when the parent is a plain
        StateStorage: this layer is cleared in the same step and the
        copy-in/copy-out discipline of set_row/get_row means no alias to
        a stored Entry can exist outside, so ownership transfer is safe —
        this halves the per-merge Entry traffic on the block hot path
        (tx overlay -> shadow -> block merges dominated the flood's
        Python tail). Subclasses that override set_row keep the copying
        path so their hooks still see every row."""
        prev = self.prev
        if prev is None:
            raise ValueError("no previous layer to merge into")
        if type(prev) is StateStorage:
            with self._lock:
                items = list(self._data.items())
                self._data.clear()
            with prev._lock:
                prev._data.update(items)
            return
        with self._lock:
            items = list(self._data.items())
            self._data.clear()
        for (t, k), e in items:
            prev.set_row(t, k, e)  # set_row copies; traverse() would too

    # -- state root (hot spot #3) -------------------------------------------

    def hash_async(self, suite: CryptoSuite):
        """Dispatch the state-root hash batch, defer the sync: () -> bytes.
        Order-independent XOR root over dirty entries, hashed as one device
        batch (vs the reference's tbb loop, StateStorage.h:457-486)."""
        preimages = []
        with codec_ctx("hash"):
            for t, k, e in self.traverse():
                w = FlatWriter()
                w.str_(t)
                w.bytes_(k)
                preimages.append(w.out() + e.encode())
        if not preimages:
            return lambda: _ZERO32
        resolve = suite.hash_batch_async(preimages)
        return lambda: bytes(np.bitwise_xor.reduce(resolve(), axis=0))

    def hash(self, suite: CryptoSuite) -> bytes:
        return self.hash_async(suite)()
