"""Flat deterministic binary codec — the framework's canonical byte format.

Plays the role the reference gives Tars IDL serialization
(bcos-tars-protocol/tars/*.tars + protocol/*Impl.*): one canonical encoding
serves as in-memory object payload, network wire format, and storage format,
and — critically — as the *hash preimage* for transactions and block headers,
so it must be deterministic: fixed field order, little-endian fixed-width
ints, u32 length prefixes, no optional/default compression. This is a fresh
format (not Tars): simple enough to write by hand, deterministic by
construction, and friendly to batch padding on device.
"""

from __future__ import annotations

import struct


class FlatWriter:
    __slots__ = ("_parts",)

    def __init__(self) -> None:
        self._parts: list[bytes] = []

    def u8(self, v: int) -> "FlatWriter":
        self._parts.append(struct.pack("<B", v))
        return self

    def u32(self, v: int) -> "FlatWriter":
        self._parts.append(struct.pack("<I", v))
        return self

    def i64(self, v: int) -> "FlatWriter":
        self._parts.append(struct.pack("<q", v))
        return self

    def u64(self, v: int) -> "FlatWriter":
        self._parts.append(struct.pack("<Q", v))
        return self

    def bytes_(self, v: bytes) -> "FlatWriter":
        self._parts.append(struct.pack("<I", len(v)))
        self._parts.append(bytes(v))
        return self

    def str_(self, v: str) -> "FlatWriter":
        return self.bytes_(v.encode("utf-8"))

    def fixed(self, v: bytes, n: int) -> "FlatWriter":
        if len(v) != n:
            raise ValueError(f"fixed field: expected {n} bytes, got {len(v)}")
        self._parts.append(bytes(v))
        return self

    def seq(self, items, write_item) -> "FlatWriter":
        self._parts.append(struct.pack("<I", len(items)))
        for it in items:
            write_item(self, it)
        return self

    def out(self) -> bytes:
        return b"".join(self._parts)


class FlatReader:
    __slots__ = ("_buf", "_off")

    def __init__(self, buf: bytes) -> None:
        self._buf = buf
        self._off = 0

    def _take(self, n: int) -> bytes:
        if self._off + n > len(self._buf):
            raise ValueError("flat decode: truncated input")
        v = self._buf[self._off : self._off + n]
        self._off += n
        return v

    def u8(self) -> int:
        return self._take(1)[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self._take(8))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def bytes_(self) -> bytes:
        return self._take(self.u32())

    def str_(self) -> str:
        return self.bytes_().decode("utf-8")

    def fixed(self, n: int) -> bytes:
        return self._take(n)

    def seq(self, read_item) -> list:
        return [read_item(self) for _ in range(self.u32())]

    def at_end(self) -> bool:
        """True when the buffer is exhausted — the probe optional trailing
        sections use (fields added after a release decode as absent on old
        bytes, and absent fields encode to NOTHING, keeping pre-extension
        encodings byte-identical)."""
        return self._off == len(self._buf)

    def done(self) -> None:
        if self._off != len(self._buf):
            raise ValueError(
                f"flat decode: {len(self._buf) - self._off} trailing bytes"
            )
