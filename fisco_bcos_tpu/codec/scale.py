"""SCALE codec — the WASM/liquid contract parameter encoding.

Reference: bcos-codec/scale/{Scale.h, ScaleEncoderStream.cpp,
ScaleDecoderStream.cpp} (parity-SCALE: compact length-prefixed vectors,
little-endian fixed-width ints, single-byte bools, 0x00/0x01 options).
Type descriptors are strings, mirroring how the ABI codec names types:

    u8 u16 u32 u64 u128 i8..i128 bool string bytes
    compact                      (compact-encoded unsigned integer)
    vec<T>   option<T>   (T1,T2,...)   [T;N]
"""

from __future__ import annotations


class ScaleError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Compact integers (the SCALE hallmark)
# ---------------------------------------------------------------------------


def encode_compact(n: int) -> bytes:
    if n < 0:
        raise ScaleError("compact is unsigned")
    if n < 1 << 6:
        return bytes([n << 2])
    if n < 1 << 14:
        return ((n << 2) | 0b01).to_bytes(2, "little")
    if n < 1 << 30:
        return ((n << 2) | 0b10).to_bytes(4, "little")
    data = n.to_bytes((n.bit_length() + 7) // 8, "little")
    if len(data) > 67:
        raise ScaleError("compact too large")
    return bytes([((len(data) - 4) << 2) | 0b11]) + data


def decode_compact(buf: bytes, pos: int = 0) -> tuple[int, int]:
    """Returns (value, new_pos)."""
    if pos >= len(buf):
        raise ScaleError("truncated compact")
    mode = buf[pos] & 0b11
    if mode == 0b00:
        return buf[pos] >> 2, pos + 1
    if mode == 0b01:
        if pos + 2 > len(buf):
            raise ScaleError("truncated compact16")
        return int.from_bytes(buf[pos : pos + 2], "little") >> 2, pos + 2
    if mode == 0b10:
        if pos + 4 > len(buf):
            raise ScaleError("truncated compact32")
        return int.from_bytes(buf[pos : pos + 4], "little") >> 2, pos + 4
    nbytes = (buf[pos] >> 2) + 4
    if pos + 1 + nbytes > len(buf):
        raise ScaleError("truncated big compact")
    return int.from_bytes(buf[pos + 1 : pos + 1 + nbytes], "little"), pos + 1 + nbytes


# ---------------------------------------------------------------------------
# Type-driven encode/decode
# ---------------------------------------------------------------------------

_INTS = {f"u{b}": (b // 8, False) for b in (8, 16, 32, 64, 128)}
_INTS.update({f"i{b}": (b // 8, True) for b in (8, 16, 32, 64, 128)})


def _split_tuple(inner: str) -> list[str]:
    parts, depth, cur = [], 0, ""
    for ch in inner:
        if ch in "<([":
            depth += 1
        elif ch in ">)]":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append(cur.strip())
            cur = ""
        else:
            cur += ch
    if cur.strip():
        parts.append(cur.strip())
    return parts


def scale_encode(typ: str, value) -> bytes:
    typ = typ.strip()
    if typ in _INTS:
        size, signed = _INTS[typ]
        return int(value).to_bytes(size, "little", signed=signed)
    if typ == "bool":
        return b"\x01" if value else b"\x00"
    if typ == "compact":
        return encode_compact(int(value))
    if typ == "string":
        data = value.encode() if isinstance(value, str) else bytes(value)
        return encode_compact(len(data)) + data
    if typ == "bytes":
        data = bytes(value)
        return encode_compact(len(data)) + data
    if typ.startswith("vec<") and typ.endswith(">"):
        inner = typ[4:-1]
        out = encode_compact(len(value))
        for v in value:
            out += scale_encode(inner, v)
        return out
    if typ.startswith("option<") and typ.endswith(">"):
        if value is None:
            return b"\x00"
        return b"\x01" + scale_encode(typ[7:-1], value)
    if typ.startswith("(") and typ.endswith(")"):
        parts = _split_tuple(typ[1:-1])
        if len(parts) != len(value):
            raise ScaleError(f"tuple arity mismatch: {typ}")
        return b"".join(scale_encode(t, v) for t, v in zip(parts, value))
    if typ.startswith("[") and typ.endswith("]") and ";" in typ:
        inner, _, n = typ[1:-1].rpartition(";")
        n = int(n)
        if len(value) != n:
            raise ScaleError(f"fixed array length mismatch: {typ}")
        return b"".join(scale_encode(inner.strip(), v) for v in value)
    raise ScaleError(f"unknown SCALE type: {typ}")


def scale_decode(typ: str, buf: bytes, pos: int = 0) -> tuple[object, int]:
    typ = typ.strip()
    if typ in _INTS:
        size, signed = _INTS[typ]
        if pos + size > len(buf):
            raise ScaleError(f"truncated {typ}")
        return int.from_bytes(buf[pos : pos + size], "little", signed=signed), pos + size
    if typ == "bool":
        if pos >= len(buf) or buf[pos] not in (0, 1):
            raise ScaleError("bad bool")
        return buf[pos] == 1, pos + 1
    if typ == "compact":
        return decode_compact(buf, pos)
    if typ in ("string", "bytes"):
        n, pos = decode_compact(buf, pos)
        if pos + n > len(buf):
            raise ScaleError("truncated bytes")
        raw = bytes(buf[pos : pos + n])
        return (raw.decode() if typ == "string" else raw), pos + n
    if typ.startswith("vec<") and typ.endswith(">"):
        inner = typ[4:-1]
        n, pos = decode_compact(buf, pos)
        out = []
        for _ in range(n):
            v, pos = scale_decode(inner, buf, pos)
            out.append(v)
        return out, pos
    if typ.startswith("option<") and typ.endswith(">"):
        if pos >= len(buf) or buf[pos] not in (0, 1):
            raise ScaleError("bad option tag")
        if buf[pos] == 0:
            return None, pos + 1
        return scale_decode(typ[7:-1], buf, pos + 1)
    if typ.startswith("(") and typ.endswith(")"):
        parts = _split_tuple(typ[1:-1])
        out = []
        for t in parts:
            v, pos = scale_decode(t, buf, pos)
            out.append(v)
        return tuple(out), pos
    if typ.startswith("[") and typ.endswith("]") and ";" in typ:
        inner, _, n = typ[1:-1].rpartition(";")
        out = []
        for _ in range(int(n)):
            v, pos = scale_decode(inner.strip(), buf, pos)
            out.append(v)
        return out, pos
    raise ScaleError(f"unknown SCALE type: {typ}")


def scale_decode_exact(typ: str, buf: bytes):
    v, pos = scale_decode(typ, buf)
    if pos != len(buf):
        raise ScaleError(f"{len(buf) - pos} trailing bytes")
    return v
