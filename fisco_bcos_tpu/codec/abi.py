"""Minimal Solidity ABI codec.

Reference: bcos-codec/abi/ContractABICodec.* (used by every precompile for
input parsing and output encoding, e.g.
bcos-executor/src/precompiled/extension/DagTransferPrecompiled.cpp:44-64's
name2Selector table). Supports the types the system/benchmark precompiles
use: uint256/int256, address, bool, string, bytes, bytes32, and dynamic
arrays of them. Function selector = first 4 bytes of hash("name(type,...)"),
where the hash is the suite hash (keccak256, or SM3 on SM chains — matching
the reference's getFuncSelector, common/Utilities.cpp).
"""

from __future__ import annotations

from typing import Any

_WORD = 32


def _pad32(b: bytes, left: bool = True) -> bytes:
    if len(b) % _WORD == 0 and b:
        return b
    pad = _WORD - (len(b) % _WORD or _WORD)
    return (b"\x00" * pad + b) if left else (b + b"\x00" * pad)


def _is_dynamic(typ: str) -> bool:
    return typ in ("string", "bytes") or typ.endswith("[]")


def _encode_static(typ: str, val: Any) -> bytes:
    if typ.startswith("uint") or typ == "bool":
        v = int(val)
        if v < 0:
            raise ValueError(f"{typ} cannot encode negative {v}")
        return v.to_bytes(_WORD, "big")
    if typ.startswith("int"):
        return int(val).to_bytes(_WORD, "big", signed=True)
    if typ == "address":
        if isinstance(val, str):
            b = bytes.fromhex(val[2:] if val[:2] in ("0x", "0X") else val)
        else:
            b = bytes(val)
        if len(b) != 20:
            raise ValueError("address must be 20 bytes")
        return b"\x00" * 12 + b
    if typ.startswith("bytes") and typ != "bytes":
        n = int(typ[5:])
        if not 1 <= n <= 32:
            raise ValueError(f"bad fixed-bytes width {typ}")
        b = bytes(val)
        if len(b) > n:
            raise ValueError(f"{typ} overflow")
        return b.ljust(32, b"\x00")
    raise ValueError(f"unsupported static type {typ}")


def _encode_one(typ: str, val: Any) -> bytes:
    """Encoding of one value; for dynamic types this is the *tail* data."""
    if typ == "string":
        val = val.encode() if isinstance(val, str) else bytes(val)
        return len(val).to_bytes(_WORD, "big") + _pad32(val, left=False)
    if typ == "bytes":
        val = bytes(val)
        return len(val).to_bytes(_WORD, "big") + _pad32(val, left=False)
    if typ.endswith("[]"):
        elem = typ[:-2]
        return len(val).to_bytes(_WORD, "big") + abi_encode([elem] * len(val), val)
    return _encode_static(typ, val)


def abi_encode(types: list[str], values: list[Any]) -> bytes:
    """Head/tail ABI encoding of a value tuple."""
    if len(types) != len(values):
        raise ValueError("types/values length mismatch")
    heads: list[bytes] = []
    tails: list[bytes] = []
    head_len = _WORD * len(types)
    for typ, val in zip(types, values):
        if _is_dynamic(typ):
            offset = head_len + sum(len(t) for t in tails)
            heads.append(offset.to_bytes(_WORD, "big"))
            tails.append(_encode_one(typ, val))
        else:
            heads.append(_encode_static(typ, val))
    return b"".join(heads) + b"".join(tails)


def _decode_static(typ: str, word: bytes) -> Any:
    if typ.startswith("uint"):
        return int.from_bytes(word, "big")
    if typ == "bool":
        return bool(int.from_bytes(word, "big"))
    if typ.startswith("int"):
        return int.from_bytes(word, "big", signed=True)
    if typ == "address":
        return word[12:]
    if typ.startswith("bytes") and typ != "bytes":
        return word[: int(typ[5:])]  # bytes32 -> the whole word
    raise ValueError(f"unsupported static type {typ}")


def _decode_one(typ: str, data: bytes, offset: int) -> Any:
    # an offset whose length word lies outside the buffer is malformed, not
    # an empty value (the reference ContractABICodec rejects it too)
    if offset + _WORD > len(data):
        raise ValueError("abi decode: dynamic offset out of range")
    if typ == "string" or typ == "bytes":
        n = int.from_bytes(data[offset : offset + _WORD], "big")
        raw = data[offset + _WORD : offset + _WORD + n]
        if len(raw) != n:
            raise ValueError("abi decode: truncated dynamic data")
        return raw.decode() if typ == "string" else raw
    if typ.endswith("[]"):
        elem = typ[:-2]
        n = int.from_bytes(data[offset : offset + _WORD], "big")
        # each element needs at least one head word: a declared length beyond
        # that is malformed, not a multi-terabyte allocation
        if n > (len(data) - offset - _WORD) // _WORD:
            raise ValueError("abi decode: array length exceeds calldata")
        return abi_decode([elem] * n, data[offset + _WORD :])
    return _decode_static(typ, data[offset : offset + _WORD])


def abi_decode(types: list[str], data: bytes) -> list[Any]:
    out: list[Any] = []
    for i, typ in enumerate(types):
        word = data[i * _WORD : (i + 1) * _WORD]
        if len(word) != _WORD:
            raise ValueError("abi decode: truncated head")
        if _is_dynamic(typ):
            out.append(_decode_one(typ, data, int.from_bytes(word, "big")))
        else:
            out.append(_decode_static(typ, word))
    return out


class ABICodec:
    """Selector-aware codec bound to a crypto suite's hash
    (reference: ContractABICodec + getFuncSelector)."""

    def __init__(self, hash_fn):
        self._hash = hash_fn

    def selector(self, signature: str) -> bytes:
        return self._hash(signature.encode())[:4]

    @staticmethod
    def _sig_types(signature: str) -> list[str]:
        inner = signature[signature.index("(") + 1 : signature.rindex(")")]
        return [t.strip() for t in inner.split(",") if t.strip()]

    def encode_call(self, signature: str, *values: Any) -> bytes:
        return self.selector(signature) + abi_encode(
            self._sig_types(signature), list(values)
        )

    def decode_input(self, signature: str, data: bytes) -> list[Any]:
        """Decode calldata that includes the 4-byte selector."""
        return abi_decode(self._sig_types(signature), data[4:])

    def encode_output(self, types: list[str], *values: Any) -> bytes:
        return abi_encode(types, list(values))

    def decode_output(self, types: list[str], data: bytes) -> list[Any]:
        return abi_decode(types, data)
