"""Solidity ABI codec — the full static/dynamic type algebra.

Reference: bcos-codec/abi/ContractABICodec.* (used by every precompile for
input parsing and output encoding, e.g.
bcos-executor/src/precompiled/extension/DagTransferPrecompiled.cpp:44-64's
name2Selector table). Covers the reference codec's whole surface: elementary
types (uintN/intN, address, bool, bytesN, bytes, string), fixed-size arrays
``T[k]``, dynamic arrays ``T[]``, nested arrays, and tuples ``(T1,T2,...)``
with arbitrary nesting — head/tail layout per the Solidity ABI spec, with
strict decode (out-of-range offsets and truncated data raise, they don't
yield empty values). Function selector = first 4 bytes of
hash("name(type,...)"), where the hash is the suite hash (keccak256, or SM3
on SM chains — matching the reference's getFuncSelector,
precompiled/common/Utilities.cpp).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache
from typing import Any

_WORD = 32


# ---------------------------------------------------------------------------
# Type grammar
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class AbiType:
    """Parsed ABI type. `base` is one of uint/int/address/bool/fbytes/
    bytes/string/array/tuple; `bits` holds the uint/int width or the
    fixed-bytes byte count; arrays carry `elem` and `length` (-1 = dynamic);
    tuples carry `components`."""

    base: str
    bits: int = 0
    length: int = -1
    elem: "AbiType | None" = None
    components: tuple = ()

    @property
    def is_dynamic(self) -> bool:
        if self.base in ("bytes", "string"):
            return True
        if self.base == "array":
            return self.length < 0 or self.elem.is_dynamic
        if self.base == "tuple":
            return any(c.is_dynamic for c in self.components)
        return False

    @property
    def head_words(self) -> int:
        """Words this type occupies in its enclosing head block
        (1 for any dynamic type: the offset word)."""
        if self.is_dynamic:
            return 1
        if self.base == "array":
            return self.length * self.elem.head_words
        if self.base == "tuple":
            return sum(c.head_words for c in self.components)
        return 1


def split_toplevel(s: str, sep: str = ",") -> list[str]:
    """Split on `sep` at bracket depth 0 (tuple/array aware)."""
    parts: list[str] = []
    depth = 0
    cur: list[str] = []
    for ch in s:
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
            if depth < 0:
                raise ValueError(f"unbalanced brackets in {s!r}")
        if ch == sep and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if depth != 0:
        raise ValueError(f"unbalanced brackets in {s!r}")
    parts.append("".join(cur))
    return [p.strip() for p in parts if p.strip()]


@lru_cache(maxsize=4096)
def parse_type(s: str) -> AbiType:
    # memoized: AbiType is frozen, and block execution parses the same few
    # signatures for every tx (a top host cost in the flood profile)
    s = s.strip()
    if not s:
        raise ValueError("empty type")
    if s.endswith("]"):
        i = s.rindex("[")
        inner = s[i + 1 : -1].strip()
        if inner:
            k = int(inner)
            if k < 0:
                raise ValueError(f"negative array length in {s!r}")
        else:
            k = -1
        return AbiType("array", length=k, elem=parse_type(s[:i]))
    if s.startswith("(") and s.endswith(")"):
        return AbiType(
            "tuple", components=tuple(parse_type(p) for p in split_toplevel(s[1:-1]))
        )
    if s in ("string", "bytes", "address", "bool"):
        return AbiType(s)
    if s.startswith("uint"):
        bits = int(s[4:]) if s[4:] else 256
        if not 8 <= bits <= 256 or bits % 8:
            raise ValueError(f"bad uint width {s!r}")
        return AbiType("uint", bits=bits)
    if s.startswith("int"):
        bits = int(s[3:]) if s[3:] else 256
        if not 8 <= bits <= 256 or bits % 8:
            raise ValueError(f"bad int width {s!r}")
        return AbiType("int", bits=bits)
    if s.startswith("bytes"):
        n = int(s[5:])
        if not 1 <= n <= 32:
            raise ValueError(f"bad fixed-bytes width {s!r}")
        return AbiType("fbytes", bits=n)
    raise ValueError(f"unsupported ABI type {s!r}")


# ---------------------------------------------------------------------------
# Encoding
# ---------------------------------------------------------------------------


def _pad_right(b: bytes) -> bytes:
    r = len(b) % _WORD
    return b + b"\x00" * (_WORD - r) if r else b


def _encode_static_word(t: AbiType, val: Any) -> bytes:
    if t.base == "uint" or t.base == "bool":
        v = int(val)
        if v < 0:
            raise ValueError(f"uint{t.bits or ''} cannot encode negative {v}")
        if t.base == "uint" and v >> t.bits:
            raise ValueError(f"uint{t.bits} overflow: {v}")
        return v.to_bytes(_WORD, "big")
    if t.base == "int":
        v = int(val)
        if not -(1 << (t.bits - 1)) <= v < (1 << (t.bits - 1)):
            raise ValueError(f"int{t.bits} overflow: {v}")
        return v.to_bytes(_WORD, "big", signed=True)
    if t.base == "address":
        if isinstance(val, str):
            b = bytes.fromhex(val[2:] if val[:2] in ("0x", "0X") else val)
        else:
            b = bytes(val)
        if len(b) != 20:
            raise ValueError("address must be 20 bytes")
        return b"\x00" * 12 + b
    if t.base == "fbytes":
        b = bytes(val)
        if len(b) > t.bits:
            raise ValueError(f"bytes{t.bits} overflow")
        return b.ljust(_WORD, b"\x00")
    raise ValueError(f"not a static word type: {t.base}")


def _encode_value(t: AbiType, val: Any) -> bytes:
    """Full encoding of one value — for dynamic types this is the tail."""
    if t.base == "string":
        raw = val.encode() if isinstance(val, str) else bytes(val)
        return len(raw).to_bytes(_WORD, "big") + _pad_right(raw)
    if t.base == "bytes":
        raw = bytes(val)
        return len(raw).to_bytes(_WORD, "big") + _pad_right(raw)
    if t.base == "array":
        vals = list(val)
        if t.length >= 0 and len(vals) != t.length:
            raise ValueError(
                f"fixed array expects {t.length} elements, got {len(vals)}"
            )
        body = _encode_sequence([t.elem] * len(vals), vals)
        if t.length < 0:
            return len(vals).to_bytes(_WORD, "big") + body
        return body
    if t.base == "tuple":
        vals = list(val)
        if len(vals) != len(t.components):
            raise ValueError(
                f"tuple expects {len(t.components)} fields, got {len(vals)}"
            )
        return _encode_sequence(list(t.components), vals)
    return _encode_static_word(t, val)


def _encode_sequence(types: list[AbiType], values: list[Any]) -> bytes:
    """Head/tail layout of a value sequence (top-level args, tuple fields,
    array elements all share this shape; offsets are relative to the
    sequence start)."""
    heads: list[bytes] = []
    tails: list[bytes] = []
    head_len = _WORD * sum(t.head_words for t in types)
    for t, v in zip(types, values):
        if t.is_dynamic:
            offset = head_len + sum(len(x) for x in tails)
            heads.append(offset.to_bytes(_WORD, "big"))
            tails.append(_encode_value(t, v))
        else:
            heads.append(_encode_value(t, v))
    return b"".join(heads) + b"".join(tails)


def abi_encode(types: list[str], values: list[Any]) -> bytes:
    """Head/tail ABI encoding of a value tuple."""
    if len(types) != len(values):
        raise ValueError("types/values length mismatch")
    return _encode_sequence([parse_type(t) for t in types], list(values))


# ---------------------------------------------------------------------------
# Decoding (strict: malformed offsets/lengths raise)
# ---------------------------------------------------------------------------


def _word_at(data: bytes, pos: int) -> bytes:
    if pos < 0 or pos + _WORD > len(data):
        raise ValueError("abi decode: word out of range")
    return data[pos : pos + _WORD]


def _decode_static_word(t: AbiType, word: bytes) -> Any:
    if t.base == "uint":
        return int.from_bytes(word, "big")
    if t.base == "bool":
        return bool(int.from_bytes(word, "big"))
    if t.base == "int":
        return int.from_bytes(word, "big", signed=True)
    if t.base == "address":
        return word[12:]
    if t.base == "fbytes":
        return word[: t.bits]
    raise ValueError(f"not a static word type: {t.base}")


def _decode_value(t: AbiType, data: bytes, pos: int) -> Any:
    if t.base in ("string", "bytes"):
        n = int.from_bytes(_word_at(data, pos), "big")
        raw = data[pos + _WORD : pos + _WORD + n]
        if len(raw) != n:
            raise ValueError("abi decode: truncated dynamic data")
        return raw.decode() if t.base == "string" else raw
    if t.base == "array":
        if t.length < 0:
            n = int.from_bytes(_word_at(data, pos), "big")
            # every element occupies ≥1 head word: a declared length beyond
            # that is malformed, not a multi-terabyte allocation
            need = n * t.elem.head_words
            if pos + _WORD + need * _WORD > len(data):
                raise ValueError("abi decode: array length exceeds calldata")
            return _decode_sequence([t.elem] * n, data, pos + _WORD)
        return _decode_sequence([t.elem] * t.length, data, pos)
    if t.base == "tuple":
        return _decode_sequence(list(t.components), data, pos)
    return _decode_static_word(t, _word_at(data, pos))


def _decode_sequence(types: list[AbiType], data: bytes, base: int) -> list[Any]:
    """Decode a head/tail sequence starting at `base`; dynamic offsets in
    the heads are relative to `base` (the enclosing frame)."""
    out: list[Any] = []
    pos = base
    for t in types:
        if t.is_dynamic:
            offset = int.from_bytes(_word_at(data, pos), "big")
            out.append(_decode_value(t, data, base + offset))
            pos += _WORD
        else:
            out.append(_decode_value(t, data, pos))
            pos += _WORD * t.head_words
    return out


def abi_decode(types: list[str], data: bytes) -> list[Any]:
    return _decode_sequence([parse_type(t) for t in types], data, 0)


# ---------------------------------------------------------------------------
# Selector-aware codec
# ---------------------------------------------------------------------------


class ABICodec:
    """Selector-aware codec bound to a crypto suite's hash
    (reference: ContractABICodec + getFuncSelector)."""

    def __init__(self, hash_fn):
        self._hash = hash_fn

    def selector(self, signature: str) -> bytes:
        return self._hash(signature.encode())[:4]

    @staticmethod
    def _sig_types(signature: str) -> list[str]:
        inner = signature[signature.index("(") + 1 : signature.rindex(")")]
        return split_toplevel(inner)

    def encode_call(self, signature: str, *values: Any) -> bytes:
        return self.selector(signature) + abi_encode(
            self._sig_types(signature), list(values)
        )

    def decode_input(self, signature: str, data: bytes) -> list[Any]:
        """Decode calldata that includes the 4-byte selector."""
        return abi_decode(self._sig_types(signature), data[4:])

    def encode_output(self, types: list[str], *values: Any) -> bytes:
        return abi_encode(types, list(values))

    def decode_output(self, types: list[str], data: bytes) -> list[Any]:
        return abi_decode(types, data)
