"""Codecs: deterministic wire/storage serialization + contract ABI."""

from .flat import FlatReader, FlatWriter  # noqa: F401
