"""JSON-RPC client, account signing, and contract helper.

Reference surface: bcos-cpp-sdk/rpc/JsonRpcImpl.cpp (the method wrappers),
bcos-cpp-sdk/SdkFactory.cpp (client assembly), DuplicateTransactionFactory
(the TPS-flood helper, bcos-rpc/jsonrpc/DupTestTxJsonRpcImpl_2_0.h) —
`Account.duplicate_signed` serves that role for benchmarks.
"""

from __future__ import annotations

import itertools
import json
import ssl
import time
import urllib.request

from ..codec.abi import ABICodec
from ..crypto.suite import CryptoSuite, KeyPair, ecdsa_suite, sm_suite
from ..protocol.transaction import Transaction, TransactionFactory
from ..utils.bytesutil import from_hex, to_hex


class RpcError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"rpc error {code}: {message}")
        self.code = code
        self.message = message


class ReceiptTimeout(Exception):
    pass


class Client:
    """JSON-RPC 2.0 over HTTP(S).  `ca_cert` verifies a TLS node endpoint
    (build_chain --ssl deployments)."""

    def __init__(
        self,
        url: str,
        group: str = "group0",
        node: str = "",
        timeout: float = 15.0,
        ca_cert: str | None = None,
    ):
        self.url = url
        self.group = group
        self.node = node
        self.timeout = timeout
        self._ids = itertools.count(1)
        self._ssl_ctx: ssl.SSLContext | None = None
        if url.startswith("https"):
            self._ssl_ctx = ssl.create_default_context(cafile=ca_cert)
            self._ssl_ctx.check_hostname = False

    # -- transport -----------------------------------------------------------

    def request(self, method: str, *params):
        body = {
            "jsonrpc": "2.0",
            "id": next(self._ids),
            "method": method,
            "params": list(params),
        }
        req = urllib.request.Request(
            self.url,
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        resp = urllib.request.urlopen(
            req, timeout=self.timeout, context=self._ssl_ctx
        )
        out = json.loads(resp.read())
        if "error" in out:
            raise RpcError(out["error"].get("code", -1), out["error"].get("message", ""))
        return out["result"]

    def _grouped(self, method: str, *params):
        return self.request(method, self.group, self.node, *params)

    # -- chain reads (JsonRpcInterface.cpp:16-65 surface) ---------------------

    def get_block_number(self) -> int:
        return self.request("getBlockNumber")

    def get_block_by_number(self, number: int, with_txs: bool = False) -> dict:
        # reference param order: (group, node, number, onlyHeader, onlyTxHash)
        return self._grouped("getBlockByNumber", number, False, not with_txs)

    def get_block_by_hash(self, block_hash: str, with_txs: bool = False) -> dict:
        return self._grouped("getBlockByHash", block_hash, False, not with_txs)

    def get_block_hash_by_number(self, number: int) -> str:
        return self._grouped("getBlockHashByNumber", number)

    def get_transaction(self, tx_hash: str, with_proof: bool = True) -> dict:
        return self._grouped("getTransaction", tx_hash, with_proof)

    def get_transaction_receipt(self, tx_hash: str, with_proof: bool = True) -> dict:
        return self._grouped("getTransactionReceipt", tx_hash, with_proof)

    def get_proof_batch(self, tx_hashes: list[str], kind: str = "tx") -> dict:
        """N merkle proofs in one round trip (served from the node's
        ProofPlane frozen-tree cache): ``{"kind", "proofs": [doc|None]}``,
        each doc carrying blockNumber/index/leaves/path."""
        return self._grouped("getProofBatch", list(tx_hashes), kind)

    def get_state_proof(
        self, keys: list[tuple[str, str]], number: int | None = None
    ) -> dict:
        """N state-membership proofs in one round trip (served from the
        node's StatePlane): ``keys`` is ``[(table, key_hex), ...]``;
        returns ``{"proofs": [doc|None]}``, each doc carrying the row
        bytes, the header commitment, and the chained pageProof/topProof
        in the shared index/leaves/path shape."""
        return self._grouped(
            "getStateProof",
            [{"table": t, "key": k} for t, k in keys],
            number,
        )

    def get_code(self, address: str) -> str:
        return self._grouped("getCode", address)

    def get_abi(self, address: str) -> str:
        return self._grouped("getABI", address)

    def get_sealer_list(self) -> list:
        return self.request("getSealerList")

    def get_observer_list(self) -> list:
        return self.request("getObserverList")

    def get_pbft_view(self) -> int:
        return self.request("getPbftView")

    def get_pending_tx_size(self) -> int:
        return self.request("getPendingTxSize")

    def get_sync_status(self) -> dict:
        return self.request("getSyncStatus")

    def get_consensus_status(self) -> dict:
        return self.request("getConsensusStatus")

    def get_system_config_by_key(self, key: str) -> dict:
        return self._grouped("getSystemConfigByKey", key)

    def get_total_transaction_count(self) -> dict:
        return self.request("getTotalTransactionCount")

    def get_peers(self) -> dict:
        return self.request("getPeers")

    def get_group_list(self) -> list:
        return self.request("getGroupList")

    def get_group_info(self) -> dict:
        return self.request("getGroupInfo", self.group)

    # -- writes ---------------------------------------------------------------

    def send_raw_transaction(self, tx: Transaction) -> dict:
        return self._grouped("sendTransaction", to_hex(tx.encode()))

    def call(self, to: bytes | str, data: bytes) -> dict:
        to_h = to if isinstance(to, str) else to_hex(to)
        return self._grouped("call", to_h, to_hex(data))

    def wait_for_receipt(
        self, tx_hash: str, timeout: float = 30.0, interval: float = 0.1
    ) -> dict:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                return self.get_transaction_receipt(tx_hash)
            except RpcError:
                time.sleep(interval)
        raise ReceiptTimeout(tx_hash)


class Account:
    """Key management + transaction building (bcos-cpp-sdk TransactionBuilder)."""

    def __init__(
        self,
        suite: CryptoSuite | None = None,
        keypair: KeyPair | None = None,
        sm_crypto: bool = False,
        chain_id: str = "chain0",
        group_id: str = "group0",
    ):
        self.suite = suite or (sm_suite() if sm_crypto else ecdsa_suite())
        self.keypair = keypair or self.suite.signature_impl.generate_keypair()
        self.factory = TransactionFactory(self.suite)
        self.chain_id = chain_id
        self.group_id = group_id
        self._nonce = itertools.count(int(time.time() * 1000))

    @property
    def address(self) -> bytes:
        return self.suite.calculate_address(self.keypair.pub)

    def sign_tx(
        self,
        to: bytes = b"",
        data: bytes = b"",
        block_limit: int = 500,
        nonce: str | None = None,
        abi: str = "",
    ) -> Transaction:
        return self.factory.create_signed(
            self.keypair,
            chain_id=self.chain_id,
            group_id=self.group_id,
            block_limit=block_limit,
            nonce=nonce if nonce is not None else f"sdk-{next(self._nonce)}",
            to=to,
            input=data,
            abi=abi,
        )

    def duplicate_signed(self, tx: Transaction, count: int) -> list[Transaction]:
        """N re-signed copies with fresh nonces — the reference's TPS-flood
        helper (DuplicateTransactionFactory.cpp duplicates a signed tx for
        load tests)."""
        return [
            self.sign_tx(
                to=tx.to, data=tx.input, block_limit=tx.block_limit, abi=tx.abi
            )
            for _ in range(count)
        ]


class Contract:
    """ABI-aware deploy/send/call wrapper (bcos-cpp-sdk TransactionManager +
    ContractABICodec glue)."""

    def __init__(self, client: Client, account: Account, address: bytes = b""):
        self.client = client
        self.account = account
        self.address = address
        self.codec = ABICodec(account.suite.hash)

    def deploy(self, bytecode: bytes, abi: str = "", timeout: float = 30.0):
        """Deploy `bytecode` (CREATE); returns (contract_address, receipt)."""
        # block_limit must be FINAL before signing — it is part of the
        # signed payload, so mutating it afterwards would break the
        # signature (and recover a wrong sender) on any chain past genesis
        block_limit = self.client.get_block_number() + 500
        tx = self.account.sign_tx(
            to=b"", data=bytecode, abi=abi, block_limit=block_limit
        )
        res = self.client.send_raw_transaction(tx)
        rc = self.client.wait_for_receipt(res["transactionHash"], timeout=timeout)
        if rc.get("status") != 0:
            raise RpcError(rc.get("status", -1), f"deploy reverted: {rc}")
        self.address = from_hex(rc["contractAddress"])
        return self.address, rc

    def send(self, signature: str, *args, timeout: float = 30.0) -> dict:
        """State-changing call: sign, submit, wait for the receipt."""
        data = self.codec.encode_call(signature, *args)
        block_limit = self.client.get_block_number() + 500
        tx = self.account.sign_tx(
            to=self.address, data=data, block_limit=block_limit
        )
        res = self.client.send_raw_transaction(tx)
        return self.client.wait_for_receipt(res["transactionHash"], timeout=timeout)

    def call(self, signature: str, out_types: list[str], *args):
        """Read-only call; decodes the output tuple."""
        data = self.codec.encode_call(signature, *args)
        out = self.client.call(self.address, data)
        raw = from_hex(out.get("output", "0x"))
        if not out_types:
            return ()
        return self.codec.decode_output(out_types, raw)
