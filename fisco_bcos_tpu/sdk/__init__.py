"""Client SDK — the bcos-sdk (bcos-cpp-sdk) analog in Python.

Reference: bcos-sdk/bcos-cpp-sdk/{rpc/JsonRpcImpl.cpp, SdkFactory.cpp} plus
the event/amop client channels.  `Client` speaks JSON-RPC over HTTP(S);
`Account` signs transactions; `Contract` wraps ABI encode/decode around
deploy/send/call.
"""

from .client import Account, Client, Contract, ReceiptTimeout

__all__ = ["Account", "Client", "Contract", "ReceiptTimeout"]
