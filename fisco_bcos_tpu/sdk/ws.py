"""SDK WebSocket client — push-capable channel (events, AMOP, block notify).

Reference: bcos-cpp-sdk/ws/Service.cpp + event/amop client wrappers. Minimal
RFC 6455 client on stdlib sockets: masked frames out, notification dispatch
on a reader thread, request/response correlation by JSON-RPC id.
"""

from __future__ import annotations

import base64
import itertools
import json
import os
import socket
import struct
import threading
from typing import Callable


class WsClient:
    def __init__(self, host: str, port: int, timeout: float = 15.0):
        self.timeout = timeout
        self.sock = socket.create_connection((host, port), timeout=timeout)
        key = base64.b64encode(os.urandom(16)).decode()
        self.sock.sendall(
            (
                f"GET / HTTP/1.1\r\nHost: {host}:{port}\r\n"
                "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n\r\n"
            ).encode()
        )
        data = b""
        while b"\r\n\r\n" not in data:
            chunk = self.sock.recv(4096)
            if not chunk:
                raise ConnectionError("ws handshake failed")
            data += chunk
        if b"101" not in data.split(b"\r\n", 1)[0]:
            raise ConnectionError(f"ws handshake rejected: {data[:100]!r}")
        self.sock.settimeout(None)
        self._ids = itertools.count(1)
        self._pending: dict[int, dict | None] = {}
        self._cv = threading.Condition()
        self.notifications: list[dict] = []  # push messages, in arrival order
        self.on_notify: Callable[[dict], None] | None = None
        self._open = True
        threading.Thread(target=self._reader, name="ws-client", daemon=True).start()

    # -- frames ---------------------------------------------------------------

    def _send_frame(self, opcode: int, payload: bytes) -> None:
        head = bytes([0x80 | opcode])
        n = len(payload)
        if n < 126:
            head += bytes([0x80 | n])
        elif n < 1 << 16:
            head += bytes([0x80 | 126]) + struct.pack(">H", n)
        else:
            head += bytes([0x80 | 127]) + struct.pack(">Q", n)
        mask = os.urandom(4)
        body = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        self.sock.sendall(head + mask + body)

    def _recv_exact(self, n: int) -> bytes | None:
        buf = b""
        while len(buf) < n:
            try:
                chunk = self.sock.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf += chunk
        return buf

    def _reader(self) -> None:
        while self._open:
            head = self._recv_exact(2)
            if head is None:
                break
            opcode = head[0] & 0x0F
            length = head[1] & 0x7F
            if length == 126:
                ext = self._recv_exact(2)
                if ext is None:
                    break
                (length,) = struct.unpack(">H", ext)
            elif length == 127:
                ext = self._recv_exact(8)
                if ext is None:
                    break
                (length,) = struct.unpack(">Q", ext)
            payload = self._recv_exact(length) if length else b""
            if payload is None:
                break
            if opcode == 0x9:  # ping
                self._send_frame(0xA, payload)
                continue
            if opcode == 0x8:  # close
                break
            try:
                msg = json.loads(payload)
            except ValueError:
                continue
            with self._cv:
                if "id" in msg and msg["id"] in self._pending:
                    self._pending[msg["id"]] = msg
                    self._cv.notify_all()
                else:
                    self.notifications.append(msg)
                    self._cv.notify_all()
            if "id" not in msg and self.on_notify is not None:
                try:
                    self.on_notify(msg)
                except Exception as e:
                    from ..utils.log import note_swallowed

                    note_swallowed("sdk.ws.on_notify", e)
        self._open = False
        with self._cv:
            self._cv.notify_all()

    # -- rpc ------------------------------------------------------------------

    def request(self, method: str, *params):
        rid = next(self._ids)
        with self._cv:
            self._pending[rid] = None
        self._send_frame(
            0x1,
            json.dumps(
                {"jsonrpc": "2.0", "id": rid, "method": method, "params": list(params)}
            ).encode(),
        )
        with self._cv:
            self._cv.wait_for(
                lambda: self._pending[rid] is not None or not self._open,
                timeout=self.timeout,
            )
            resp = self._pending.pop(rid)
        if resp is None:
            raise TimeoutError(f"ws request {method} timed out")
        if "error" in resp:
            raise RuntimeError(f"rpc error: {resp['error']}")
        return resp["result"]

    def wait_notification(self, predicate=None, timeout: float = 15.0) -> dict | None:
        """Pop the first (matching) push notification, waiting if needed."""
        deadline = threading.TIMEOUT_MAX if timeout is None else timeout

        def find():
            for i, m in enumerate(self.notifications):
                if predicate is None or predicate(m):
                    return i
            return None

        with self._cv:
            ok = self._cv.wait_for(
                lambda: find() is not None or not self._open, timeout=deadline
            )
            idx = find()
            if not ok or idx is None:
                return None
            return self.notifications.pop(idx)

    # -- channels -------------------------------------------------------------

    def subscribe_event(self, filter_obj: dict) -> str:
        return self.request("subscribeEvent", filter_obj)

    def unsubscribe_event(self, sub_id: str) -> bool:
        return self.request("unsubscribeEvent", sub_id)

    def subscribe_block_number(self) -> bool:
        return self.request("subscribeBlockNumber")

    def amop_subscribe(self, *topics: str) -> bool:
        return self.request("amopSubscribe", *topics)

    def amop_publish(self, topic: str, data: bytes) -> int:
        return self.request("amopPublish", topic, data.hex())

    def amop_broadcast(self, topic: str, data: bytes) -> int:
        return self.request("amopBroadcast", topic, data.hex())

    def close(self) -> None:
        self._open = False
        try:
            self._send_frame(0x8, b"")
            self.sock.close()
        except OSError:
            pass
