"""JSON-RPC over HTTP (stdlib ThreadingHTTPServer).

Reference transport: bcos-rpc over bcos-boostssl ws/http. HTTP POST with
JSON-RPC 2.0 bodies (single or batch); the ws push channels (AMOP, event
subscription, block notify) ride the amop/event modules.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils.log import get_logger
from .jsonrpc import JsonRpcImpl

_log = get_logger("rpc-http")


def _accepts_openmetrics(accept: str | None) -> bool:
    """True when the Accept header opts INTO application/openmetrics-text:
    an offer with q=0 is an explicit refusal, not an opt-in."""
    for part in (accept or "").split(","):
        media, _, params = part.partition(";")
        if "openmetrics" not in media:
            continue
        q = 1.0
        for p in params.split(";"):
            k, _, v = p.strip().partition("=")
            if k == "q":
                try:
                    q = float(v)
                except ValueError:
                    q = 0.0
        if q > 0:
            return True
    return False


class RpcHttpServer:
    """`ssl_context` (gateway.tls.make_server_context) upgrades to HTTPS —
    the reference's boostssl TLS RPC channel."""

    def __init__(
        self,
        impl: JsonRpcImpl,
        host: str = "127.0.0.1",
        port: int = 20200,
        ssl_context=None,
        metrics=None,
        tracer=None,
        health=None,
        trace_tx=None,
        pipeline=None,
        profile=None,
        device=None,
        fleet=None,
        round_doc=None,
        rounds=None,
        storage=None,
    ):
        self.impl = impl
        # `metrics` needs .render() -> str; `tracer` needs .export_json() ->
        # str; `health` needs .to_json() -> str — satisfied by
        # MetricsRegistry/Tracer/HealthRegistry in-process and by the
        # RemoteTelemetry proxy in the split (Pro/Max) deployment.
        # `trace_tx` (tx-hash hex -> critical-path dict) serves
        # GET /trace/tx/<hash>; `pipeline` (() -> dict) serves the stage
        # occupancy/watermark document at GET /pipeline; `profile`
        # (seconds -> dict) serves the sampling profiler at
        # GET /profile?seconds=N; `device` (() -> dict) serves the device
        # observatory (compile ledger + phase attribution) at GET /device.
        # When omitted, a tracer exposing its own
        # .trace_tx/.pipeline/.profile/.device (RemoteTelemetry) is used.
        self.metrics = metrics
        self.tracer = tracer
        self.health = health
        self.trace_tx = trace_tx or getattr(tracer, "trace_tx", None)
        self.pipeline = pipeline or getattr(tracer, "pipeline", None)
        self.profile = profile or getattr(tracer, "profile", None)
        self.device = device or getattr(tracer, "device", None)
        # fleet observatory (ISSUE 16): `fleet` (() -> dict) merges every
        # peer's telemetry into one cluster doc at GET /fleet; `round_doc`
        # (height -> dict) serves per-round forensics at GET /round/<h>;
        # `rounds` (last -> dict) the recent-rounds sweep at GET /rounds
        self.fleet = fleet or getattr(tracer, "fleet", None)
        self.round_doc = round_doc or getattr(tracer, "round_doc", None)
        self.rounds = rounds or getattr(tracer, "rounds", None)
        # storage observatory (ISSUE 19): `storage` (() -> dict) serves the
        # commit-path codec/copy ledger + 2PC shard attribution document
        self.storage = storage or getattr(tracer, "storage", None)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self) -> None:  # noqa: N802
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(length)
                    req = json.loads(body)
                    # strike attribution: this client's IP is the source
                    # the txpool files invalid-signature strikes against
                    from .jsonrpc import client_source

                    with client_source(f"rpc:{self.client_address[0]}"):
                        if isinstance(req, list):
                            resp = [outer.impl.handle(r) for r in req]
                        else:
                            resp = outer.impl.handle(req)
                    data = json.dumps(resp).encode()
                    self.send_response(200)
                except Exception as e:
                    data = json.dumps(
                        {
                            "jsonrpc": "2.0",
                            "id": None,
                            "error": {"code": -32700, "message": f"parse error: {e}"},
                        }
                    ).encode()
                    self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self) -> None:  # noqa: N802 — telemetry scrape
                code = 200
                if self.path == "/metrics" and outer.metrics is not None:
                    # exemplars only under negotiated OpenMetrics — the
                    # classic 0.0.4 text parser rejects the suffix
                    om = _accepts_openmetrics(self.headers.get("Accept"))
                    try:
                        data = outer.metrics.render(openmetrics=om).encode()
                    except TypeError:  # renderer without the kwarg
                        data = outer.metrics.render().encode()
                        om = False
                    if om and not data.strip():
                        # a failed split-mode render returns "" — an empty
                        # body labeled OpenMetrics lacks the mandatory
                        # '# EOF' and fails strict scrapers; serve it as
                        # (empty) classic text instead
                        om = False
                    ctype = (
                        "application/openmetrics-text; version=1.0.0; "
                        "charset=utf-8"
                        if om
                        else "text/plain; version=0.0.4"
                    )
                elif self.path == "/trace" and outer.tracer is not None:
                    # Chrome trace-event JSON — load in Perfetto as-is
                    data = outer.tracer.export_json().encode()
                    ctype = "application/json"
                elif (
                    self.path.startswith("/trace/tx/")
                    and outer.trace_tx is not None
                ):
                    # stitched per-transaction critical path (ISSUE 4):
                    # every lifecycle span sharing the tx's trace set,
                    # ordered, with the dominant stage named
                    doc = outer.trace_tx(
                        self.path.split("?", 1)[0].rsplit("/", 1)[1]
                    )
                    data = json.dumps(doc, default=str).encode()
                    ctype = "application/json"
                    if not doc.get("found"):
                        code = 404
                elif (
                    self.path.split("?", 1)[0] == "/pipeline"
                    and outer.pipeline is not None
                ):
                    # stage occupancy + blocked-on edges + backpressure
                    # watermark timelines (ISSUE 9 pipeline observatory)
                    data = json.dumps(outer.pipeline(), default=str).encode()
                    ctype = "application/json"
                elif (
                    self.path.split("?", 1)[0] == "/device"
                    and outer.device is not None
                ):
                    # device observatory (ISSUE 13): compile ledger with
                    # cold-vs-persistent-cache attribution, per-op phase
                    # totals, memory watermarks, recompile-storm state
                    data = json.dumps(outer.device(), default=str).encode()
                    ctype = "application/json"
                elif (
                    self.path.split("?", 1)[0] == "/storage"
                    and outer.storage is not None
                ):
                    # storage observatory (ISSUE 19): per-table codec
                    # traffic, copy-amplification ledger per block,
                    # per-shard 2PC attribution
                    data = json.dumps(outer.storage(), default=str).encode()
                    ctype = "application/json"
                elif (
                    self.path.split("?", 1)[0] == "/profile"
                    and outer.profile is not None
                ):
                    # sampling wall-clock profiler: blocks for ?seconds=N
                    # (server-side clamped) and returns collapsed stacks +
                    # per-function self time
                    from urllib.parse import parse_qs, urlsplit

                    qs = parse_qs(urlsplit(self.path).query)
                    seconds = (qs.get("seconds") or ["2"])[0]
                    doc = outer.profile(seconds)
                    data = json.dumps(doc, default=str).encode()
                    ctype = "application/json"
                    if doc.get("error"):
                        code = 503
                elif (
                    self.path.split("?", 1)[0] == "/fleet"
                    and outer.fleet is not None
                ):
                    # federated cluster document (ISSUE 16): this node pulls
                    # every committee peer's snapshot + round ledger over
                    # the gateway mesh and merges them — unreachable peers
                    # appear as degraded rows, never vanish
                    data = json.dumps(outer.fleet(), default=str).encode()
                    ctype = "application/json"
                elif (
                    self.path.startswith("/round/")
                    and outer.round_doc is not None
                ):
                    # cross-node forensics for one consensus height: aligned
                    # phase spans, per-signer vote arrivals, straggler
                    try:
                        height = int(
                            self.path.split("?", 1)[0].rsplit("/", 1)[1]
                        )
                    except ValueError:
                        self.send_response(404)
                        self.end_headers()
                        return
                    doc = outer.round_doc(height)
                    data = json.dumps(doc, default=str).encode()
                    ctype = "application/json"
                    if not doc.get("found"):
                        code = 404
                elif (
                    self.path.split("?", 1)[0] == "/rounds"
                    and outer.rounds is not None
                ):
                    # recent rounds with skew percentiles; ?last=N bounds it
                    from urllib.parse import parse_qs, urlsplit

                    qs = parse_qs(urlsplit(self.path).query)
                    try:
                        last = int((qs.get("last") or ["32"])[0])
                    except ValueError:
                        last = 32
                    data = json.dumps(
                        outer.rounds(last), default=str
                    ).encode()
                    ctype = "application/json"
                elif self.path == "/health" and outer.health is not None:
                    # degraded-mode registry (resilience.HEALTH or the
                    # split-mode RemoteTelemetry proxy). 503 ONLY on
                    # "critical" (not ready: probes should pull the node);
                    # "degraded" still answers 200 — the node is serving
                    # through fallbacks and the JSON body carries the detail
                    data = outer.health.to_json().encode()
                    ctype = "application/json"
                    try:
                        if json.loads(data).get("status") == "critical":
                            code = 503
                    except ValueError:
                        code = 503
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, fmt, *args):  # quiet
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        if ssl_context is not None:
            self._server.socket = ssl_context.wrap_socket(
                self._server.socket, server_side=True
            )
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="rpc-http", daemon=True
        )
        self._thread.start()
        _log.info("json-rpc listening on %d", self.port)

    def stop(self) -> None:
        if self._thread is not None:
            # shutdown() blocks until serve_forever acknowledges — calling
            # it on a never-started server waits forever
            self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
