"""JSON-RPC over HTTP (stdlib ThreadingHTTPServer).

Reference transport: bcos-rpc over bcos-boostssl ws/http. HTTP POST with
JSON-RPC 2.0 bodies (single or batch); the ws push channels (AMOP, event
subscription, block notify) ride the amop/event modules.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils.log import get_logger
from .jsonrpc import JsonRpcImpl

_log = get_logger("rpc-http")


class RpcHttpServer:
    """`ssl_context` (gateway.tls.make_server_context) upgrades to HTTPS —
    the reference's boostssl TLS RPC channel."""

    def __init__(
        self,
        impl: JsonRpcImpl,
        host: str = "127.0.0.1",
        port: int = 20200,
        ssl_context=None,
        metrics=None,
        tracer=None,
        health=None,
    ):
        self.impl = impl
        # `metrics` needs .render() -> str; `tracer` needs .export_json() ->
        # str; `health` needs .to_json() -> str — satisfied by
        # MetricsRegistry/Tracer/HealthRegistry in-process and by the
        # RemoteTelemetry proxy in the split (Pro/Max) deployment
        self.metrics = metrics
        self.tracer = tracer
        self.health = health
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self) -> None:  # noqa: N802
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(length)
                    req = json.loads(body)
                    if isinstance(req, list):
                        resp = [outer.impl.handle(r) for r in req]
                    else:
                        resp = outer.impl.handle(req)
                    data = json.dumps(resp).encode()
                    self.send_response(200)
                except Exception as e:
                    data = json.dumps(
                        {
                            "jsonrpc": "2.0",
                            "id": None,
                            "error": {"code": -32700, "message": f"parse error: {e}"},
                        }
                    ).encode()
                    self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self) -> None:  # noqa: N802 — telemetry scrape
                code = 200
                if self.path == "/metrics" and outer.metrics is not None:
                    data = outer.metrics.render().encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path == "/trace" and outer.tracer is not None:
                    # Chrome trace-event JSON — load in Perfetto as-is
                    data = outer.tracer.export_json().encode()
                    ctype = "application/json"
                elif self.path == "/health" and outer.health is not None:
                    # degraded-mode registry (resilience.HEALTH or the
                    # split-mode RemoteTelemetry proxy). 503 ONLY on
                    # "critical" (not ready: probes should pull the node);
                    # "degraded" still answers 200 — the node is serving
                    # through fallbacks and the JSON body carries the detail
                    data = outer.health.to_json().encode()
                    ctype = "application/json"
                    try:
                        if json.loads(data).get("status") == "critical":
                            code = 503
                    except ValueError:
                        code = 503
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, fmt, *args):  # quiet
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        if ssl_context is not None:
            self._server.socket = ssl_context.wrap_socket(
                self._server.socket, server_side=True
            )
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="rpc-http", daemon=True
        )
        self._thread.start()
        _log.info("json-rpc listening on %d", self.port)

    def stop(self) -> None:
        if self._thread is not None:
            # shutdown() blocks until serve_forever acknowledges — calling
            # it on a never-started server waits forever
            self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
