"""Group manager + multi-group JSON-RPC routing.

Reference: bcos-rpc/groupmgr/{GroupManager, AirGroupManager, NodeService}
— the RPC layer holds one NodeService per (group, node) and routes each
request by its group parameter; group listing/info methods aggregate over
the registry.
"""

from __future__ import annotations

import threading

from .jsonrpc import JsonRpcImpl


class GroupManager:
    def __init__(self):
        self._impls: dict[str, JsonRpcImpl] = {}
        self._lock = threading.RLock()

    def add_node(self, node) -> JsonRpcImpl:
        impl = JsonRpcImpl(node)
        with self._lock:
            self._impls[node.config.group_id] = impl
        return impl

    def impl_for(self, group: str) -> JsonRpcImpl | None:
        with self._lock:
            return self._impls.get(group)

    def groups(self) -> list[str]:
        with self._lock:
            return sorted(self._impls)

    def impls(self) -> list[JsonRpcImpl]:
        with self._lock:
            return [self._impls[g] for g in sorted(self._impls)]


class MultiGroupRpc:
    """Drop-in for JsonRpcImpl.handle: routes by the request's group param
    (first positional param of every grouped method), aggregates the
    group-listing surface."""

    def __init__(self, manager: GroupManager, default_group: str):
        self.manager = manager
        self.default_group = default_group

    def _default(self) -> JsonRpcImpl:
        impl = self.manager.impl_for(self.default_group)
        if impl is None:
            impls = self.manager.impls()
            if not impls:
                raise RuntimeError("no groups registered")
            impl = impls[0]
        return impl

    def handle(self, request: dict) -> dict:
        method = request.get("method", "")
        params = request.get("params", [])
        if method == "getGroupList":
            return self._ok(request, {"groupList": self.manager.groups()})
        if method == "getGroupInfoList":
            return self._ok(
                request,
                [impl.get_group_info() for impl in self.manager.impls()],
            )
        impl = self._default()
        if (
            params
            and isinstance(params[0], str)
            and self.manager.impl_for(params[0]) is not None
        ):
            impl = self.manager.impl_for(params[0])
        elif params and isinstance(params[0], str) and params[0]:
            # an explicit unknown group is an error, not a silent default
            # (only for methods whose first param is a group name)
            if params[0] not in ("",) and self._looks_like_group(method):
                return {
                    "jsonrpc": "2.0",
                    "id": request.get("id"),
                    "error": {"code": -32602, "message": f"unknown group: {params[0]}"},
                }
        return impl.handle(request)

    @staticmethod
    def _looks_like_group(method: str) -> bool:
        return method in {
            "call", "sendTransaction", "getTransaction", "getTransactionReceipt",
            "getBlockByHash", "getBlockByNumber", "getBlockHashByNumber",
            "getCode", "getABI", "getSealerList", "getObserverList",
            "getPbftView", "getPendingTxSize", "getSyncStatus",
            "getConsensusStatus", "getSystemConfigByKey",
            "getTotalTransactionCount", "getGroupPeers", "getGroupInfo",
            "getGroupNodeInfo",
        }

    @staticmethod
    def _ok(request: dict, result) -> dict:
        return {"jsonrpc": "2.0", "id": request.get("id"), "result": result}
