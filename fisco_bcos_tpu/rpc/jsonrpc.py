"""JSON-RPC 2.0 method implementations.

Reference: bcos-rpc/jsonrpc/JsonRpcInterface.cpp:16-65 (the method table) and
JsonRpcImpl_2_0.cpp (implementations; sendTransaction:417 co_awaits the
txpool). JSON field shapes follow the reference's responses (hex-encoded
hashes/bytes with 0x prefixes).
"""

from __future__ import annotations

import contextvars
import time
from contextlib import contextmanager
from typing import Any

from ..node.node import Node
from ..protocol.block import Block
from ..protocol.block_header import BlockHeader
from ..protocol.receipt import TransactionReceipt
from ..protocol.transaction import Transaction
from ..utils.bytesutil import from_hex, to_hex
from ..utils.error import ErrorCode
from ..utils.log import get_logger

_log = get_logger("rpc")


# JSON-RPC methods that open a lifecycle trace root; read polling stays
# span-free so it cannot evict block-lifecycle spans from the bounded ring
TRACED_RPC_METHODS = frozenset({"sendTransaction"})

# Which client is submitting, for the txpool's strike accounting
# (txpool/quota.py). The transports bind their peer address around handle()
# so one client spamming invalid signatures demotes ITSELF, not the shared
# default "local" — a shared strike source would let three garbage txs from
# anyone block every client's submissions (renewable RPC-wide DoS).
CLIENT_SOURCE: contextvars.ContextVar[str] = contextvars.ContextVar(
    "rpc_client_source", default="local"
)


@contextmanager
def client_source(source: str):
    """Bind the submitting client's identity (e.g. ``rpc:<ip>``) for the
    duration of a request dispatch."""
    token = CLIENT_SOURCE.set(source)
    try:
        yield
    finally:
        CLIENT_SOURCE.reset(token)


class JsonRpcError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


def _tx_json(tx: Transaction, suite) -> dict:
    return {
        "version": tx.version,
        "hash": to_hex(tx.hash(suite)),
        "chainID": tx.chain_id,
        "groupID": tx.group_id,
        "blockLimit": tx.block_limit,
        "nonce": tx.nonce,
        "to": to_hex(tx.to) if tx.to else "",
        "from": to_hex(tx.sender) if tx.sender else "",
        "input": to_hex(tx.input),
        "abi": tx.abi,
        "signature": to_hex(tx.signature),
        "importTime": tx.import_time,
        "attribute": tx.attribute,
        "extraData": to_hex(tx.extra_data),
    }


def _receipt_json(rc: TransactionReceipt, tx_hash: bytes, suite) -> dict:
    return {
        "version": rc.version,
        "transactionHash": to_hex(tx_hash),
        "receiptHash": to_hex(rc.hash(suite)),
        "blockNumber": rc.block_number,
        "gasUsed": str(rc.gas_used),
        "contractAddress": to_hex(rc.contract_address) if rc.contract_address else "",
        "status": rc.status,
        "output": to_hex(rc.output),
        "logEntries": [
            {
                "address": to_hex(e.address),
                "topics": [to_hex(t) for t in e.topics],
                "data": to_hex(e.data),
            }
            for e in rc.log_entries
        ],
        "effectiveGasPrice": rc.effective_gas_price,
    }


def _proof_json(items, idx: int, n: int) -> dict:
    """Wide-merkle proof JSON shape (shared by getTransaction's txProof,
    getTransactionReceipt's receiptProof and getProofBatch)."""
    return {
        "index": idx,
        "leaves": n,
        "path": [[to_hex(g) for g in it.group] for it in items],
    }


def _header_json(h: BlockHeader, suite) -> dict:
    return {
        "version": h.version,
        "hash": to_hex(h.hash(suite)),
        "parentInfo": [
            {"blockNumber": p.number, "blockHash": to_hex(p.hash)} for p in h.parent_info
        ],
        "txsRoot": to_hex(h.txs_root),
        "receiptsRoot": to_hex(h.receipts_root),
        "stateRoot": to_hex(h.state_root),
        "number": h.number,
        "gasUsed": str(h.gas_used),
        "timestamp": h.timestamp,
        "sealer": h.sealer,
        "sealerList": [to_hex(s) for s in h.sealer_list],
        "extraData": to_hex(h.extra_data),
        "consensusWeights": list(h.consensus_weights),
        "signatureList": [
            {"index": s.index, "signature": to_hex(s.signature)}
            for s in h.signature_list
        ],
        # only when the succinct state plane carried one (FISCO_STATE_PROOF):
        # the anchor getStateProof results verify against
        **(
            {"stateCommitment": to_hex(h.state_commitment)}
            if h.state_commitment
            else {}
        ),
    }


def _block_json(blk: Block, suite, with_txs: bool) -> dict:
    out = _header_json(blk.header, suite)
    if with_txs:
        out["transactions"] = [_tx_json(t, suite) for t in blk.transactions]
    else:
        out["transactions"] = [to_hex(h) for h in blk.tx_metadata]
    return out


class JsonRpcImpl:
    """Method table bound to one node (group manager arrives with multi-group)."""

    def __init__(self, node: Node):
        self.node = node
        self.suite = node.suite
        self.methods = {
            "call": self.call,
            "sendTransaction": self.send_transaction,
            "getTransaction": self.get_transaction,
            "getTransactionReceipt": self.get_transaction_receipt,
            "getProofBatch": self.get_proof_batch,
            "getStateProof": self.get_state_proof,
            "getBlockByHash": self.get_block_by_hash,
            "getBlockByNumber": self.get_block_by_number,
            "getBlockHashByNumber": self.get_block_hash_by_number,
            "getBlockNumber": self.get_block_number,
            "getCode": self.get_code,
            "getABI": self.get_abi,
            "getSealerList": self.get_sealer_list,
            "getObserverList": self.get_observer_list,
            "getPbftView": self.get_pbft_view,
            "getPendingTxSize": self.get_pending_tx_size,
            "getSyncStatus": self.get_sync_status,
            "getConsensusStatus": self.get_consensus_status,
            "getSystemConfigByKey": self.get_system_config_by_key,
            "getTotalTransactionCount": self.get_total_transaction_count,
            "getPeers": self.get_peers,
            "getGroupPeers": self.get_group_peers,
            "getGroupList": self.get_group_list,
            "getGroupInfo": self.get_group_info,
            "getGroupInfoList": self.get_group_info_list,
            "getGroupNodeInfo": self.get_group_node_info,
        }

    # -- dispatch ------------------------------------------------------------

    def handle(self, request: dict) -> dict:
        rid = request.get("id")
        try:
            method = request.get("method", "")
            fn = self.methods.get(method)
            if fn is None:
                raise JsonRpcError(-32601, f"method not found: {method}")
            params = request.get("params", [])
            if method in TRACED_RPC_METHODS:
                from ..observability import TRACER

                # the lifecycle root (Air mode) or the node-side
                # continuation of the RPC process's root (split mode, via
                # the facade traceparent). Only lifecycle-bearing methods:
                # a span per read poll (getBlockNumber at hundreds/s)
                # would churn the bounded ring and evict the block spans
                # /trace/tx stitches.
                with TRACER.span("rpc.request", method=method):
                    result = fn(*params)
            else:
                result = fn(*params)
            return {"jsonrpc": "2.0", "id": rid, "result": result}
        except JsonRpcError as e:
            return {
                "jsonrpc": "2.0",
                "id": rid,
                "error": {"code": e.code, "message": e.message},
            }
        except Exception as e:  # malformed params etc.
            return {
                "jsonrpc": "2.0",
                "id": rid,
                "error": {"code": -32602, "message": f"invalid params: {e}"},
            }

    # -- tx methods ----------------------------------------------------------

    def send_transaction(self, group: str, node_name: str, data: str, require_proof: bool = False) -> dict:
        tx = Transaction.decode(from_hex(data))
        result = self.node.txpool.submit(tx, source=CLIENT_SOURCE.get())
        if result.status != ErrorCode.SUCCESS:
            raise JsonRpcError(int(result.status), result.status.name)
        # gossip promptly so peers can verify proposals carrying this tx
        self.node.tx_sync.maintain()
        return {
            "transactionHash": to_hex(result.tx_hash),
            "from": to_hex(result.sender),
            "status": int(result.status),
        }

    def call(self, group: str, node_name: str, to: str, data: str) -> dict:
        tx = Transaction(to=from_hex(to), input=from_hex(data))
        rc = self.node.scheduler.call(tx)
        return {
            "blockNumber": self.node.block_number(),
            "status": rc.status,
            "output": to_hex(rc.output),
        }

    def get_transaction(self, group: str, node_name: str, tx_hash: str, proof: bool = False) -> dict:
        h = from_hex(tx_hash)
        tx = self.node.ledger.tx_by_hash(h) or self.node.txpool.get(h)
        if tx is None:
            raise JsonRpcError(-32602, "transaction not found")
        out = _tx_json(tx, self.suite)
        if proof:
            p = self.node.ledger.tx_proof(h)
            if p is not None:
                items, idx, n = p
                out["txProof"] = _proof_json(items, idx, n)
        return out

    def get_transaction_receipt(self, group: str, node_name: str, tx_hash: str, proof: bool = False) -> dict:
        h = from_hex(tx_hash)
        rc = self.node.ledger.receipt_by_hash(h)
        if rc is None:
            raise JsonRpcError(-32602, "receipt not found")
        out = _receipt_json(rc, h, self.suite)
        if proof:
            p = self.node.ledger.receipt_proof(h)
            if p is not None:
                items, idx, n = p
                out["receiptProof"] = _proof_json(items, idx, n)
        return out

    def get_proof_batch(
        self, group: str = "", node_name: str = "",
        tx_hashes: list | None = None, kind: str = "tx",
    ) -> dict:
        """ProofPlane batch surface (ISSUE 7): one request carries N
        hashes, the node answers from the frozen-tree cache — one tree per
        height, O(depth) per proof — instead of N full rebuilds."""
        from ..proofs import MAX_PROOF_BATCH

        if kind not in ("tx", "receipt"):
            raise JsonRpcError(-32602, f"unknown proof kind {kind!r}")
        hashes = [from_hex(h) for h in (tx_hashes or [])]
        if len(hashes) > MAX_PROOF_BATCH:
            raise JsonRpcError(
                -32602, f"proof batch over {MAX_PROOF_BATCH} hashes"
            )
        plane = getattr(self.node, "proof_plane", None)
        if plane is not None:
            results = plane.proof_batch(hashes, kind)
        else:  # cache-off fallback: per-hash direct rebuild
            results = self.node.ledger.proof_batch_direct(hashes, kind)
        proofs = []
        for res in results:
            if res is None:
                proofs.append(None)
                continue
            number, items, idx, n = res
            doc = _proof_json(items, idx, n)
            doc["blockNumber"] = number
            proofs.append(doc)
        return {"kind": kind, "proofs": proofs}

    def get_state_proof(
        self, group: str = "", node_name: str = "",
        keys: list | None = None, number: int | None = None,
    ) -> dict:
        """StatePlane batch surface (ISSUE 18): ``keys`` is a list of
        ``{"table": str, "key": hex}`` rows; the node answers membership
        proofs against the ``state_commitment`` of block ``number``
        (default: the committed head). Each proof doc carries the row
        bytes plus the two chained wide-merkle paths (page subtree, then
        top tree) in the shared index/leaves/path shape."""
        from ..succinct import MAX_STATE_PROOF_BATCH

        reqs = [(str(k["table"]), from_hex(k["key"])) for k in (keys or [])]
        if len(reqs) > MAX_STATE_PROOF_BATCH:
            raise JsonRpcError(
                -32602, f"state proof batch over {MAX_STATE_PROOF_BATCH} keys"
            )
        plane = getattr(self.node, "state_plane", None)
        if plane is None:
            raise JsonRpcError(
                -32602, "state plane disabled (FISCO_STATE_PROOF=0)"
            )
        results = plane.state_proof_batch(
            reqs, None if number is None else int(number)
        )
        proofs = []
        for res in results:
            if res is None:
                proofs.append(None)
                continue
            doc = {
                "blockNumber": res.number,
                "page": res.page,
                "pages": res.n_pages,
                "entry": to_hex(res.entry_bytes),
                "commitment": to_hex(res.commitment),
                "pageProof": _proof_json(
                    res.page_items, res.leaf_index, res.n_leaves
                ),
                "topProof": _proof_json(res.top_items, res.page, res.n_pages),
            }
            proofs.append(doc)
        return {"proofs": proofs}

    # -- block methods -------------------------------------------------------

    def get_block_number(self, group: str = "", node_name: str = "") -> int:
        return self.node.block_number()

    def get_block_by_number(
        self, group: str = "", node_name: str = "", number: int = 0,
        only_header: bool = False, only_tx_hash: bool = False,
    ) -> dict:
        blk = self.node.ledger.block_by_number(int(number), with_txs=not only_tx_hash)
        if blk is None:
            raise JsonRpcError(-32602, f"block {number} not found")
        if only_header:
            return _header_json(blk.header, self.suite)
        return _block_json(blk, self.suite, with_txs=not only_tx_hash)

    def get_block_by_hash(
        self, group: str = "", node_name: str = "", block_hash: str = "",
        only_header: bool = False, only_tx_hash: bool = False,
    ) -> dict:
        n = self.node.ledger.block_number_by_hash(from_hex(block_hash))
        if n is None:
            raise JsonRpcError(-32602, "block not found")
        return self.get_block_by_number(group, node_name, n, only_header, only_tx_hash)

    def get_block_hash_by_number(self, group: str = "", node_name: str = "", number: int = 0) -> str:
        h = self.node.ledger.block_hash_by_number(int(number))
        if h is None:
            raise JsonRpcError(-32602, f"block {number} not found")
        return to_hex(h)

    # -- contract/code -------------------------------------------------------

    def get_code(self, group: str = "", node_name: str = "", address: str = "") -> str:
        # contract accounts live in /apps/<addr> rows (executor/evm.py
        # contract_table; TransactionExecutor::getCode:1881 reads the same)
        from ..executor.evm import F_CODE, contract_table

        e = self.node.storage.get_row(contract_table(from_hex(address)), b"#account")
        code = e.fields.get(F_CODE, b"") if e is not None else b""
        return to_hex(code) if code else "0x"

    def get_abi(self, group: str = "", node_name: str = "", address: str = "") -> str:
        from ..executor.evm import F_ABI, contract_table

        e = self.node.storage.get_row(contract_table(from_hex(address)), b"#account")
        abi = e.fields.get(F_ABI, b"") if e is not None else b""
        return abi.decode(errors="replace")

    # -- status methods ------------------------------------------------------

    def get_sealer_list(self, group: str = "", node_name: str = "") -> list:
        return [
            {"nodeID": to_hex(n.node_id, prefix=False), "weight": n.weight}
            for n in self.node.ledger.consensus_nodes()
            if n.node_type == "consensus_sealer"
        ]

    def get_observer_list(self, group: str = "", node_name: str = "") -> list:
        return [
            to_hex(n.node_id, prefix=False)
            for n in self.node.ledger.consensus_nodes()
            if n.node_type == "consensus_observer"
        ]

    def get_pbft_view(self, group: str = "", node_name: str = "") -> int:
        return self.node.engine.view

    def get_pending_tx_size(self, group: str = "", node_name: str = "") -> int:
        return self.node.txpool.pending_count()

    def get_sync_status(self, group: str = "", node_name: str = "") -> dict:
        num = self.node.block_number()
        return {
            "blockNumber": num,
            "latestHash": to_hex(self.node.ledger.block_hash_by_number(num) or b""),
            "genesisHash": to_hex(self.node.ledger.block_hash_by_number(0) or b""),
            "nodeID": to_hex(self.node.node_id, prefix=False),
            "isSyncing": False,
            "knownHighestNumber": max(
                [num] + [st.number for st in self.node.block_sync.peer_statuses()]
            ),
        }

    def get_consensus_status(self, group: str = "", node_name: str = "") -> dict:
        cfg = self.node.pbft_config
        return {
            "nodeID": to_hex(self.node.node_id, prefix=False),
            "index": cfg.my_index,
            "view": self.node.engine.view,
            "committedNumber": self.node.engine.committed_number,
            "leaderIndex": cfg.leader_index(self.node.engine.committed_number + 1,
                                            self.node.engine.view),
            "committeeSize": cfg.committee_size,
            "quorum": cfg.quorum,
            "timeout": self.node.engine.timeout_state,
        }

    def get_system_config_by_key(self, group: str = "", node_name: str = "", key: str = "") -> dict:
        v = self.node.ledger.system_config(key.encode())
        if v is None:
            raise JsonRpcError(-32602, f"unknown system config {key}")
        return {"value": v[0], "blockNumber": v[1]}

    def get_total_transaction_count(self, group: str = "", node_name: str = "") -> dict:
        return {
            "blockNumber": self.node.block_number(),
            "transactionCount": self.node.ledger.total_transaction_count(),
            "failedTransactionCount": self.node.ledger.total_failed_transaction_count(),
        }

    # -- group/peer methods (single-group node; gateway fills peers) ---------

    def get_peers(self, group: str = "", node_name: str = "") -> dict:
        gw = self.node.front._gateway
        gw_peers = gw.peers() if gw is not None and hasattr(gw, "peers") else []
        peers = gw_peers or self.node.block_sync.peer_ids()
        return {"peers": [to_hex(p, prefix=False) for p in peers]}

    def get_group_peers(self, group: str = "", node_name: str = "") -> list:
        return [to_hex(p, prefix=False) for p in self.node.block_sync.peer_ids()]

    def get_group_list(self) -> dict:
        return {"groupList": [self.node.config.group_id]}

    def get_group_info(self, group: str = "") -> dict:
        return {
            "chainID": self.node.config.chain_id,
            "groupID": self.node.config.group_id,
            "genesisConfig": {
                "consensusType": "pbft",
                "txCountLimit": self.node.ledger.ledger_config().tx_count_limit,
                "leaderPeriod": self.node.ledger.ledger_config().leader_period,
            },
            "nodeList": [self.get_group_node_info(group)],
        }

    def get_group_info_list(self) -> list:
        return [self.get_group_info()]

    def get_group_node_info(self, group: str = "", node_name: str = "") -> dict:
        return {
            "name": node_name or "node0",
            "nodeID": to_hex(self.node.node_id, prefix=False),
            "type": 0 if self.node.is_sealer() else 1,
            "blockNumber": self.node.block_number(),
            "timestamp": int(time.time() * 1000),
        }
