"""TPS-bench load duplication — flood a chain from one seed transaction.

Reference: bcos-rpc/jsonrpc/DupTestTxJsonRpcImpl_2_0.h (a JsonRpcImpl
subclass whose sendTransaction ALSO multiplies the tx into the pool) +
DuplicateTransactionFactory.cpp:11-37 (each copy gets a fresh
``nonce + utcTimeUs`` and is re-signed with a bench keypair).  This is how
the reference measures its published 4-node TPS: one client connection,
one signed tx, N pool entries.

The duplicated copies are REAL transactions — fresh nonce, full re-sign,
normal admission — so the flood exercises the same batch-verification
plane as N distinct clients would; only client-side socket I/O is skipped.
"""

from __future__ import annotations

import time

from ..crypto.suite import KeyPair
from ..protocol.transaction import Transaction, TransactionFactory
from ..utils.error import ErrorCode
from ..utils.log import get_logger
from ..utils.bytesutil import from_hex
from .jsonrpc import JsonRpcImpl

_log = get_logger("dup-test-rpc")


def multi_build(
    suite, seed_tx: Transaction, keypair: KeyPair, num: int
) -> list[Transaction]:
    """`num` fresh copies of seed_tx: same call, new nonce, re-signed
    (DuplicateTransactionFactory::multiBuild)."""
    fac = TransactionFactory(suite)
    base = int(time.time() * 1_000_000)
    return [
        fac.create_signed(
            keypair,
            chain_id=seed_tx.chain_id,
            group_id=seed_tx.group_id,
            block_limit=seed_tx.block_limit,
            nonce=f"{seed_tx.nonce}-dup-{base + i}",
            to=seed_tx.to,
            input=seed_tx.input,
            abi=seed_tx.abi,
        )
        for i in range(num)
    ]


class DupTestJsonRpcImpl(JsonRpcImpl):
    """JsonRpcImpl that multiplies every sendTransaction by ``dup_count``
    using ``bench_keypair`` — the TPS-bench RPC front
    (DupTestTxJsonRpcImpl_2_0). Deploys are not duplicated (same guard as
    the reference: `tx->to().empty()` is ignored)."""

    def __init__(self, node, bench_keypair: KeyPair, dup_count: int = 100):
        super().__init__(node)
        self.bench_keypair = bench_keypair
        self.dup_count = dup_count

    def send_transaction(
        self, group: str, node_name: str, data: str, require_proof: bool = False
    ) -> dict:
        out = super().send_transaction(group, node_name, data, require_proof)
        seed = Transaction.decode(from_hex(data))
        if not seed.to:
            return out  # ignore deploy tx
        dups = multi_build(self.suite, seed, self.bench_keypair, self.dup_count)
        results = self.node.txpool.submit_batch(dups)
        accepted = sum(1 for r in results if r.status == ErrorCode.SUCCESS)
        self.node.tx_sync.maintain()
        _log.info(
            "duplicated sendTransaction x%d (%d accepted)", self.dup_count, accepted
        )
        out["duplicated"] = accepted
        return out
