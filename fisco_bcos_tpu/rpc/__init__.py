"""JSON-RPC 2.0 API layer."""

from .dup_test import DupTestJsonRpcImpl  # noqa: F401
from .jsonrpc import JsonRpcImpl  # noqa: F401
from .http_server import RpcHttpServer  # noqa: F401
