"""Event-log subscription engine.

Reference: bcos-rpc/event/{EventSub.cpp, EventSubMatcher.cpp,
EventSubTask.cpp} — clients register a filter (block range, addresses,
topics), the node replays the historical range from the ledger and then
pushes matched logs from every newly committed block.

Filter semantics match the reference/Ethereum style: `addresses` OR-match
the log address; `topics[i]` is a list OR-matched against the log's i-th
topic (empty list = wildcard).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Callable

from ..utils.bytesutil import from_hex, to_hex
from ..utils.log import get_logger

_log = get_logger("event-sub")


@dataclass
class EventFilter:
    from_block: int = -1  # -1: start at current head (live only)
    to_block: int = -1  # -1: unbounded
    addresses: list[bytes] = field(default_factory=list)
    topics: list[list[bytes]] = field(default_factory=list)

    @classmethod
    def from_json(cls, obj: dict) -> "EventFilter":
        return cls(
            from_block=int(obj.get("fromBlock", -1)),
            to_block=int(obj.get("toBlock", -1)),
            addresses=[from_hex(a) for a in obj.get("addresses", [])],
            topics=[
                [from_hex(t) for t in (ts if isinstance(ts, list) else [ts])]
                for ts in obj.get("topics", [])
            ],
        )

    def matches(self, address: bytes, topics: list[bytes]) -> bool:
        if self.addresses and address not in self.addresses:
            return False
        for i, wanted in enumerate(self.topics):
            if not wanted:
                continue  # wildcard position
            if i >= len(topics) or topics[i] not in wanted:
                return False
        return True


def _log_json(number: int, tx_hash: bytes, log_index: int, entry) -> dict:
    return {
        "blockNumber": number,
        "transactionHash": to_hex(tx_hash),
        "logIndex": log_index,
        "address": to_hex(entry.address),
        "topics": [to_hex(t) for t in entry.topics],
        "data": to_hex(entry.data),
    }


@dataclass
class _Subscription:
    sub_id: str
    filt: EventFilter
    push: Callable[[dict], None]  # delivery hook (ws session send)
    # while the historical replay runs, live commits buffer here instead of
    # pushing (ordering); None once the subscription is live
    buffer: list | None = None


class EventSubEngine:
    """Register with `scheduler.on_committed` for live pushes; `subscribe`
    replays any historical range from the ledger first (EventSubTask
    semantics: history, then live)."""

    def __init__(self, ledger, suite):
        self.ledger = ledger
        self.suite = suite
        self._subs: dict[str, _Subscription] = {}
        self._ids = itertools.count(1)
        self._lock = threading.RLock()

    # -- wiring ---------------------------------------------------------------

    def on_block_committed(self, number: int, block) -> None:
        with self._lock:
            subs = list(self._subs.values())
        if not subs:
            return
        events = self._collect(number, block)
        if not events:
            return
        for sub in subs:
            if sub.filt.to_block != -1 and number > sub.filt.to_block:
                self.unsubscribe(sub.sub_id)
                continue
            matched = [
                e
                for e, (addr, topics) in events
                if sub.filt.matches(addr, topics)
            ]
            if not matched:
                continue
            with self._lock:
                if sub.buffer is not None:
                    # replay still running on the subscriber thread — hold
                    # the push so history stays ahead of live events
                    sub.buffer.append((number, matched))
                    continue
            self._push(sub, number, matched)

    def _collect(self, number: int, block):
        """[(log_json, (address, topics))] for one committed block."""
        out = []
        txs = block.transactions
        receipts = block.receipts
        for i, rc in enumerate(receipts):
            tx_hash = txs[i].hash(self.suite) if i < len(txs) else b""
            for j, entry in enumerate(rc.log_entries):
                out.append(
                    (_log_json(number, tx_hash, j, entry), (entry.address, entry.topics))
                )
        return out

    def _push(self, sub: _Subscription, number: int, logs: list[dict]) -> None:
        try:
            sub.push(
                {
                    "method": "eventLogPush",
                    "params": {"id": sub.sub_id, "blockNumber": number, "logs": logs},
                }
            )
        except Exception:
            _log.info("push failed; dropping subscription %s", sub.sub_id)
            self.unsubscribe(sub.sub_id)

    # -- api ------------------------------------------------------------------

    def subscribe(self, filt: EventFilter, push: Callable[[dict], None]) -> str:
        sub_id = f"sub-{next(self._ids)}"
        sub = _Subscription(sub_id, filt, push, buffer=[])
        # register BEFORE reading head/replaying: a block committed between
        # the head read and registration would otherwise be delivered by
        # neither the replay nor the live path (silent event gap). Live
        # pushes buffer until the replay finishes, then drain deduped.
        with self._lock:
            self._subs[sub_id] = sub
        head = self.ledger.block_number()
        end = -1
        # historical replay (EventSubTask): blocks [from, min(head, to)]
        if 0 <= filt.from_block <= head:
            end = head if filt.to_block == -1 else min(head, filt.to_block)
            for n in range(filt.from_block, end + 1):
                block = self.ledger.block_by_number(
                    n, with_txs=True, with_receipts=True
                )
                if block is None:
                    continue
                events = self._collect(n, block)
                matched = [
                    e for e, (addr, topics) in events if filt.matches(addr, topics)
                ]
                if matched:
                    self._push(sub, n, matched)
        # drain-until-empty, clearing the buffer flag only once it IS empty
        # under the lock: clearing first and pushing outside would let a
        # concurrent commit (on the notify worker) deliver block N+1 ahead
        # of still-buffered block N
        while True:
            with self._lock:
                buffered = sub.buffer or []
                if not buffered:
                    sub.buffer = None
                    break
                sub.buffer = []
            for number, matched in buffered:
                if number <= end:
                    continue  # the replay already delivered this block
                self._push(sub, number, matched)
        return sub_id

    def unsubscribe(self, sub_id: str) -> bool:
        with self._lock:
            return self._subs.pop(sub_id, None) is not None

    def drop_by_push_owner(self, owner) -> None:
        """Remove every subscription whose push hook belongs to `owner`
        (a closed ws session)."""
        with self._lock:
            dead = [
                s.sub_id
                for s in self._subs.values()
                if getattr(s.push, "__self__", None) is owner
            ]
            for sid in dead:
                self._subs.pop(sid, None)
