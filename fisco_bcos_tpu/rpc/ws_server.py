"""WebSocket service — RFC 6455 server carrying JSON-RPC plus push channels.

Reference: bcos-boostssl/websocket/{WsService, WsSession, WsMessage} and the
bcos-rpc ws endpoint: the same JSON-RPC 2.0 method table as HTTP, plus the
push-capable channels that HTTP cannot carry — event-log subscription
(bcos-rpc/event/EventSub*.cpp), AMOP (amop/AMOPClient.cpp), and block-number
notify.  Implemented on stdlib sockets: handshake = HTTP Upgrade with the
Sec-WebSocket-Accept digest; frames = client-masked, server-unmasked;
ping/pong + close handled in-session.

Service-level methods (consumed by sdk.WsClient):
    subscribeEvent(filterJson) -> subId         eventLogPush notifications
    unsubscribeEvent(subId)
    subscribeBlockNumber() -> ok                blockNumberPush notifications
    amopSubscribe(topic...)                     amopPush notifications
    amopPublish(topic, dataHex)
    amopBroadcast(topic, dataHex)
Everything else dispatches to the JsonRpcImpl method table.
"""

from __future__ import annotations

import base64
import hashlib
import json
import socket
import struct
import threading

from ..utils.log import get_logger
from .event_sub import EventFilter

_log = get_logger("ws")

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_TEXT = 0x1
OP_BIN = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA


class WsSession:
    def __init__(self, sock: socket.socket, addr, service: "WsService"):
        self.sock = sock
        self.addr = addr
        self.service = service
        self.wlock = threading.Lock()
        self.open = True
        self.topics: set[str] = set()  # AMOP subscriptions
        # bound sends: a client that stops reading fills its TCP buffer and
        # sendall would otherwise block whichever thread is pushing (block
        # notify / event logs) forever; timeout -> OSError -> session drop
        try:
            self.sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_SNDTIMEO, struct.pack("ll", 20, 0)
            )
        except OSError:
            pass

    # -- frame io ------------------------------------------------------------

    def _recv_exact(self, n: int) -> bytes | None:
        buf = b""
        while len(buf) < n:
            try:
                chunk = self.sock.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf += chunk
        return buf

    def recv_frame(self) -> tuple[int, bytes] | None:
        head = self._recv_exact(2)
        if head is None:
            return None
        fin_op, mask_len = head
        opcode = fin_op & 0x0F
        masked = mask_len & 0x80
        length = mask_len & 0x7F
        if length == 126:
            ext = self._recv_exact(2)
            if ext is None:
                return None
            (length,) = struct.unpack(">H", ext)
        elif length == 127:
            ext = self._recv_exact(8)
            if ext is None:
                return None
            (length,) = struct.unpack(">Q", ext)
        if length > 64 * 1024 * 1024:
            return None
        mask = b"\x00" * 4
        if masked:
            mask = self._recv_exact(4)
            if mask is None:
                return None
        payload = self._recv_exact(length) if length else b""
        if payload is None:
            return None
        if masked:
            payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        return opcode, payload

    def send_frame(self, opcode: int, payload: bytes) -> bool:
        head = bytes([0x80 | opcode])
        n = len(payload)
        if n < 126:
            head += bytes([n])
        elif n < 1 << 16:
            head += bytes([126]) + struct.pack(">H", n)
        else:
            head += bytes([127]) + struct.pack(">Q", n)
        try:
            with self.wlock:
                # analysis: allow(lock-order, per-session write mutex — ws
                # frame atomicity on ONE socket, no other lock is ever nested)
                self.sock.sendall(head + payload)
            return True
        except OSError:
            self.open = False
            return False

    def send_json(self, obj: dict) -> bool:
        return self.send_frame(OP_TEXT, json.dumps(obj).encode())

    def close(self) -> None:
        self.open = False
        try:
            self.send_frame(OP_CLOSE, b"")
            self.sock.close()
        except OSError:
            pass


class WsService:
    def __init__(
        self,
        impl,
        event_engine=None,
        amop=None,
        host: str = "127.0.0.1",
        port: int = 0,
        ssl_context=None,
    ):
        self.impl = impl  # JsonRpcImpl (or None)
        self.events = event_engine  # EventSubEngine
        self.amop = amop  # AMOPService
        self._ssl = ssl_context
        self._sessions: set[WsSession] = set()
        self._block_subs: set[WsSession] = set()
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self.host, self.port = self._listener.getsockname()
        if amop is not None:
            amop.attach_ws(self)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        threading.Thread(target=self._accept_loop, name="ws-accept", daemon=True).start()
        _log.info("websocket listening on %s:%d", self.host, self.port)

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            sessions = list(self._sessions)
            self._sessions.clear()
        for s in sessions:
            s.close()

    # -- push channels --------------------------------------------------------

    def on_block_committed(self, number: int, block) -> None:
        """Block notify push (reference asyncNotifyBlockNumber → ws)."""
        if self.events is not None:
            self.events.on_block_committed(number, block)
        with self._lock:
            subs = list(self._block_subs)
        for s in subs:
            if not s.send_json(
                {"method": "blockNumberPush", "params": {"blockNumber": number}}
            ):
                self._drop(s)

    def local_amop_push(self, topic: str, data_hex: str, from_node: str) -> int:
        """Deliver an AMOP message to local subscribers; returns count."""
        with self._lock:
            targets = [s for s in self._sessions if topic in s.topics]
        delivered = 0
        for s in targets:
            if s.send_json(
                {
                    "method": "amopPush",
                    "params": {"topic": topic, "data": data_hex, "from": from_node},
                }
            ):
                delivered += 1
            else:
                self._drop(s)
        return delivered

    def local_topics(self) -> set[str]:
        with self._lock:
            out: set[str] = set()
            for s in self._sessions:
                out |= s.topics
            return out

    # -- internals ------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, addr = self._listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(sock, addr), name="ws-conn", daemon=True
            ).start()

    def _handshake(self, sock: socket.socket) -> bool:
        sock.settimeout(10)
        data = b""
        while b"\r\n\r\n" not in data:
            chunk = sock.recv(4096)
            if not chunk or len(data) > 64 * 1024:
                return False
            data += chunk
        headers = {}
        for line in data.split(b"\r\n")[1:]:
            if b":" in line:
                k, v = line.split(b":", 1)
                headers[k.strip().lower()] = v.strip()
        key = headers.get(b"sec-websocket-key")
        if key is None or b"websocket" not in headers.get(b"upgrade", b"").lower():
            sock.sendall(b"HTTP/1.1 400 Bad Request\r\n\r\n")
            return False
        accept = base64.b64encode(
            hashlib.sha1(key + _GUID.encode()).digest()
        ).decode()
        sock.sendall(
            (
                "HTTP/1.1 101 Switching Protocols\r\n"
                "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                f"Sec-WebSocket-Accept: {accept}\r\n\r\n"
            ).encode()
        )
        sock.settimeout(None)
        return True

    def _serve(self, sock: socket.socket, addr) -> None:
        if self._ssl is not None:
            try:
                sock = self._ssl.wrap_socket(sock, server_side=True)
            except (OSError, ValueError):
                return
        try:
            if not self._handshake(sock):
                sock.close()
                return
        except OSError:
            return
        session = WsSession(sock, addr, self)
        with self._lock:
            self._sessions.add(session)
        while not self._stop.is_set() and session.open:
            frame = session.recv_frame()
            if frame is None:
                break
            opcode, payload = frame
            if opcode == OP_CLOSE:
                break
            if opcode == OP_PING:
                session.send_frame(OP_PONG, payload)
                continue
            if opcode in (OP_TEXT, OP_BIN):
                self._dispatch(session, payload)
        self._drop(session)

    def _drop(self, session: WsSession) -> None:
        with self._lock:
            self._sessions.discard(session)
            self._block_subs.discard(session)
        if self.events is not None:
            self.events.drop_by_push_owner(session)
        if self.amop is not None and session.topics:
            self.amop.on_local_topics_changed()
        session.close()

    def _dispatch(self, session: WsSession, payload: bytes) -> None:
        try:
            req = json.loads(payload)
        except ValueError:
            session.send_json(
                {"jsonrpc": "2.0", "id": None,
                 "error": {"code": -32700, "message": "parse error"}}
            )
            return
        method = req.get("method", "")
        rid = req.get("id")
        params = req.get("params", [])
        handler = {
            "subscribeEvent": self._m_subscribe_event,
            "unsubscribeEvent": self._m_unsubscribe_event,
            "subscribeBlockNumber": self._m_subscribe_block,
            "amopSubscribe": self._m_amop_subscribe,
            "amopUnsubscribe": self._m_amop_unsubscribe,
            "amopPublish": self._m_amop_publish,
            "amopBroadcast": self._m_amop_broadcast,
        }.get(method)
        if handler is not None:
            try:
                result = handler(session, *params)
                session.send_json({"jsonrpc": "2.0", "id": rid, "result": result})
            except Exception as e:
                session.send_json(
                    {"jsonrpc": "2.0", "id": rid,
                     "error": {"code": -32602, "message": str(e)}}
                )
            return
        if self.impl is not None:
            from .jsonrpc import client_source

            # strike attribution: the ws peer's IP is the source the
            # txpool files invalid-signature strikes against
            with client_source(f"rpc:{session.addr[0]}"):
                session.send_json(self.impl.handle(req))
        else:
            session.send_json(
                {"jsonrpc": "2.0", "id": rid,
                 "error": {"code": -32601, "message": f"method not found: {method}"}}
            )

    # -- service methods -------------------------------------------------------

    def _m_subscribe_event(self, session: WsSession, filter_obj) -> str:
        if self.events is None:
            raise ValueError("event subscription unavailable")
        if isinstance(filter_obj, str):
            filter_obj = json.loads(filter_obj)
        return self.events.subscribe(
            EventFilter.from_json(filter_obj), session.send_json
        )

    def _m_unsubscribe_event(self, session: WsSession, sub_id: str) -> bool:
        if self.events is None:
            raise ValueError("event subscription unavailable")
        return self.events.unsubscribe(sub_id)

    def _m_subscribe_block(self, session: WsSession) -> bool:
        with self._lock:
            self._block_subs.add(session)
        return True

    def _m_amop_subscribe(self, session: WsSession, *topics: str) -> bool:
        session.topics.update(topics)
        if self.amop is not None:
            self.amop.on_local_topics_changed()
        return True

    def _m_amop_unsubscribe(self, session: WsSession, *topics: str) -> bool:
        session.topics.difference_update(topics)
        if self.amop is not None:
            self.amop.on_local_topics_changed()
        return True

    def _m_amop_publish(self, session: WsSession, topic: str, data_hex: str) -> int:
        if self.amop is None:
            return self.local_amop_push(topic, data_hex, "")
        return self.amop.publish(topic, data_hex)

    def _m_amop_broadcast(self, session: WsSession, topic: str, data_hex: str) -> int:
        if self.amop is None:
            return self.local_amop_push(topic, data_hex, "")
        return self.amop.broadcast(topic, data_hex)
