"""Named, deterministic in-process crash points — the process-death analog
of :mod:`.faults`.

A :class:`FaultPlan` injures the *wire* (drop/corrupt/kill a connection —
``kill`` severs the link, the process keeps running); a :class:`CrashPlan`
kills the *process* at a named seam: the Nth time execution reaches an
armed :func:`crashpoint`, :class:`InjectedCrash` is raised and the node is
dead from that instant — whatever was durably written stays written,
whatever was in memory is gone (the harness abandons the node objects and
reboots a fresh :class:`~fisco_bcos_tpu.node.Node` over the same storage).

The seams are planted across the pipelined commit path — exactly the
windows PR 14's overlap opened:

- ``engine.pre_commit_broadcast`` — after ``save_prepared`` made the
  prepared proposal durable, before the COMMIT vote broadcasts: a
  restarted node must re-offer the proposal in view change, never
  equivocate against its own durable vote.
- ``engine.post_head_advance`` — after the optimistic ``consensus_head()``
  advanced, while the 2PC may still be queued on the commit worker: the
  optimistic head dies with the process and must be rebuilt from the
  durable ledger at boot.
- ``scheduler.mid_2pc`` — on the commit path between ``prepare`` and
  ``commit``: a durable prepared-but-unresolved 2PC slot survives the
  crash and boot must re-drive or roll it back (Node's boot scan rolls
  back stale local slots; consensus/block-sync re-drives the block).
- ``sealer.mid_prebuild`` — after the prebuild sealed its txs out of the
  pool, before the proposal exists: the reboot's ``reload_persisted``
  must return them to the sealable set.

Determinism: a rule fires on the Nth *matching* hit of its named seam
(``after`` hits pass first), scoped to one node of a multi-node process by
substring match on the seam's ``scope`` tag (each Node tags its engine/
scheduler/sealer with its pubkey prefix). No RNG — crash points are
count-deterministic, not probabilistic.

Activation mirrors the fault plan: zero overhead when off (one module
global read per seam), armed explicitly (:func:`install_crash_plan`) or
from the environment::

    FISCO_CRASH_PLAN="scheduler.mid_2pc@a1b2c3d4,after=1"

Spec grammar: ``;``-separated clauses ``name[@scope][,after=N][,count=M]``
(scope = substring of the node tag, default ``*`` = any node; count
defaults to 1 — a process only dies once per life).

:class:`InjectedCrash` subclasses ``BaseException`` so no ``except
Exception`` guard on the commit/consensus path can absorb it — it kills
worker threads and halts the engine exactly like process death, and only
the drive/transport boundaries (tests, the engine's message entry) are
allowed to observe it.
"""

from __future__ import annotations

import os
import threading

from ..utils.log import get_logger
from ..utils.metrics import REGISTRY

_log = get_logger("crashpoints")


class InjectedCrash(BaseException):
    """A deliberately injected process death. BaseException on purpose:
    the failure-handling ``except Exception`` paths under test must not be
    able to survive it — a crashed process runs nothing."""


# the registry: every planted seam, by name (tests iterate this to build
# the kill/reboot matrix; arming an unknown name is a loud error)
CRASH_POINTS = (
    "engine.pre_commit_broadcast",
    "engine.post_head_advance",
    "scheduler.mid_2pc",
    "sealer.mid_prebuild",
)


class CrashRule:
    __slots__ = ("name", "scope", "after", "count", "seen", "fired")

    def __init__(self, name: str, scope: str = "*", after: int = 0, count: int = 1):
        if name not in CRASH_POINTS:
            raise ValueError(
                f"unknown crash point {name!r} (known: {', '.join(CRASH_POINTS)})"
            )
        self.name = name
        self.scope = scope or "*"
        self.after = int(after)
        self.count = int(count)
        self.seen = 0
        self.fired = 0

    def matches(self, name: str, scope: str) -> bool:
        if self.name != name:
            return False
        return self.scope == "*" or self.scope in scope

    def __repr__(self) -> str:
        return (
            f"CrashRule({self.name}@{self.scope} after={self.after} "
            f"count={self.count} fired={self.fired})"
        )


class CrashPlan:
    """A set of armed crash rules plus what actually fired.

    ``fired`` lists ``(name, scope)`` in firing order — the harness's
    crash witness (a kill that propagated through worker threads has no
    other observable)."""

    def __init__(self):
        self._rules: list[CrashRule] = []
        self._lock = threading.Lock()
        self.fired: list[tuple[str, str]] = []

    @property
    def crashed(self) -> bool:
        return bool(self.fired)

    def arm(self, name: str, scope: str = "*", after: int = 0, count: int = 1) -> "CrashPlan":
        self._rules.append(CrashRule(name, scope, after, count))
        return self

    @classmethod
    def from_spec(cls, spec: str) -> "CrashPlan":
        """Parse the ``FISCO_CRASH_PLAN`` grammar (module docstring)."""
        plan = cls()
        for clause in spec.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            head, _, tail = clause.partition(",")
            name, _, scope = head.partition("@")
            kw: dict = {}
            if tail:
                for pair in tail.split(","):
                    k, _, v = pair.partition("=")
                    k = k.strip()
                    if k in ("after", "count"):
                        kw[k] = int(v)
                    else:
                        raise ValueError(f"unknown crash key {k!r} in {clause!r}")
            plan.arm(name.strip(), scope.strip() or "*", **kw)
        return plan

    def hit(self, name: str, scope: str) -> None:
        """One execution reached the named seam: fire the first matching
        armed rule (raising :class:`InjectedCrash`) or pass through."""
        with self._lock:
            rule = None
            for r in self._rules:
                if not r.matches(name, scope):
                    continue
                r.seen += 1
                if r.seen <= r.after:
                    continue
                if r.fired >= r.count:
                    continue
                r.fired += 1
                self.fired.append((name, scope))
                rule = r
                break
        if rule is None:
            return
        REGISTRY.counter_add(
            f'fisco_crashpoints_fired_total{{point="{name}"}}',
            help="injected process deaths by crash point",
        )
        _log.error("crash point %s fired at scope %r — node dies here", name, scope)
        # black box (ISSUE 16): the firing is the death certificate — record
        # it and flush BEFORE raising, while this "process" still runs; the
        # dying node's last events must not depend on anyone catching the
        # crash. Lazy import: resilience must stay importable without the
        # observability layer mid-boot.
        try:
            from ..observability.flight import FLIGHT

            FLIGHT.record("crash", "fired", scope=scope, point=name)
            FLIGHT.flush(scope or "node", f"crash:{name}")
        except Exception as e:
            from ..utils.log import note_swallowed

            note_swallowed("crashpoints.flight", e)
        raise InjectedCrash(f"injected crash at {name} (scope {scope!r})")


# -- global activation (one pointer read per seam when off) -------------------

_PLAN: CrashPlan | None = None
_env_checked = False


def install_crash_plan(plan: CrashPlan | None) -> None:
    """Explicit arming (tests / harnesses). ``None`` clears."""
    global _PLAN
    _PLAN = plan
    if plan is not None:
        try:
            from ..observability.flight import FLIGHT

            for r in plan._rules:
                FLIGHT.record(
                    "crash", "armed", point=r.name, scope_filter=r.scope,
                    after=r.after, count=r.count,
                )
        except Exception as e:
            from ..utils.log import note_swallowed

            note_swallowed("crashpoints.arm_flight", e)


def clear_crash_plan() -> None:
    install_crash_plan(None)


def active_crash_plan() -> CrashPlan | None:
    return _PLAN


def crashpoint(name: str, scope: str = "") -> None:
    """The seam: zero-overhead no-op unless a plan is armed. ``scope``
    tags which node of a multi-node process is executing (Node sets
    ``crash_scope`` on its engine/scheduler/sealer)."""
    plan = _PLAN
    if plan is not None:
        plan.hit(name, scope)


def ensure_env_crash_plan() -> None:
    """Install the ``FISCO_CRASH_PLAN`` plan once, if the env asks for
    one (called at consensus/scheduler module import — a missing var
    costs one getenv per process lifetime)."""
    global _env_checked, _PLAN
    if _env_checked:
        return
    _env_checked = True
    spec = os.environ.get("FISCO_CRASH_PLAN")
    if spec:
        install_crash_plan(CrashPlan.from_spec(spec))
        _log.warning("crash plan active from FISCO_CRASH_PLAN: %s", spec)
