"""Retry, backoff and deadline semantics — the shared contract for every
remote call in the Air/Pro/Max splits.

Reference analogs: tars proxy reconnect-with-backoff (the service clients in
bcos-tars-protocol retry through the tars runtime), TarsRemoteExecutorManager's
bounded wait loops, and the per-call timeouts every servant declares. The
reproduction previously scattered ad-hoc ``except (ServiceRemoteError,
OSError)`` blocks and fixed sleeps across service/storage/sync; this module
is the single place those semantics live:

- :class:`RetryPolicy` — capped exponential backoff with deterministic
  jitter (seeded per policy, so fault-injected tests replay identically).
- :class:`Deadline` — an absolute time budget threaded through nested
  calls; ``DeadlineExceeded`` subclasses ``TimeoutError`` (hence
  ``OSError``), so existing transport-failure handling absorbs it.
- Idempotency classification per service-RPC method name: retrying a
  non-idempotent method after a connection loss could double-execute it
  (the request may have been applied before the reply was lost), so only
  classified-idempotent methods are ever auto-retried by the RPC client.
"""

from __future__ import annotations

import random
import time


class DeadlineExceeded(TimeoutError):
    """A per-call deadline ran out (TimeoutError -> OSError subclass: the
    transports' connection-loss handling applies unchanged)."""


class Deadline:
    """An absolute deadline carried through nested remote calls."""

    __slots__ = ("at",)

    def __init__(self, at: float):
        self.at = at

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(time.monotonic() + seconds)

    def remaining(self) -> float:
        return self.at - time.monotonic()

    def expired(self) -> bool:
        return self.remaining() <= 0

    def check(self, what: str = "call") -> None:
        if self.expired():
            raise DeadlineExceeded(f"{what}: deadline exceeded")

    def clamp(self, timeout: float) -> float:
        """A socket/sleep timeout bounded by what is left of the budget."""
        return max(0.001, min(timeout, self.remaining()))


class RetryPolicy:
    """Capped exponential backoff with deterministic jitter.

    ``delay(attempt)`` = min(max_delay, base * multiplier**attempt) plus a
    jitter drawn from the policy's own seeded RNG — two policies built with
    the same seed produce the same delay sequence, which keeps
    fault-injected tests reproducible while still de-synchronizing real
    fleets (every client constructs its policy with the default entropy
    seed).
    """

    __slots__ = (
        "max_attempts", "base_delay", "max_delay", "multiplier",
        "jitter", "retry_on", "_rng",
    )

    def __init__(
        self,
        max_attempts: int = 3,
        base_delay: float = 0.05,
        max_delay: float = 2.0,
        multiplier: float = 2.0,
        jitter: float = 0.25,
        retry_on: tuple = (ConnectionError, TimeoutError),
        seed: int | None = None,
    ):
        self.max_attempts = max(1, int(max_attempts))
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.retry_on = retry_on
        self._rng = random.Random(seed)

    def delay(self, attempt: int) -> float:
        d = min(self.max_delay, self.base_delay * (self.multiplier ** attempt))
        if self.jitter > 0:
            d *= 1.0 + self.jitter * self._rng.random()
        return d

    def run(
        self,
        fn,
        *args,
        retry_on: tuple | None = None,
        deadline: Deadline | None = None,
        on_retry=None,
        **kwargs,
    ):
        """Call ``fn(*args, **kwargs)``, retrying classified errors with
        backoff until attempts or the deadline run out. The LAST error is
        re-raised (not a wrapper: failover seams key on error types).

        Re-attempts (attempt >= 1) run inside a ``retry.attempt`` child
        span carrying the attempt number, so a fault-injected trace shows
        the retries instead of an unexplained gap; the first attempt stays
        span-free (the callee's own spans cover the happy path)."""
        from ..observability.tracer import TRACER

        classify = retry_on if retry_on is not None else self.retry_on
        last: BaseException | None = None
        name = getattr(fn, "__name__", "call")
        for attempt in range(self.max_attempts):
            if deadline is not None:
                deadline.check(name)
            try:
                if attempt == 0:
                    return fn(*args, **kwargs)
                with TRACER.span(
                    "retry.attempt", attempt=attempt, fn=name
                ) as sp:
                    try:
                        return fn(*args, **kwargs)
                    except classify as e:  # type: ignore[misc]
                        sp.set(error=type(e).__name__)
                        raise
            except classify as e:  # type: ignore[misc]
                last = e
                if attempt + 1 >= self.max_attempts:
                    break
                d = self.delay(attempt)
                if deadline is not None:
                    if deadline.remaining() <= d:
                        break  # sleeping would blow the budget: fail now
                    d = deadline.clamp(d)
                if on_retry is not None:
                    on_retry(attempt, e)
                time.sleep(d)
        assert last is not None
        raise last


# -- idempotency classification per service-RPC method -----------------------

# A method is idempotent when re-sending the same request after a lost reply
# cannot change durable state beyond the first application. The 2PC verbs
# are idempotent BY DESIGN (keyed on block number — re-preparing/committing/
# rolling back the same number is a no-op, which 2PC recovery already relies
# on). Execution verbs are NOT: execute_transactions mutates the in-flight
# block context cumulatively, and `handle` may carry a sendTransaction.
IDEMPOTENT_METHODS: set[str] = {
    # storage service
    "get_row", "set_row", "set_rows", "get_primary_keys",
    "prepare", "commit", "rollback", "pending_2pc",
    # executor service (read/2PC surface)
    "get_hash", "call", "get_code", "get_abi", "known_callee",
    "next_block_header", "get_storage", "ctx_floor",
    # registry / telemetry / health
    "register", "heartbeat", "metrics", "trace", "trace_tx", "trace_spans",
    "health", "pipeline", "profile", "device",
    # fleet observatory (ISSUE 16): pure reads — the facade's merged
    # cluster/round docs and the 4007 peer telemetry pull (a re-pulled
    # snapshot/ledger/probe only re-reads the peer's in-memory state)
    "fleet", "round", "rounds", "fleet_pull",
    # key center (pure transforms of the payload under the master key)
    "encDataKey", "decDataKey",
    # gateway read/connect surface (re-connecting to a live peer is a no-op)
    "peers", "connect_peer",
    # succinct state plane (ISSUE 18): pure reads off frozen per-height
    # snapshots — a re-served batch rebuilds at most a cached page tree
    "getStateProof", "state_proof", "state_proof_batch",
    # storage observatory (ISSUE 19): a pure snapshot of the in-memory
    # codec/copy ledger — re-pulling after a lost reply re-reads counters
    "storage",
}

NON_IDEMPOTENT_METHODS: set[str] = {
    "execute_transactions", "dag_execute_transactions",
    "dmc_execute", "dmc_cancel", "dmc_commit_ctx", "dmc_set_ownership",
    "align", "handle", "send", "broadcast", "register_front",
    # frame delivery to the node: replaying re-dispatches module handlers
    "on_receive",
    # quota grant: a retry after a lost reply double-spends the permits
    "acquire",
}


def is_idempotent(method: str) -> bool:
    """Unknown methods default to NOT idempotent — auto-retry must be
    opted into, never inferred."""
    return method in IDEMPOTENT_METHODS


def mark_idempotent(method: str, flag: bool = True) -> None:
    """Extend the classification (new servants register their methods)."""
    if flag:
        NON_IDEMPOTENT_METHODS.discard(method)
        IDEMPOTENT_METHODS.add(method)
    else:
        IDEMPOTENT_METHODS.discard(method)
        NON_IDEMPOTENT_METHODS.add(method)
