"""Deterministic fault injection for the service-RPC / gateway transports.

Reference role: the chaos harness the reference project exercises with
external tooling (killing tars servants, dropping TCP links between
microservices) — here it is a first-class, *seedable* layer so the test
suite can provoke executor loss, shard loss and network flaps on demand and
get the same failure sequence on every run.

A :class:`FaultPlan` is a list of rules. Each rule names an *action*
(``drop``/``delay``/``duplicate``/``truncate``/``corrupt``/``refuse``/
``kill``/``partition``), a
*site* (``connect``/``send``/``recv``/``*``) and a *target* substring
matched against the transport's scope string (service clients use
``"host:port"``, servers ``"svc:<name>"``, the gateway ``"gw:<port>"``), so
one plan can flap a single storage shard while everything else runs clean.

``partition`` is the one two-endpoint action: its target names two host
sets (``hostA+hostB|hostC``) and the TCP gateway consults
:meth:`FaultPlan.blocked` with BOTH endpoints of every dial/send/recv —
traffic *within* a side flows, traffic *across* the cut is refused until
the rule's ``ms`` heal delay elapses (or :meth:`FaultPlan.heal_partitions`
heals it on demand). Severed links re-establish through the gateway's
:class:`~fisco_bcos_tpu.resilience.retry.RetryPolicy` redial.

Determinism: probabilistic rules (``p < 1``) draw from one
``random.Random(seed)`` owned by the plan, and counters (``after``/
``count``) are per-rule — the same plan replayed over the same traffic
produces the same fault sequence. (Under multi-threaded traffic the
*interleaving* is the scheduler's; tests that need strict determinism keep
the faulted path single-threaded, which all the RPC client paths are.)

Activation: transports check :data:`_PLAN` (one global read per frame —
zero overhead when ``None``). It is set either explicitly
(:func:`install_fault_plan`, tests) or from the ``FISCO_FAULT_PLAN``
environment spec parsed once at transport import (:func:`ensure_env_plan`):

    FISCO_FAULT_PLAN="seed=7;drop@recv:42001,p=0.5,count=3;refuse@connect:executor"

Spec grammar: ``;``-separated clauses; ``seed=N`` may appear once; every
other clause is ``action@site:target[,key=val...]`` with keys ``p`` (float
probability), ``count`` (max firings), ``after`` (pass N matching events
first), ``ms`` (delay milliseconds; for ``partition`` the heal delay,
0/absent = manual heal), ``keep`` (truncate: bytes kept),
``bits`` (corrupt: bit flips per frame).

``corrupt`` flips ``bits`` seeded-random bits in the frame *body* (never
the length header, so the frame still parses as a frame and the garbage
reaches the CODEC) — the wire-level garbage a flaky NIC or a malicious
peer produces. Decode paths must reject it with typed errors
(:class:`~fisco_bcos_tpu.service.rpc.BadFrame`, dropped-peer logs) and
count it (``note_swallowed`` sites), never crash or silently absorb it.

Injected failures surface as :class:`InjectedFault`, an ``OSError``
subclass — every transport already treats ``OSError`` as connection loss,
so the fault layer needs no special-casing in the error paths it tests.
"""

from __future__ import annotations

import os
import random
import threading
import time

from ..utils.log import get_logger

_log = get_logger("faults")


class InjectedFault(OSError):
    """A deliberately injected transport failure (subclasses OSError so the
    existing connection-loss handling absorbs it unchanged)."""


_ACTIONS = (
    "drop", "delay", "duplicate", "truncate", "corrupt", "refuse", "kill",
    "partition",
)
_SITES = ("connect", "send", "recv", "*")


class FaultRule:
    """One match-and-act rule. See module docstring for the fields."""

    __slots__ = (
        "action", "site", "target", "p", "count", "after",
        "delay_ms", "keep", "bits", "fired", "seen", "sides", "heal_at",
    )

    def __init__(
        self,
        action: str,
        site: str = "*",
        target: str = "*",
        p: float = 1.0,
        count: int | None = None,
        after: int = 0,
        delay_ms: float | None = None,
        keep: int = 2,
        bits: int = 3,
    ):
        if action not in _ACTIONS:
            raise ValueError(f"unknown fault action {action!r}")
        if site not in _SITES:
            raise ValueError(f"unknown fault site {site!r}")
        self.action = action
        self.site = site
        self.target = target
        self.p = float(p)
        self.count = count  # None = unlimited firings
        self.after = int(after)  # pass this many matching events first
        # for partition rules `ms` is the heal delay (None/0 = manual heal);
        # for every other action it is the injected latency (default 10 ms)
        self.delay_ms = 10.0 if delay_ms is None else float(delay_ms)
        self.keep = int(keep)  # truncate: wire bytes that still go out
        self.bits = int(bits)  # corrupt: bit flips per frame body
        self.fired = 0
        self.seen = 0
        # partition: the two host sets of the cut, parsed from
        # ``target = "hostA+hostB|hostC"``, and the monotonic heal time
        # (armed at rule creation — the cut begins when the plan does)
        self.sides: tuple[list[str], list[str]] | None = None
        self.heal_at: float | None = None
        if action == "partition":
            a, _, b = target.partition("|")
            side_a = [s for s in a.split("+") if s]
            side_b = [s for s in b.split("+") if s]
            if not side_a or not side_b:
                raise ValueError(
                    "partition target must name two '|'-separated host "
                    f"sets ('h1+h2|h3'), got {target!r}"
                )
            self.sides = (side_a, side_b)
            if delay_ms is not None and delay_ms > 0:
                self.heal_at = time.monotonic() + delay_ms / 1e3

    def crosses(self, local: str, remote: str) -> bool:
        """Partition-rule test: does (local, remote) span the cut (either
        direction — the refuse is bidirectional)?"""
        if self.sides is None:
            return False
        a, b = self.sides
        in_a = lambda s: any(h in s for h in a)  # noqa: E731
        in_b = lambda s: any(h in s for h in b)  # noqa: E731
        return (in_a(local) and in_b(remote)) or (in_b(local) and in_a(remote))

    def matches(self, site: str, scope: str) -> bool:
        if self.site != "*" and self.site != site:
            return False
        return self.target == "*" or self.target in scope

    def __repr__(self) -> str:  # debuggability of CI failures
        return (
            f"FaultRule({self.action}@{self.site}:{self.target}"
            f" p={self.p} count={self.count} after={self.after})"
        )


class FaultPlan:
    """A seeded set of fault rules plus the firing state.

    Hook surface (called by the transports):

    - :meth:`on_connect` — may raise (refuse).
    - :meth:`on_send` — returns ``(chunks, kill)``: the wire chunks to
      actually send (empty = drop, two = duplicate, truncated prefix =
      torn write) and whether to kill the connection afterwards.
    - :meth:`on_recv` — returns the (possibly truncated) body, ``None``
      to drop it, or raises to kill the connection.
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._rng = random.Random(seed)
        self._rules: list[FaultRule] = []
        self._lock = threading.Lock()
        self.injected = 0  # total faults fired (assertable in tests)

    # -- building ------------------------------------------------------------

    def add(self, rule: FaultRule) -> "FaultPlan":
        self._rules.append(rule)
        return self

    def rule(self, action: str, site: str = "*", target: str = "*", **kw) -> "FaultPlan":
        return self.add(FaultRule(action, site, target, **kw))

    def drop(self, site: str = "*", target: str = "*", **kw):
        return self.rule("drop", site, target, **kw)

    def delay(self, site: str = "*", target: str = "*", **kw):
        return self.rule("delay", site, target, **kw)

    def duplicate(self, site: str = "*", target: str = "*", **kw):
        return self.rule("duplicate", site, target, **kw)

    def truncate(self, site: str = "*", target: str = "*", **kw):
        return self.rule("truncate", site, target, **kw)

    def corrupt(self, site: str = "*", target: str = "*", **kw):
        """Seeded bit-flips in the frame body (codec-level garbage)."""
        return self.rule("corrupt", site, target, **kw)

    def refuse_connect(self, target: str = "*", **kw):
        return self.rule("refuse", "connect", target, **kw)

    def partition(
        self,
        side_a: list[str] | tuple[str, ...],
        side_b: list[str] | tuple[str, ...],
        heal_ms: float = 0.0,
    ) -> "FaultPlan":
        """Bidirectional refuse between two host sets with a timed heal.

        Every dial, send and receive whose (local, remote) endpoints span
        the cut is refused/severed until ``heal_ms`` milliseconds have
        elapsed (0 = no auto-heal; :meth:`heal_partitions` heals on
        demand). The gateway consults :meth:`blocked` with BOTH endpoint
        scopes, so the cut isolates whole hosts — the grammar spelling is
        ``partition@*:hostA+hostB|hostC,ms=2000``."""
        target = "+".join(side_a) + "|" + "+".join(side_b)
        return self.add(FaultRule("partition", "*", target, delay_ms=heal_ms))

    def heal_partitions(self) -> None:
        """Heal every partition rule NOW (deterministic heal for tests
        that must not sleep out a wall-clock timer)."""
        with self._lock:
            for r in self._rules:
                if r.action == "partition":
                    r.heal_at = 0.0

    def kill_after(self, n: int, site: str = "*", target: str = "*", **kw):
        """Let n matching messages through, then kill the CONNECTION.

        ``kill`` severs the transport link (the peer sees a dead socket
        and reconnect/retry machinery engages) — the *process* on the
        other end keeps running with all of its in-memory state. To
        simulate the process itself dying mid-operation, use the crash
        points in :mod:`.crashpoints` (``FISCO_CRASH_PLAN``): those raise
        :class:`~.crashpoints.InjectedCrash` at a named seam so only the
        durably-written state survives into the rebooted node."""
        return self.rule("kill", site, target, after=n, **kw)

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        """Parse the ``FISCO_FAULT_PLAN`` environment grammar."""
        plan = cls()
        for clause in spec.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            if clause.startswith("seed="):
                plan.seed = int(clause[5:])
                plan._rng = random.Random(plan.seed)
                continue
            head, _, tail = clause.partition(",")
            action, _, rest = head.partition("@")
            site, _, target = rest.partition(":")
            kw: dict = {}
            if tail:
                for pair in tail.split(","):
                    k, _, v = pair.partition("=")
                    k = k.strip()
                    if k == "p":
                        kw["p"] = float(v)
                    elif k in ("count", "after", "keep", "bits"):
                        kw[k] = int(v)
                    elif k == "ms":
                        kw["delay_ms"] = float(v)
                    else:
                        raise ValueError(f"unknown fault key {k!r} in {clause!r}")
            plan.add(FaultRule(action.strip(), site.strip() or "*", target or "*", **kw))
        return plan

    # -- firing --------------------------------------------------------------

    def blocked(self, local: str, remote: str) -> bool:
        """Partition consult (the TCP gateway calls this at connect, send
        AND recv with both endpoint scopes): True while an unhealed
        partition rule cuts (local, remote). Unlike :meth:`_fire` this
        needs BOTH endpoints — a single-scope rule cannot express 'A may
        not talk to B while everyone else talks to both'."""
        now = time.monotonic()
        with self._lock:
            for r in self._rules:
                if r.action != "partition":
                    continue
                if r.heal_at is not None and now >= r.heal_at:
                    continue  # healed: traffic flows again
                if r.crosses(local, remote):
                    r.fired += 1
                    self.injected += 1
                    return True
        return False

    def _fire(self, site: str, scope: str) -> FaultRule | None:
        """The first rule that matches AND fires for this event."""
        with self._lock:
            for r in self._rules:
                if r.action == "partition":
                    continue  # two-endpoint rules fire via blocked()
                if not r.matches(site, scope):
                    continue
                r.seen += 1
                if r.seen <= r.after:
                    continue
                if r.count is not None and r.fired >= r.count:
                    continue
                if r.p < 1.0 and self._rng.random() >= r.p:
                    continue
                r.fired += 1
                self.injected += 1
                _log.info("fault %s fired at %s:%s", r, site, scope)
                return r
        return None

    def _corrupt_bytes(self, data: bytes, bits: int, skip: int = 0) -> bytes:
        """Flip ``bits`` seeded-random bits in ``data[skip:]`` — ``skip``
        protects the length header so the frame still parses as a frame and
        the garbage reaches the codec, which is the layer under test."""
        span = len(data) - skip
        if span <= 0 or bits <= 0:
            return data
        buf = bytearray(data)
        with self._lock:
            for _ in range(bits):
                i = skip + self._rng.randrange(span)
                buf[i] ^= 1 << self._rng.randrange(8)
        return bytes(buf)

    def on_connect(self, scope: str) -> None:
        r = self._fire("connect", scope)
        if r is not None and r.action in ("refuse", "kill", "drop"):
            raise InjectedFault(f"injected connect refusal to {scope}")
        if r is not None and r.action == "delay":
            time.sleep(r.delay_ms / 1e3)

    def on_send(self, scope: str, wire: bytes) -> tuple[list[bytes], bool]:
        r = self._fire("send", scope)
        if r is None:
            return [wire], False
        if r.action == "drop":
            return [], False
        if r.action == "delay":
            time.sleep(r.delay_ms / 1e3)
            return [wire], False
        if r.action == "duplicate":
            return [wire, wire], False
        if r.action == "truncate":
            # a torn write: part of the frame goes out, then the link dies —
            # what a crashed peer mid-sendall looks like from the other end
            return [wire[: r.keep]], True
        if r.action == "corrupt":
            # garbage-on-the-wire: the frame arrives intact-looking but its
            # body is bit-flipped — the connection stays up, the DECODER
            # must reject it (skip=4 spares the u32 length header)
            return [self._corrupt_bytes(wire, r.bits, skip=4)], False
        # kill / refuse at the send site: connection dies before the write
        return [], True

    def on_recv(self, scope: str, body: bytes) -> bytes | None:
        r = self._fire("recv", scope)
        if r is None:
            return body
        if r.action == "drop":
            return None
        if r.action == "delay":
            time.sleep(r.delay_ms / 1e3)
            return body
        if r.action == "truncate":
            return body[: r.keep]
        if r.action == "corrupt":
            return self._corrupt_bytes(body, r.bits)
        if r.action == "duplicate":
            return body  # duplication is a send-side concept; pass through
        raise InjectedFault(f"injected {r.action} on recv at {scope}")


# -- global activation (one pointer read on the transport hot paths) ---------

_PLAN: FaultPlan | None = None
_env_checked = False


def install_fault_plan(plan: FaultPlan | None) -> None:
    """Explicit injection (tests / tools). ``None`` clears."""
    global _PLAN
    _PLAN = plan


def clear_fault_plan() -> None:
    install_fault_plan(None)


def active_plan() -> FaultPlan | None:
    return _PLAN


def ensure_env_plan() -> None:
    """Install the ``FISCO_FAULT_PLAN`` plan once, if the env asks for one.
    Called at transport import; a missing/empty var costs one getenv per
    process lifetime and the hot path stays a single global read."""
    global _env_checked, _PLAN
    if _env_checked:
        return
    _env_checked = True
    spec = os.environ.get("FISCO_FAULT_PLAN")
    if spec:
        _PLAN = FaultPlan.from_spec(spec)
        _log.warning("fault plan active from FISCO_FAULT_PLAN: %s", spec)
