"""Circuit breaker + degraded-mode registry.

Reference analogs: the tars heartbeat/reconnect machinery keeps a servant's
liveness state and stops routing to a dead endpoint until it answers again;
TiKVStorage's switch handler flips the scheduler into a recovery term. Here
the same pattern is generic: a :class:`CircuitBreaker` trips after repeated
failures (stops hammering a dead path, half-opens a probe after a cooldown)
and every tripped breaker — plus any subsystem that self-reports — lands in
the process-wide :class:`HealthRegistry`, which `GET /health` and the
``fisco_component_health`` metrics gauge expose.

Degraded mode is a REPORTING state, not a stop: a degraded component keeps
serving through whatever fallback its caller wired (host-path crypto,
surviving executors, re-armed 2PC recovery). The registry exists so an
operator (or tool/check_resilience.py) can see the transition and confirm
the recovery edge.
"""

from __future__ import annotations

import json
import threading
import time


class HealthRegistry:
    """Process-wide component health: ``ok`` / ``degraded`` per component.

    Transitions push a ``fisco_component_health{component=...}`` gauge
    (1 = ok, 0 = degraded) and count into
    ``fisco_component_degraded_total`` so /metrics shows flap history even
    after recovery.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # name -> (status, reason, since_monotonic, critical)
        self._components: dict[str, tuple[str, str, float, bool]] = {}
        self.transitions = 0

    # -- reporting -----------------------------------------------------------

    def ok(self, component: str, reason: str = "") -> None:
        self._set(component, "ok", reason, True)

    def degrade(self, component: str, reason: str = "", critical: bool = True) -> None:
        """``critical=True`` (default): the node cannot serve its core duty
        (e.g. a storage shard is gone — 2PC cannot commit) and /health
        answers 503 so probes take it out of rotation. ``critical=False``:
        the node KEEPS serving through a fallback (survivor executors, the
        host crypto loop, the XLA leg after a Pallas latch) — /health stays
        200 with the degradation in the JSON body, because evicting a
        correctly-serving node over a permanent informational latch would
        turn one slow path into an outage."""
        self._set(component, "degraded", reason, critical)

    def _set(self, component: str, status: str, reason: str, critical: bool) -> None:
        changed = False
        with self._lock:
            prev = self._components.get(component)
            if prev is None or prev[0] != status:
                changed = True
                self.transitions += 1
            self._components[component] = (
                status, reason, time.monotonic(), critical
            )
        if changed:
            self._export(component, status, reason)

    def _export(self, component: str, status: str, reason: str) -> None:
        try:  # lazy: resilience must import without dragging metrics in
            from ..utils.metrics import REGISTRY

            REGISTRY.gauge_set(
                f'fisco_component_health{{component="{component}"}}',
                1.0 if status == "ok" else 0.0,
                help="component health (1 ok, 0 degraded)",
            )
            if status != "ok":
                REGISTRY.counter_add(
                    f'fisco_component_degraded_total{{component="{component}"}}',
                    1.0,
                    help="degraded-mode entries per component",
                )
        except Exception as e:
            from ..utils.log import note_swallowed

            note_swallowed("health.metrics_export", e)
        if status != "ok":
            from ..utils.log import get_logger

            get_logger("health").warning(
                "component %s DEGRADED: %s", component, reason or "unspecified"
            )

    # -- querying ------------------------------------------------------------

    def status(self, component: str) -> str:
        with self._lock:
            ent = self._components.get(component)
        return ent[0] if ent is not None else "unknown"

    def overall(self) -> str:
        """``critical`` (a critical component is degraded: not ready, 503) >
        ``degraded`` (serving through fallbacks, 200 + JSON detail) >
        ``ok``."""
        with self._lock:
            vals = list(self._components.values())
        if any(s != "ok" and c for s, _r, _t, c in vals):
            return "critical"
        if any(s != "ok" for s, _r, _t, _c in vals):
            return "degraded"
        return "ok"

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            comps = {
                name: {
                    "status": s,
                    "reason": r,
                    "for_seconds": round(now - t, 3),
                    "critical": c,
                }
                for name, (s, r, t, c) in sorted(self._components.items())
            }
        return {"status": self.overall(), "components": comps}

    def to_json(self) -> str:
        return json.dumps(self.snapshot())

    def reset(self) -> None:
        """Test isolation: forget all components."""
        with self._lock:
            self._components.clear()
            self.transitions = 0


# the process registry — subsystems report here, GET /health serves it
HEALTH = HealthRegistry()


class CircuitBreaker:
    """Closed -> open after ``failure_threshold`` consecutive failures;
    half-open probe after ``reset_timeout``; closes again on success.

    Wired to a :class:`HealthRegistry` component: tripping reports
    ``degraded``, closing reports ``ok``. Thread-safe; `allow()` grants the
    half-open probe to exactly one caller per cooldown window.
    """

    def __init__(
        self,
        name: str,
        failure_threshold: int = 3,
        reset_timeout: float = 30.0,
        registry: HealthRegistry | None = None,
        critical: bool = True,
    ):
        self.name = name
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_timeout = float(reset_timeout)
        self.registry = registry if registry is not None else HEALTH
        # critical=False: tripping reports a SERVING degradation (a fallback
        # carries the load) — /health stays 200 (see HealthRegistry.degrade)
        self.critical = critical
        self._lock = threading.Lock()
        self._failures = 0
        self._opened_at: float | None = None
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            if self._opened_at is None:
                return "closed"
            if time.monotonic() - self._opened_at >= self.reset_timeout:
                return "half-open"
            return "open"

    def allow(self) -> bool:
        """May the protected path be attempted right now?"""
        with self._lock:
            if self._opened_at is None:
                return True
            if time.monotonic() - self._opened_at < self.reset_timeout:
                return False
            if self._probing:
                return False  # one probe at a time
            self._probing = True
            return True

    def record_success(self) -> None:
        with self._lock:
            was_open = self._opened_at is not None
            self._failures = 0
            self._opened_at = None
            self._probing = False
        if was_open:
            self.registry.ok(self.name, "recovered")

    def release_probe(self) -> None:
        """Free the half-open probe slot WITHOUT recording a verdict — for
        callers whose protected attempt never reached an outcome (e.g. a
        data error that fails both paths). Without this, an exception
        escaping the probe would leave ``_probing`` latched and wedge the
        breaker in half-open (allow() false forever)."""
        with self._lock:
            self._probing = False

    def record_failure(self, reason: str = "") -> None:
        with self._lock:
            self._failures += 1
            self._probing = False
            tripping = (
                self._opened_at is None
                and self._failures >= self.failure_threshold
            )
            if tripping:
                self._opened_at = time.monotonic()
            elif self._opened_at is not None:
                # failed probe: restart the cooldown window
                self._opened_at = time.monotonic()
        if tripping:
            self.registry.degrade(
                self.name,
                reason or f"{self._failures} consecutive failures",
                critical=self.critical,
            )

    def call(self, fn, *args, fallback=None, classify=(Exception,), **kwargs):
        """Run ``fn`` under the breaker; on open (or on failure) route to
        ``fallback`` when provided, else re-raise."""
        if not self.allow():
            if fallback is not None:
                return fallback(*args, **kwargs)
            raise RuntimeError(f"circuit {self.name} open")
        try:
            out = fn(*args, **kwargs)
        except classify as e:  # type: ignore[misc]
            self.record_failure(f"{type(e).__name__}: {e}")
            if fallback is not None:
                return fallback(*args, **kwargs)
            raise
        except BaseException:
            # unclassified escape: no verdict, but the probe slot must not
            # stay latched
            self.release_probe()
            raise
        self.record_success()
        return out
