"""Resilience subsystem: fault injection, retry/deadline semantics, circuit
breakers and degraded-mode reporting (ISSUE 2 tentpole).

Three pieces:

- :mod:`.faults` — a deterministic, seedable fault-injection layer
  (``FaultPlan``: drop/delay/duplicate/truncate frames, refuse connects,
  kill a connection after N messages) hooked into the service-RPC socket
  layer and the gateway TCP transport. Enabled only via the
  ``FISCO_FAULT_PLAN`` env spec or explicit ``install_fault_plan`` — one
  global pointer read per frame when disabled.
- :mod:`.retry` — ``RetryPolicy`` (capped exponential backoff +
  deterministic jitter), ``Deadline`` (per-call budgets,
  ``DeadlineExceeded``) and the idempotency classification per service-RPC
  method that gates automatic retries.
- :mod:`.breaker` — ``CircuitBreaker`` (closed/open/half-open) and the
  process-wide ``HEALTH`` :class:`~.breaker.HealthRegistry` served at
  ``GET /health`` and exported as ``fisco_component_health`` gauges.
- :mod:`.crashpoints` — named deterministic in-process crash points
  (``CrashPlan`` / ``FISCO_CRASH_PLAN``): the process-death analog of the
  fault plan's connection ``kill``, planted across the pipelined commit
  path so kill-and-reboot recovery is testable on demand.

The reference analogs are tars heartbeat/reconnect loops, the
TarsRemoteExecutorManager reaper and TiKVStorage's switch handler — see
docs/resilience.md for the knob-by-knob mapping.
"""

from __future__ import annotations

from .breaker import HEALTH, CircuitBreaker, HealthRegistry  # noqa: F401
from .crashpoints import (  # noqa: F401
    CRASH_POINTS,
    CrashPlan,
    InjectedCrash,
    active_crash_plan,
    clear_crash_plan,
    crashpoint,
    install_crash_plan,
)
from .faults import (  # noqa: F401
    FaultPlan,
    FaultRule,
    InjectedFault,
    active_plan,
    clear_fault_plan,
    install_fault_plan,
)
from .retry import (  # noqa: F401
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    is_idempotent,
    mark_idempotent,
)
