"""Ledger — chain data schema and access.

Reference: bcos-ledger/src/libledger/Ledger.cpp (asyncPrewriteBlock,
asyncStoreTransactions, asyncGetBlockDataByNumber, getTxProof/getReceiptProof,
genesis build) over the system tables of
bcos-framework/ledger/LedgerTypeDef.h:59-73:

  s_consensus          key "key" -> consensus node list (type+weight+enable#)
  s_config             config key -> (value, enable-block-number)
  s_current_state      "current_number" / "total_transaction_count" / ...
  s_hash_2_number      block hash -> number
  s_number_2_hash      number -> block hash
  s_block_number_2_nonces  number -> nonce list (block-limit replay window)
  s_number_2_header    number -> encoded header
  s_number_2_txs       number -> tx hash list
  s_hash_2_tx          tx hash -> encoded tx
  s_hash_2_receipt     tx hash -> encoded receipt
  s_code_binary        code hash -> bytecode
  s_contract_abi       code hash -> abi json

Writes go into a caller-supplied StateStorage overlay (the block-commit 2PC
stages that overlay into the durable backend) — mirroring asyncPrewriteBlock's
participation in the scheduler's two-phase commit.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..codec.flat import FlatReader, FlatWriter
from ..crypto.suite import CryptoSuite
from ..ops.merkle import MerkleProofItem
from ..protocol import Block, BlockHeader, Transaction, TransactionReceipt
from ..protocol.transaction import hash_transactions_batch
from ..storage.entry import Entry
from ..storage.interfaces import StorageInterface
from ..utils.log import get_logger

# system table names (LedgerTypeDef.h:59-73)
SYS_CONSENSUS = "s_consensus"
SYS_CONFIG = "s_config"
SYS_CURRENT_STATE = "s_current_state"
SYS_HASH_2_NUMBER = "s_hash_2_number"
SYS_NUMBER_2_HASH = "s_number_2_hash"
SYS_NUMBER_2_NONCES = "s_block_number_2_nonces"
SYS_NUMBER_2_HEADER = "s_number_2_header"
SYS_NUMBER_2_TXS = "s_number_2_txs"
SYS_HASH_2_TX = "s_hash_2_tx"
SYS_HASH_2_RECEIPT = "s_hash_2_receipt"
SYS_CODE_BINARY = "s_code_binary"
SYS_CONTRACT_ABI = "s_contract_abi"

SYSTEM_TABLES = [
    SYS_CONSENSUS,
    SYS_CONFIG,
    SYS_CURRENT_STATE,
    SYS_HASH_2_NUMBER,
    SYS_NUMBER_2_HASH,
    SYS_NUMBER_2_NONCES,
    SYS_NUMBER_2_HEADER,
    SYS_NUMBER_2_TXS,
    SYS_HASH_2_TX,
    SYS_HASH_2_RECEIPT,
    SYS_CODE_BINARY,
    SYS_CONTRACT_ABI,
]

# s_current_state keys
KEY_CURRENT_NUMBER = b"current_number"
KEY_TOTAL_TX_COUNT = b"total_transaction_count"
KEY_TOTAL_FAILED_TX_COUNT = b"total_failed_transaction_count"

# s_config keys (SystemConfigPrecompiled-governed)
CONFIG_TX_COUNT_LIMIT = b"tx_count_limit"
CONFIG_LEADER_PERIOD = b"consensus_leader_period"
CONFIG_GAS_LIMIT = b"tx_gas_limit"

_log = get_logger("ledger")


@dataclass(frozen=True)
class ConsensusNode:
    node_id: bytes  # node public key (64 bytes)
    weight: int = 1
    node_type: str = "consensus_sealer"  # or "consensus_observer"
    enable_number: int = 0
    # registered quorum-certificate pubkey (consensus/qc.py): 32-byte
    # ed25519 or 48-byte BLS G1, derived from the member's consensus
    # secret (qc_pub_for). Empty = member not QC-capable — the engine then
    # keeps the legacy per-signature path for the whole committee.
    # Registration here is the proof-of-possession boundary for BLS
    # rogue-key safety.
    qc_pub: bytes = b""


@dataclass
class GenesisConfig:
    chain_id: str = "chain0"
    group_id: str = "group0"
    consensus_nodes: list[ConsensusNode] = field(default_factory=list)
    tx_count_limit: int = 1000
    leader_period: int = 1
    gas_limit: int = 3_000_000_000
    version: int = 1
    timestamp: int = 0
    # chain VM type (the reference genesis [executor] is_wasm flag): a wasm
    # chain runs liquid/WASM contracts, an EVM chain Solidity bytecode
    is_wasm: bool = False
    # WASM gas strategy — "dispatch" (per-instruction) or "inject"
    # (GasInjector-style per-basic-block). CHAIN-level because the two
    # differ on trap receipts (inject charges the whole entered block); a
    # per-node setting would fork receipt roots
    wasm_gas_mode: str = "dispatch"
    # account-governance governor addresses (hex) — the AuthCommittee
    # governor list analog consumed by AccountManagerPrecompiled
    governors: list[str] = field(default_factory=list)


@dataclass
class LedgerConfig:
    """What consensus needs from the ledger (bcos-framework LedgerConfig)."""

    block_number: int = 0
    block_hash: bytes = b"\x00" * 32
    consensus_nodes: list[ConsensusNode] = field(default_factory=list)
    tx_count_limit: int = 1000
    leader_period: int = 1
    gas_limit: int = 3_000_000_000


def _encode_nodes(nodes: list[ConsensusNode]) -> bytes:
    w = FlatWriter()
    w.seq(
        nodes,
        lambda w2, n: (
            w2.bytes_(n.node_id),
            w2.u64(n.weight),
            w2.str_(n.node_type),
            w2.i64(n.enable_number),
            w2.bytes_(n.qc_pub),
        ),
    )
    return w.out()


def _decode_nodes(buf: bytes) -> list[ConsensusNode]:
    # current format carries qc_pub per row; fall back to the pre-QC row
    # shape for tables written by an older build (durable sqlite chains)
    for with_qc in (True, False):
        try:
            r = FlatReader(buf)
            nodes = r.seq(
                lambda r2: ConsensusNode(
                    r2.bytes_(),
                    r2.u64(),
                    r2.str_(),
                    r2.i64(),
                    qc_pub=r2.bytes_() if with_qc else b"",
                )
            )
            r.done()
            return nodes
        except ValueError:
            if not with_qc:
                raise
    raise ValueError("undecodable consensus node table")


def _encode_hash_list(hashes: list[bytes]) -> bytes:
    return b"".join(hashes)


def _decode_hash_list(buf: bytes) -> list[bytes]:
    return [buf[i : i + 32] for i in range(0, len(buf), 32)]


class Ledger:
    def __init__(self, storage: StorageInterface, suite: CryptoSuite):
        self.storage = storage
        self.suite = suite
        # read-path proof server (proofs/plane.py), attached by Node boot.
        # None (or FISCO_PROOF_PLANE=0) = the direct per-request rebuild
        # bodies below — the cache-off fallback the bit-identity tests pin.
        self.proof_plane = None

    # -- genesis ------------------------------------------------------------

    def build_genesis(self, cfg: GenesisConfig) -> BlockHeader:
        """Idempotent genesis bootstrap (Ledger.cpp buildGenesisBlock)."""
        existing = self.storage.get_row(SYS_NUMBER_2_HEADER, b"0")
        if existing is not None:
            return BlockHeader.decode(existing.get())
        header = BlockHeader(
            version=cfg.version,
            number=0,
            timestamp=cfg.timestamp,
            extra_data=f"{cfg.chain_id}/{cfg.group_id}".encode(),
            sealer_list=[n.node_id for n in cfg.consensus_nodes],
            consensus_weights=[n.weight for n in cfg.consensus_nodes],
        )
        h = header.hash(self.suite)
        put = self.storage.set_row
        put(SYS_NUMBER_2_HEADER, b"0", Entry().set(header.encode()))
        put(SYS_NUMBER_2_HASH, b"0", Entry().set(h))
        put(SYS_HASH_2_NUMBER, h, Entry().set(b"0"))
        put(SYS_CURRENT_STATE, KEY_CURRENT_NUMBER, Entry().set(b"0"))
        put(SYS_CURRENT_STATE, KEY_TOTAL_TX_COUNT, Entry().set(b"0"))
        put(SYS_CURRENT_STATE, KEY_TOTAL_FAILED_TX_COUNT, Entry().set(b"0"))
        put(SYS_CONSENSUS, b"key", Entry().set(_encode_nodes(cfg.consensus_nodes)))
        for key, val in (
            (CONFIG_TX_COUNT_LIMIT, cfg.tx_count_limit),
            (CONFIG_LEADER_PERIOD, cfg.leader_period),
            (CONFIG_GAS_LIMIT, cfg.gas_limit),
        ):
            e = Entry().set(str(val).encode()).set("enable_number", b"0")
            put(SYS_CONFIG, key, e)
        if cfg.governors:
            e = Entry().set(",".join(cfg.governors).encode())
            e.set("enable_number", b"0")
            put(SYS_CONFIG, b"auth_governors", e)
        _log.info("genesis built: hash=%s nodes=%d", h.hex()[:16], len(cfg.consensus_nodes))
        return header

    # -- block write (participates in the commit 2PC via `out` overlay) -----

    def prewrite_block(self, block: Block, out: StorageInterface) -> None:
        """Stage all chain-data writes for `block` into the overlay `out`
        (Ledger.cpp asyncPrewriteBlock)."""
        header = block.header
        suite = self.suite
        num_key = str(header.number).encode()
        h = header.hash(suite)
        put = out.set_row
        put(SYS_NUMBER_2_HEADER, num_key, Entry().set(header.encode()))
        put(SYS_NUMBER_2_HASH, num_key, Entry().set(h))
        put(SYS_HASH_2_NUMBER, h, Entry().set(num_key))
        put(SYS_CURRENT_STATE, KEY_CURRENT_NUMBER, Entry().set(num_key))

        tx_hashes = (
            hash_transactions_batch(block.transactions, suite)
            if block.transactions
            else list(block.tx_metadata)
        )
        put(SYS_NUMBER_2_TXS, num_key, Entry().set(_encode_hash_list(tx_hashes)))
        for tx, th in zip(block.transactions, tx_hashes):
            put(SYS_HASH_2_TX, th, Entry().set(tx.encode()))
        failed = 0
        for rc, th in zip(block.receipts, tx_hashes):
            if rc.status != 0:
                failed += 1
            put(SYS_HASH_2_RECEIPT, th, Entry().set(rc.encode()))
        nonces = FlatWriter()
        nonces.seq(
            [t.nonce for t in block.transactions], lambda w2, n: w2.str_(n)
        )
        put(SYS_NUMBER_2_NONCES, num_key, Entry().set(nonces.out()))

        # totals read through the overlay first so pipelined prewrites see
        # earlier staged increments, then fall back to committed state
        def staged_total(key: bytes) -> int:
            e = out.get_row(SYS_CURRENT_STATE, key)
            if e is not None:
                return int(e.get().decode())
            return self._current_state(key)

        total = staged_total(KEY_TOTAL_TX_COUNT) + len(tx_hashes)
        put(SYS_CURRENT_STATE, KEY_TOTAL_TX_COUNT, Entry().set(str(total).encode()))
        if failed:
            tfail = staged_total(KEY_TOTAL_FAILED_TX_COUNT) + failed
            put(
                SYS_CURRENT_STATE,
                KEY_TOTAL_FAILED_TX_COUNT,
                Entry().set(str(tfail).encode()),
            )

    def store_code(self, code_hash: bytes, code: bytes, abi: str, out: StorageInterface) -> None:
        out.set_row(SYS_CODE_BINARY, code_hash, Entry().set(code))
        if abi:
            out.set_row(SYS_CONTRACT_ABI, code_hash, Entry().set(abi.encode()))

    # -- reads --------------------------------------------------------------

    def _current_state(self, key: bytes) -> int:
        e = self.storage.get_row(SYS_CURRENT_STATE, key)
        return int(e.get().decode()) if e is not None else 0

    def block_number(self) -> int:
        return self._current_state(KEY_CURRENT_NUMBER)

    def total_transaction_count(self) -> int:
        return self._current_state(KEY_TOTAL_TX_COUNT)

    def total_failed_transaction_count(self) -> int:
        return self._current_state(KEY_TOTAL_FAILED_TX_COUNT)

    def block_hash_by_number(self, number: int) -> bytes | None:
        e = self.storage.get_row(SYS_NUMBER_2_HASH, str(number).encode())
        return e.get() if e is not None else None

    def block_number_by_hash(self, h: bytes) -> int | None:
        e = self.storage.get_row(SYS_HASH_2_NUMBER, h)
        return int(e.get().decode()) if e is not None else None

    def header_by_number(self, number: int) -> BlockHeader | None:
        e = self.storage.get_row(SYS_NUMBER_2_HEADER, str(number).encode())
        return BlockHeader.decode(e.get()) if e is not None else None

    def tx_hashes_by_number(self, number: int) -> list[bytes]:
        e = self.storage.get_row(SYS_NUMBER_2_TXS, str(number).encode())
        return _decode_hash_list(e.get()) if e is not None else []

    def tx_by_hash(self, h: bytes) -> Transaction | None:
        e = self.storage.get_row(SYS_HASH_2_TX, h)
        return Transaction.decode(e.get()) if e is not None else None

    def receipt_by_hash(self, h: bytes) -> TransactionReceipt | None:
        e = self.storage.get_row(SYS_HASH_2_RECEIPT, h)
        return TransactionReceipt.decode(e.get()) if e is not None else None

    def block_by_number(
        self, number: int, with_txs: bool = True, with_receipts: bool = False
    ) -> Block | None:
        header = self.header_by_number(number)
        if header is None:
            return None
        blk = Block(header=header)
        hashes = self.tx_hashes_by_number(number)
        blk.tx_metadata = hashes
        if with_txs:
            txs = [self.tx_by_hash(h) for h in hashes]
            blk.transactions = [t for t in txs if t is not None]
        if with_receipts:
            rcs = [self.receipt_by_hash(h) for h in hashes]
            blk.receipts = [rc for rc in rcs if rc is not None]
        return blk

    def nonces_by_number(self, number: int) -> list[str]:
        e = self.storage.get_row(SYS_NUMBER_2_NONCES, str(number).encode())
        if e is None:
            return []
        r = FlatReader(e.get())
        out = r.seq(lambda r2: r2.str_())
        r.done()
        return out

    def system_config(self, key: bytes) -> tuple[str, int] | None:
        e = self.storage.get_row(SYS_CONFIG, key)
        if e is None:
            return None
        return e.get().decode(), int(e.get("enable_number").decode() or b"0")

    def consensus_nodes(self, storage=None) -> list[ConsensusNode]:
        """Committee membership. `storage` reads through an alternative
        layer — the pipelined commit passes the committing block's
        post-state overlay so the engine sees a committee change at
        optimistic-advance time, before the 2PC lands."""
        st = storage if storage is not None else self.storage
        e = st.get_row(SYS_CONSENSUS, b"key")
        return _decode_nodes(e.get()) if e is not None else []

    def ledger_config(self) -> LedgerConfig:
        num = self.block_number()
        cfg = LedgerConfig(
            block_number=num,
            block_hash=self.block_hash_by_number(num) or b"\x00" * 32,
            consensus_nodes=self.consensus_nodes(),
        )
        for attr, key in (
            ("tx_count_limit", CONFIG_TX_COUNT_LIMIT),
            ("leader_period", CONFIG_LEADER_PERIOD),
            ("gas_limit", CONFIG_GAS_LIMIT),
        ):
            v = self.system_config(key)
            if v is not None:
                setattr(cfg, attr, int(v[0]))
        return cfg

    # -- merkle proofs (MerkleProofUtility.cpp analog) -----------------------

    def _proof(self, number: int, target_hash: bytes) -> tuple[list[MerkleProofItem], int, int] | None:
        hashes = self.tx_hashes_by_number(number)
        try:
            idx = hashes.index(target_hash)  # one scan (was: `in` + .index)
        except ValueError:
            return None
        leaves = np.frombuffer(b"".join(hashes), dtype=np.uint8).reshape(-1, 32)
        # through the suite seam: plane-routed (or direct-but-spanned) so
        # the cache-off rebuild stays attributed in the device observatory
        tree = self.suite.merkle_tree(leaves)
        return tree.proof(idx), idx, len(hashes)

    def tx_proof(self, tx_hash: bytes):
        """-> (proof items, leaf index, leaf count) against header.txs_root.

        Served from the ProofPlane's frozen-tree cache when attached (Node
        boot wires it); the direct rebuild below is the cache-off fallback
        (FISCO_PROOF_PLANE=0 / bare Ledger constructions)."""
        if self.proof_plane is not None:
            return self.proof_plane.tx_proof(tx_hash)
        rc = self.receipt_by_hash(tx_hash)
        if rc is None:
            return None
        return self._proof(rc.block_number, tx_hash)

    def receipt_proof(self, tx_hash: bytes):
        """Proof that the *receipt* is in its block's receiptsRoot. Same
        ProofPlane delegation contract as :meth:`tx_proof` — the fallback
        re-reads every receipt in the block per request, which is exactly
        the O(N)-per-proof shape the plane exists to kill."""
        if self.proof_plane is not None:
            return self.proof_plane.receipt_proof(tx_hash)
        rc = self.receipt_by_hash(tx_hash)
        if rc is None:
            return None
        return self._receipt_proof_direct(tx_hash, rc.block_number)

    def _receipt_proof_direct(self, tx_hash: bytes, number: int):
        hashes = self.tx_hashes_by_number(number)
        try:
            idx = hashes.index(tx_hash)  # locate BEFORE paying N receipt reads
        except ValueError:
            return None
        rcs = [self.receipt_by_hash(h) for h in hashes]
        rc_hashes = [x.hash(self.suite) for x in rcs if x is not None]
        if len(rc_hashes) != len(hashes):
            return None
        leaves = np.frombuffer(b"".join(rc_hashes), dtype=np.uint8).reshape(-1, 32)
        tree = self.suite.merkle_tree(leaves)
        return tree.proof(idx), idx, len(rc_hashes)

    def proof_batch_direct(
        self, hashes: list[bytes], kind: str = "tx"
    ) -> list[tuple | None]:
        """The cache-off batch shape (aligned ``(number, items, idx, n)`` or
        None per hash): per-hash direct rebuilds, shared by every surface's
        FISCO_PROOF_PLANE=0 fallback (rpc, lightnode, bench) so the
        fallback semantics can't drift between copies."""
        out: list[tuple | None] = []
        for h in hashes:
            rc = self.receipt_by_hash(h)
            if rc is None:
                out.append(None)
                continue
            number = rc.block_number
            p = (
                self._proof(number, h)
                if kind == "tx"
                else self._receipt_proof_direct(h, number)
            )
            out.append(None if p is None else (number, *p))
        return out
