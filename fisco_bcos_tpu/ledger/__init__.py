"""Ledger: chain data schema, genesis, block access, merkle proofs."""

from .ledger import GenesisConfig, Ledger, LedgerConfig, ConsensusNode  # noqa: F401
