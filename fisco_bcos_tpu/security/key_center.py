"""KeyCenter — external key service for storage encryption.

Reference: bcos-security/bcos-security/KeyCenter.cpp (+KeyCenterHttpClient):
a node configured with ``storage_security.enable + key_center_url +
cipher_data_key`` never holds its data key in config — it asks the KeyCenter
service to decrypt the cipherDataKey at boot (JSON-RPC ``decDataKey``,
KeyCenter.cpp:195-198) and derives the working key with ``uniformDataKey``
(:236-249: keccak256 of the readable key for standard crypto, 4× sm3 for SM).

This analog keeps the exact key-handling semantics — encDataKey/decDataKey
methods, last-query cache (:173-176), uniformDataKey derivation — over the
framework's flat-codec service RPC instead of hand-rolled HTTP+JSON (the
transport every other Pro-mode service here rides; one wire protocol, one
server loop to audit).
"""

from __future__ import annotations

from ..codec.flat import FlatReader, FlatWriter
from ..crypto.encrypt import make_encryption
from ..crypto.ref.keccak import keccak256
from ..crypto.ref.sm3 import sm3
from ..service.rpc import ServiceClient, ServiceServer
from ..utils.log import get_logger

_log = get_logger("keycenter")


def uniform_data_key(readable_key: bytes, sm_crypto: bool = False) -> bytes:
    """KeyCenter.cpp:236 uniformDataKey: the working key is derived, never
    the readable key itself."""
    if sm_crypto:
        one = sm3(readable_key)
        return one * 4
    return keccak256(readable_key)


class KeyCenterService:
    """The key service process: holds the master key that wraps data keys.

    encDataKey: readable data key (hex) -> cipherDataKey (hex) — used once at
    deployment time to produce the config value. decDataKey: cipherDataKey
    (hex) -> readable data key (hex) — what booting nodes call.
    """

    def __init__(self, master_key: bytes, host: str = "127.0.0.1", port: int = 0):
        if not master_key:
            raise ValueError("KeyCenter needs a non-empty master key")
        self._cipher = make_encryption(master_key)
        self.server = ServiceServer("keycenter", host, port)
        self.server.register("encDataKey", self._enc)
        self.server.register("decDataKey", self._dec)
        self.host, self.port = self.server.host, self.server.port

    def start(self) -> None:
        self.server.start()

    def stop(self) -> None:
        self.server.stop()

    def _enc(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        readable_hex = r.str_()
        r.done()
        cipher = self._cipher.encrypt(bytes.fromhex(readable_hex))
        w = FlatWriter()
        w.str_(cipher.hex())
        return w.out()

    def _dec(self, payload: bytes) -> bytes:
        r = FlatReader(payload)
        cipher_hex = r.str_()
        r.done()
        readable = self._cipher.decrypt(bytes.fromhex(cipher_hex))
        w = FlatWriter()
        w.str_(readable.hex())
        return w.out()


class KeyCenter:
    """Client a node mounts at boot (KeyCenter.cpp getDataKey)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self._addr = (host, port, timeout)
        # cache the READABLE key, not a derived one: derivation depends on
        # sm_crypto, and a cache keyed only by cipherDataKey would hand an
        # SM mount the keccak-derived key (wrong working key, data loss)
        self._last_query: str | None = None
        self._last_readable: bytes = b""

    def _call(self, method: str, arg: str) -> str:
        host, port, timeout = self._addr
        client = ServiceClient(host, port, timeout)
        try:
            w = FlatWriter()
            w.str_(arg)
            out = client.call(method, w.out())
            r = FlatReader(out)
            res = r.str_()
            r.done()
            return res
        finally:
            client.close()  # one connection per query, like the reference

    def enc_data_key(self, readable_key: bytes) -> str:
        """Deployment-time helper: wrap a readable key into the config value."""
        return self._call("encDataKey", readable_key.hex())

    def get_data_key(self, cipher_data_key: str, sm_crypto: bool = False) -> bytes:
        if not cipher_data_key:
            raise ValueError("cipherDataKey is empty")
        if self._last_query == cipher_data_key:
            return uniform_data_key(self._last_readable, sm_crypto)
        try:
            readable_hex = self._call("decDataKey", cipher_data_key)
        except Exception as e:
            self._last_query, self._last_readable = None, b""  # clearCache (:219)
            raise RuntimeError(f"KeyCenter query failed: {e}") from e
        readable = bytes.fromhex(readable_hex)
        self._last_query, self._last_readable = cipher_data_key, readable
        return uniform_data_key(readable, sm_crypto)
