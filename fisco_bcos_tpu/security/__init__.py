"""Storage security — transparent at-rest encryption.

Reference: bcos-security/{DataEncryption.cpp, KeyCenter.cpp}.
"""

from .data_encryption import DataEncryption, EncryptedStorage

__all__ = ["DataEncryption", "EncryptedStorage"]
