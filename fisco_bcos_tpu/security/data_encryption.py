"""DataEncryption + the encrypted storage wrapper.

Reference: bcos-security/DataEncryption.h:35-55 (`encrypt`/`decrypt` over the
configured dataKey; applied to the node key file and every storage value —
RocksDBStorage's enableDBEncryption path), with KeyCenter.cpp's external key
service replaced by the local dataKey seam (`storage_security.data_key` in
config.ini).

``EncryptedStorage`` wraps any TransactionalStorage: entry payloads are
encrypted at rest (keys stay plaintext, exactly like the reference's rocksdb
values-only encryption), transparently for every reader — ledger, state,
txpool persistence, consensus state.
"""

from __future__ import annotations

from typing import Iterator

from ..crypto.encrypt import make_encryption
from ..storage.entry import Entry, EntryStatus
from ..storage.interfaces import (
    TransactionalStorage,
    TraversableStorage,
    TwoPCParams,
)


class DataEncryption:
    """dataKey-bound encrypt/decrypt (DataEncryption.cpp)."""

    def __init__(self, data_key: bytes, sm_crypto: bool = False):
        if not data_key:
            raise ValueError("storage_security requires a non-empty data_key")
        self._cipher = make_encryption(data_key, sm_crypto)

    def encrypt(self, data: bytes) -> bytes:
        return self._cipher.encrypt(data)

    def decrypt(self, data: bytes) -> bytes:
        return self._cipher.decrypt(data)


class _EncryptingView(TraversableStorage):
    """Traversal adapter handing the backend encrypted entries during 2PC."""

    def __init__(self, inner: TraversableStorage, enc: DataEncryption):
        self._inner = inner
        self._enc = enc

    def traverse(self) -> Iterator[tuple[str, bytes, Entry]]:
        for table, key, entry in self._inner.traverse():
            yield table, key, _seal(entry, self._enc)


def _seal(entry: Entry, enc: DataEncryption) -> Entry:
    if entry.deleted:
        return entry
    return Entry({"enc": enc.encrypt(entry.encode())}, status=entry.status)


def _open(entry: Entry | None, enc: DataEncryption) -> Entry | None:
    if entry is None or entry.deleted:
        return entry
    blob = entry.fields.get("enc")
    if blob is None:
        return entry  # pre-encryption row (mixed-mode migration)
    return Entry.decode(enc.decrypt(blob))


class EncryptedStorage(TransactionalStorage):
    def __init__(self, inner: TransactionalStorage, enc: DataEncryption):
        self.inner = inner
        self.enc = enc

    def get_row(self, table: str, key: bytes) -> Entry | None:
        return _open(self.inner.get_row(table, key), self.enc)

    def set_row(self, table: str, key: bytes, entry: Entry) -> None:
        self.inner.set_row(table, key, _seal(entry, self.enc))

    def set_rows(self, table: str, items) -> None:
        self.inner.set_rows(
            table, [(k, _seal(e, self.enc)) for k, e in items]
        )

    def get_primary_keys(self, table: str) -> list[bytes]:
        return self.inner.get_primary_keys(table)

    def traverse(self) -> Iterator[tuple[str, bytes, Entry]]:
        for table, key, entry in self.inner.traverse():
            opened = _open(entry, self.enc)
            if opened is not None:
                yield table, key, opened

    # -- 2PC: encrypt the staged write-set on its way down -------------------

    def prepare(self, params: TwoPCParams, writes: TraversableStorage) -> None:
        self.inner.prepare(params, _EncryptingView(writes, self.enc))

    def commit(self, params: TwoPCParams) -> None:
        self.inner.commit(params)

    def rollback(self, params: TwoPCParams) -> None:
        self.inner.rollback(params)

    def pending_numbers(self) -> list[int]:
        return self.inner.pending_numbers()


    def close(self) -> None:
        close = getattr(self.inner, "close", None)
        if close is not None:
            close()
