"""Succinct state plane — KeyPage state commitments, state proofs and
constant-work header sync.

Three connected layers (ISSUE 18):

- :mod:`.state_plane` — an incrementally-maintained merkle commitment over
  the whole KeyPage state (leaf = hash(table ‖ key ‖ value), keys bucketed
  into fixed pages, pages as subtrees), carried in the block header behind
  ``FISCO_STATE_PROOF`` and served as membership proofs through the
  frozen-tree cache machinery the ProofPlane pioneered.
- :mod:`fisco_bcos_tpu.ops.poseidon` — a jitted Poseidon sponge selectable
  as the commitment hasher (``FISCO_STATE_HASH=poseidon``), making the
  commitment SNARK-friendly.
- :mod:`.sync` — the light client's batched header verification: many
  header QCs fold into ONE multi-pairing aggregate program plus an
  accumulated commitment over the verified range.

Default OFF: with ``FISCO_STATE_PROOF`` unset (or ``0``) no plane is
created, headers carry no commitment and stay byte-identical to the
pre-succinct build (``BlockHeader.state_commitment`` encodes only when
present — the same optional-trailing-section pattern as ``qc``).
"""

from __future__ import annotations

import os

from .state_plane import (  # noqa: F401
    MAX_STATE_PROOF_BATCH,
    STATE_COMMIT_BUCKETS_MS,
    StatePlane,
    StateProofResult,
    reference_state_commitment,
    state_leaf,
    state_page_of,
    verify_state_proof,
)
from .sync import (  # noqa: F401
    SYNC_HEADERS_BUCKETS,
    HeaderRangeAccumulator,
    verify_header_batch,
)

# FISCO_STATE_HASH accepts the short spellings the issue names; they map
# onto the registered HashImpl names (crypto/suite.hash_impl_by_name)
_HASH_ALIASES = {
    "keccak": "keccak256",
    "keccak256": "keccak256",
    "sm3": "sm3",
    "poseidon": "poseidon",
}


def state_proof_enabled() -> bool:
    """``FISCO_STATE_PROOF=1`` arms the state plane (default off — headers
    stay byte-identical to the pre-succinct wire format)."""
    return os.environ.get("FISCO_STATE_PROOF", "0") != "0"


def state_hash_name() -> str:
    """The commitment hasher (``FISCO_STATE_HASH=keccak|sm3|poseidon``).
    Unknown names raise at plane construction, not at first hash — one node
    silently falling back to a different hasher is a consensus divergence."""
    raw = os.environ.get("FISCO_STATE_HASH", "keccak").strip().lower()
    try:
        return _HASH_ALIASES[raw]
    except KeyError:
        raise ValueError(
            f"FISCO_STATE_HASH={raw!r} (know: {sorted(set(_HASH_ALIASES))})"
        ) from None


def state_pages() -> int:
    """Fixed page-bucket count (``FISCO_STATE_PAGES``, default 64). Part of
    the commitment's shape — all nodes must agree on it."""
    try:
        n = int(os.environ.get("FISCO_STATE_PAGES", "64"))
    except ValueError:
        n = 64
    return max(1, n)
