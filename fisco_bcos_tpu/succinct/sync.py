"""Constant-work header sync — many header QCs, ONE pairing program.

The light client's old loop paid one aggregate pairing check per header.
Pairings don't get cheaper with committee size once QCs are aggregate, but
they DO share structure across headers: K checks

    e(-G1, sig_k) * e(apk_k, Hm_k) == 1        (k = 1..K)

fold into a single (K+1)-pair product via a Fiat-Shamir random linear
combination (``BLSCrypto.multi_pairing_verify``), which the device kernel
evaluates with one shared Miller-loop squaring chain and ONE final
exponentiation — the per-header marginal cost is a couple of lane
multiplies instead of a full pairing. :func:`verify_header_batch` does the
structural admission per header on the host
(``BlockValidator.qc_check_inputs``: sealer/weight lists, bitmap, quorum
weight, registered qc_pubs) and then buys the whole chunk with one
aggregate accept.

The accept is all-or-nothing — a single bad header rejects the chunk
without naming itself, so the light client falls back to per-header
:meth:`check_block` on rejection (and for non-aggregatable headers:
genesis, signature-list mode, ed25519 certs). Honest-path work is
constant-ish per chunk; the adversary can only force the fallback it
would have gotten anyway.

:class:`HeaderRangeAccumulator` is the client's running commitment over
everything it verified: a hash chain over (range, last hash) records,
so two light clients can compare one 32-byte digest to agree they
verified the same prefix the same way.
"""

from __future__ import annotations

from ..codec.flat import FlatWriter
from ..utils.metrics import REGISTRY

# headers folded into one aggregate pairing call (the succinct-sync payoff
# metric: honest sync should sit in the top buckets)
SYNC_HEADERS_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)


def verify_header_batch(headers, nodes, validator) -> bool | None:
    """One accept/reject for a whole chunk of QC'd headers.

    ``headers`` must already be parent-hash chained by the caller (chain
    linkage is the caller's cheap host-side check; this function buys the
    signatures). Returns ``True`` when the chunk is admitted by one
    multi-pairing check, ``False`` when the aggregate rejects (some header
    is bad — re-verify individually to name it), and ``None`` when the
    chunk is not aggregatable (any non-BLS / signature-list / genesis
    header in it — fall back to per-header ``check_block``). Structurally
    invalid headers (``qc_check_inputs`` raising) also return ``False``:
    no fallback can save those.
    """
    from ..consensus.qc import get_scheme

    if not headers:
        return True
    checks = []
    for header in headers:
        try:
            triple = validator.qc_check_inputs(header, nodes)
        except ValueError:
            return False
        if triple is None:
            return None
        checks.append(triple)
    scheme = get_scheme("bls")
    from ..device.plane import device_lane

    ok = None
    try:
        # header admission gates sync — same plane lane as check_block's
        with device_lane("consensus"):
            ok = bool(scheme._impl.multi_pairing_verify(checks))
        return ok
    finally:
        REGISTRY.observe(
            "fisco_succinct_sync_headers_per_call",
            float(len(checks)),
            buckets=SYNC_HEADERS_BUCKETS,
            help="headers folded into one multi-pairing aggregate "
            "verification during succinct header sync",
            accepted=str(bool(ok)).lower(),
        )


class HeaderRangeAccumulator:
    """Running commitment over verified header ranges.

    Each admitted chunk folds as ``acc = H(acc ‖ i64 first ‖ i64 last ‖
    last_header_hash)`` — the last header's hash transitively commits to
    the whole chained range, so the digest pins exactly which headers were
    verified and in what order without retaining any of them.
    """

    def __init__(self, suite):
        self.suite = suite
        self.digest = b"\x00" * 32
        self.headers = 0  # headers covered
        self.ranges = 0  # fold calls (aggregate chunks + fallback singles)

    def fold(self, first: int, last: int, last_hash: bytes) -> bytes:
        if last < first:
            raise ValueError("empty header range")
        w = FlatWriter()
        w.fixed(self.digest, 32)
        w.i64(first)
        w.i64(last)
        w.fixed(last_hash, 32)
        self.digest = self.suite.hash(w.out())
        self.headers += last - first + 1
        self.ranges += 1
        return self.digest

    def stats(self) -> dict:
        return {
            "digest": self.digest.hex(),
            "headers": self.headers,
            "ranges": self.ranges,
        }
