"""StatePlane — an incremental merkle commitment over the KeyPage state.

Commitment shape
----------------
Every live row ``(table, key, entry)`` hashes to one leaf::

    key_blob = flat(str table) ‖ flat(bytes key)     (the StateStorage
    leaf     = H(key_blob ‖ entry.encode())           XOR-root preimage)

Keys bucket into a FIXED number of pages (``FISCO_STATE_PAGES``, default
64) by ``H(key_blob)[:2] mod n_pages`` — the KeyPage analog: a page is the
unit of locality, and a block only dirties the pages its touched keys land
in. Each non-empty page is a wide merkle subtree over its leaves sorted by
``key_blob`` (an empty page contributes a 32-zero-byte placeholder), and
the header commitment is the root of a top tree over the page roots. Both
trees ride :class:`fisco_bcos_tpu.ops.merkle.MerkleTree` (count-bound
roots), hashed by the plane's OWN hasher (``FISCO_STATE_HASH`` —
``poseidon`` makes the whole commitment SNARK-friendly) through the
CryptoSuite seam, so batch hashing coalesces on the DevicePlane like every
other caller's.

Incremental maintenance
-----------------------
The plane never recomputes the full state: at execute time
(:meth:`preview`) the block's touched-key set updates ONLY the pages it
dirtied — copy-on-write page dicts chain block N+1's preview onto block
N's (speculative pre-execution included), untouched pages share structure
all the way back to the base. :meth:`promote` (commit time) turns the
preview into the new base and freezes it as a served height. The delta
cost is ``O(touched keys + touched pages · page size + n_pages)`` hashes,
not ``O(state)``.

Serving (the ProofPlane machinery)
----------------------------------
Per-height frozen snapshots, page trees built lazily under a per-
``(height, page)`` singleflight, every serve identity-checked against the
CURRENT ``s_number_2_hash`` row, eager eviction on rollback re-drive and
storage failover, builds dispatched under ``device_lane("proof")`` — the
lane below sync, exactly like tx/receipt proofs.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from concurrent.futures import Future
from dataclasses import dataclass

import numpy as np

from ..codec.flat import FlatWriter
from ..crypto.suite import CryptoSuite, hash_impl_by_name
from ..observability import TRACER
from ..ops.merkle import (  # host-safe names
    MerkleProofItem,
    MerkleTree,
    bind_root,
    bucket_leaves,
)
from ..proofs.plane import MAX_PROOF_BATCH
from ..utils.log import get_logger, note_swallowed
from ..utils.metrics import REGISTRY

_log = get_logger("succinct")

_ZERO32 = b"\x00" * 32

# chain-DATA tables (ledger.prewrite_block's rows) stay OUT of the
# commitment: they are staged at commit time outside the executor overlay,
# they are derivable from the blocks themselves, and block N's rows embed
# block N's header — whose preimage contains this very commitment (the
# circularity that forces every state-root scheme to scope itself to
# execution state). s_consensus/s_config stay IN: committee and config
# changes are executor writes through precompiled contracts.
EXCLUDED_TABLES = frozenset(
    {
        "s_number_2_header",
        "s_number_2_hash",
        "s_hash_2_number",
        "s_current_state",
        "s_number_2_txs",
        "s_block_number_2_nonces",
        "s_hash_2_tx",
        "s_hash_2_receipt",
    }
)

# state-proof batches share the tx/receipt proof cap — same reasoning: the
# gateway accepts frames far larger than any sane batch
MAX_STATE_PROOF_BATCH = MAX_PROOF_BATCH

# commit-time delta update: touched-leaf hashing + touched-page subtrees +
# the 64-leaf top tree (ms-class for block-sized write sets)
STATE_COMMIT_BUCKETS_MS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 1000.0,
)


def _key_blob(table: str, key: bytes) -> bytes:
    """The leaf's key prefix — EXACTLY StateStorage's XOR-root layout
    (state_storage.py hash_async), so the commitment and the state root
    agree on what a row's identity bytes are."""
    w = FlatWriter()
    w.str_(table)
    w.bytes_(key)
    return w.out()


def state_page_of(table: str, key: bytes, n_pages: int, hash_fn) -> int:
    """Fixed hash bucketing: ``H(key_blob)[:2] mod n_pages``."""
    return int.from_bytes(hash_fn(_key_blob(table, key))[:2], "big") % n_pages


def state_leaf(table: str, key: bytes, entry_bytes: bytes, hash_fn) -> bytes:
    """leaf = H(key_blob ‖ entry.encode())."""
    return hash_fn(_key_blob(table, key) + bytes(entry_bytes))


@dataclass(frozen=True)
class StateProofResult:
    """One served state proof: two chained wide-merkle proofs (leaf inside
    its page subtree, page root inside the top tree) plus the row bytes the
    client re-hashes into the leaf."""

    number: int
    page: int
    n_pages: int
    leaf_index: int
    n_leaves: int  # REAL leaf count of the page subtree
    page_items: list[MerkleProofItem]
    top_items: list[MerkleProofItem]
    entry_bytes: bytes
    commitment: bytes


def verify_state_proof(
    table: str,
    key: bytes,
    res: StateProofResult,
    commitment: bytes,
    hasher: str = "keccak256",
    n_pages: int = 64,
    width: int = 16,
) -> bool:
    """Client-side verification against a header's ``state_commitment``:
    re-derive the leaf from the served row bytes, walk the page subtree to
    its (count-bound) root, then walk the top tree to the commitment. The
    page index itself is re-derived from the key — a proof relocated to a
    different bucket fails even if both subtrees are internally sound."""
    hash_fn = hash_impl_by_name(hasher).hash
    if res.n_pages != n_pages or res.page != state_page_of(
        table, key, n_pages, hash_fn
    ):
        return False
    leaf = state_leaf(table, key, res.entry_bytes, hash_fn)
    # the page root is not transmitted: recompute it by ascending the page
    # proof from the re-derived leaf (count-bound), then prove THAT root's
    # membership in the top tree — tampering with either half breaks one walk
    page_root = _ascend(leaf, res.leaf_index, res.n_leaves, res.page_items,
                        hasher, width)
    if page_root is None:
        return False
    return MerkleTree.verify_proof(
        page_root, res.page, n_pages, res.top_items, commitment,
        width=width, hasher=hasher,
    )


def _ascend(
    leaf: bytes, idx: int, n: int, items: list[MerkleProofItem],
    hasher: str, width: int,
) -> bytes | None:
    """Recompute a tree's BOUND root from a leaf + proof (the first half of
    ``MerkleTree.verify_proof``, returning the root instead of comparing)."""
    hash_fn = hash_impl_by_name(hasher).hash
    if not 0 <= idx < n or len(leaf) != 32:
        return None
    cur, size = leaf, bucket_leaves(n)
    for item in items:
        if size <= 1:
            return None
        g0 = (idx // width) * width
        if item.index != idx - g0:
            return None
        if len(item.group) != min(width, size - g0):
            return None
        if any(len(h) != 32 for h in item.group):
            return None
        if item.group[item.index] != cur:
            return None
        cur = hash_fn(b"".join(item.group))
        idx //= width
        size = -(-size // width)
    if size != 1:
        return None
    return bind_root(cur, n, hasher)


# ---------------------------------------------------------------------------
# Independent reference walker (acceptance oracle — no ops.merkle, no
# device dispatch: plain loops over the same spec)
# ---------------------------------------------------------------------------


def _ref_hash_fn(hasher: str):
    if hasher == "keccak256":
        from ..crypto.ref.keccak import keccak256

        return keccak256
    if hasher == "sm3":
        from ..crypto.ref.sm3 import sm3

        return sm3
    if hasher == "sha256":
        from ..crypto.ref.sha2 import sha256

        return sha256
    if hasher == "poseidon":
        from ..crypto.ref.poseidon import poseidon_hash

        return poseidon_hash
    raise KeyError(hasher)


def _ref_bucket(n: int) -> int:
    if n <= 16:
        return n
    j = n.bit_length() - 5
    return -(-n // (1 << j)) << j


def _ref_tree_root(leaves: list[bytes], hasher: str, width: int = 16) -> bytes:
    """Independent wide-merkle fold: bucket-pad with zero leaves, hash
    width-groups per level, bind the real count."""
    h = _ref_hash_fn(hasher)
    n = len(leaves)
    cur = list(leaves) + [_ZERO32] * (_ref_bucket(n) - n)
    while len(cur) > 1:
        cur = [
            h(b"".join(cur[i : i + width])) for i in range(0, len(cur), width)
        ]
    return h(cur[0] + n.to_bytes(8, "big"))


def reference_state_commitment(
    rows, hasher: str = "keccak256", n_pages: int = 64, width: int = 16
) -> bytes:
    """Full-recompute oracle: fold EVERY live row of ``rows`` (an iterable
    of ``(table, key, Entry)``, deleted rows skipped) into the commitment —
    the value the plane's incremental path must match after any churn."""
    h = _ref_hash_fn(hasher)
    pages: list[list[tuple[bytes, bytes]]] = [[] for _ in range(n_pages)]
    for t, k, e in rows:
        if e.deleted or t in EXCLUDED_TABLES:
            continue
        kb = _key_blob(t, bytes(k))
        pages[int.from_bytes(h(kb)[:2], "big") % n_pages].append(
            (kb, h(kb + e.encode()))
        )
    roots = []
    for bucket in pages:
        if not bucket:
            roots.append(_ZERO32)
            continue
        bucket.sort(key=lambda kv: kv[0])
        roots.append(_ref_tree_root([lf for _, lf in bucket], hasher, width))
    return _ref_tree_root(roots, hasher, width)


# ---------------------------------------------------------------------------
# The plane
# ---------------------------------------------------------------------------


@dataclass
class _Snapshot:
    """One height's full state image. ``pages`` dicts are copy-on-write:
    NEVER mutated after publication — a block's preview copies only the
    pages it touches, so untouched pages share structure across heights."""

    number: int
    block_hash: bytes  # b"" until promoted
    pages: tuple  # tuple[dict[key_blob, (leaf, entry_bytes)], ...]
    page_roots: list[bytes]
    commitment: bytes


class StatePlane:
    """Per-node state-commitment maintainer + proof server (Node wires it
    into ``scheduler.state_plane`` / ``ledger.state_plane`` and the
    rollback/failover hooks, exactly like the ProofPlane)."""

    def __init__(
        self,
        ledger,
        suite: CryptoSuite,
        backend=None,
        hasher: str | None = None,
        n_pages: int | None = None,
        capacity: int | None = None,
    ):
        import os

        from . import state_hash_name, state_pages

        self.ledger = ledger
        self.backend = backend
        self.hasher = hasher if hasher is not None else state_hash_name()
        self.n_pages = n_pages if n_pages is not None else state_pages()
        # the plane's own suite: commitment hasher + the node's signer —
        # batch hashing and tree builds route through the same DevicePlane
        # seams as the consensus suite's, just under the `hash.<name>` /
        # `merkle_tree.<name>` op of the chosen hasher
        self.suite = CryptoSuite(hash_impl_by_name(self.hasher),
                                 suite.signature_impl)
        if capacity is None:
            try:
                capacity = int(os.environ.get("FISCO_STATE_PROOF_CAP", "64"))
            except ValueError:
                capacity = 64
        self.capacity = max(2, int(capacity))
        self._lock = threading.Lock()
        self._base: _Snapshot | None = None
        self._previews: dict[int, _Snapshot] = {}
        self._heights: OrderedDict[int, _Snapshot] = OrderedDict()
        # frozen page subtrees, built lazily per (height, page) under a
        # singleflight future (the ProofPlane discipline)
        self._trees: OrderedDict[tuple[int, int], MerkleTree] = OrderedDict()
        self._tree_cap = max(self.capacity * 4, 64)
        self._building: dict[tuple[int, int], Future] = {}
        # stats (under _lock; snapshot via stats())
        self.requests = 0
        self.hits = 0
        self.misses = 0
        self.previews = 0
        self.promotes = 0
        self.coalesced_builds = 0
        self.rebuilds = 0
        self.evictions: dict[str, int] = {}
        self._bootstrap()

    # -- base maintenance -----------------------------------------------------

    def _host_hash(self, data: bytes) -> bytes:
        return self.suite.hash(data)

    def _bootstrap(self) -> None:
        """(Re)build the base image from the durable backend — boot, and
        the failover/rollback recovery path. Backends without ``traverse``
        start from an empty image (commitments then cover post-boot deltas
        only; every in-tree transactional backend is traversable)."""
        number = self.ledger.block_number()
        rows = []
        if self.backend is not None and hasattr(self.backend, "traverse"):
            rows = [
                (t, k, e)
                for t, k, e in self.backend.traverse()
                if not e.deleted and t not in EXCLUDED_TABLES
            ]
        elif self.backend is not None:
            _log.warning(
                "state plane backend %s is not traversable: starting from an "
                "empty base image", type(self.backend).__name__,
            )
        pages: list[dict] = [{} for _ in range(self.n_pages)]
        if rows:
            blobs = [_key_blob(t, bytes(k)) for t, k, _ in rows]
            encs = [e.encode() for _, _, e in rows]
            digests = self.suite.hash_batch(
                blobs + [kb + enc for kb, enc in zip(blobs, encs)]
            )
            for i, kb in enumerate(blobs):
                pg = int.from_bytes(bytes(digests[i][:2]), "big") % self.n_pages
                pages[pg][kb] = (bytes(digests[len(blobs) + i]), encs[i])
        roots = [self._page_root(pg) for pg in pages]
        commitment = self._top_root(roots)
        snap = _Snapshot(
            number=number,
            block_hash=self.ledger.block_hash_by_number(number) or b"",
            pages=tuple(pages),
            page_roots=roots,
            commitment=commitment,
        )
        with self._lock:
            self._base = snap
            self.rebuilds += 1
            if snap.block_hash:
                self._insert_height_locked(snap)

    def _page_root(self, page: dict) -> bytes:
        if not page:
            return _ZERO32
        leaves = [lf for _, (lf, _) in sorted(page.items())]
        arr = np.frombuffer(b"".join(leaves), dtype=np.uint8).reshape(-1, 32)
        return self.suite.merkle_tree(arr).root

    def _top_root(self, page_roots: list[bytes]) -> bytes:
        arr = np.frombuffer(
            b"".join(page_roots), dtype=np.uint8
        ).reshape(-1, 32)
        return self.suite.merkle_tree(arr).root

    # -- execute-time preview / commit-time promote ---------------------------

    def preview(self, number: int, writes) -> bytes:
        """Apply a block's touched-key set to the chain of images and
        return the header commitment. Called at execute time (under the
        scheduler lock — single writer); chains onto block N-1's preview
        when N-1 is executed-but-uncommitted (speculative pre-execution)."""
        t0 = time.perf_counter()
        with self._lock:
            base = self._previews.get(number - 1) or self._base
        if base is None or base.number != number - 1:
            # the image chain is broken (failover cleared it / plane created
            # mid-run): rebuild the base from the durable backend, which is
            # exactly the state block `number` executes against
            self._bootstrap()
            with self._lock:
                base = self._base
            if base is None or base.number != number - 1:
                raise ValueError(
                    f"state plane base at {base.number if base else None}, "
                    f"cannot preview block {number}"
                )
        writes = [
            (t, bytes(k), e)
            for t, k, e in writes
            if t not in EXCLUDED_TABLES
        ]
        with TRACER.span("succinct.preview", block=number, writes=len(writes)):
            blobs = [_key_blob(t, k) for t, k, _ in writes]
            live = [
                (i, e.encode()) for i, (_, _, e) in enumerate(writes)
                if not e.deleted
            ]
            digests = (
                self.suite.hash_batch(
                    blobs + [blobs[i] + enc for i, enc in live]
                )
                if blobs
                else np.zeros((0, 32), np.uint8)
            )
            page_of = [
                int.from_bytes(bytes(digests[i][:2]), "big") % self.n_pages
                for i in range(len(blobs))
            ]
            leaf_at = {
                i: bytes(digests[len(blobs) + j])
                for j, (i, _) in enumerate(live)
            }
            enc_at = dict(live)
            pages = list(base.pages)
            roots = list(base.page_roots)
            touched: set[int] = set()
            for i, (kb, pg) in enumerate(zip(blobs, page_of)):
                if pg not in touched:
                    pages[pg] = dict(pages[pg])
                    touched.add(pg)
                if i in leaf_at:
                    pages[pg][kb] = (leaf_at[i], enc_at[i])
                else:
                    pages[pg].pop(kb, None)  # delete tombstone
            for pg in touched:
                roots[pg] = self._page_root(pages[pg])
            commitment = self._top_root(roots)
        snap = _Snapshot(
            number=number,
            block_hash=b"",
            pages=tuple(pages),
            page_roots=roots,
            commitment=commitment,
        )
        with self._lock:
            # a re-execution at `number` replaces anything speculated above
            for n in [n for n in self._previews if n >= number]:
                self._previews.pop(n)
            self._previews[number] = snap
            self.previews += 1
        REGISTRY.observe(
            "fisco_state_commit_update_ms",
            (time.perf_counter() - t0) * 1e3,
            buckets=STATE_COMMIT_BUCKETS_MS,
            help="incremental state-commitment delta update per executed "
            "block (touched-leaf hashing + touched-page subtrees + top tree)",
            pages=str(len(touched)),
        )
        return commitment

    def promote(self, number: int, block_hash: bytes) -> None:
        """Commit landed: the height's preview becomes the new base and a
        served height. Runs on the commit path (cheap: dict swaps) — must
        never throw into it."""
        try:
            with self._lock:
                snap = self._previews.pop(number, None)
                if snap is None:
                    base = self._base
                    if base is not None and base.number == number:
                        return  # already promoted (idempotent re-drive)
                    need_rebuild = True
                else:
                    need_rebuild = False
                    snap = _Snapshot(
                        number=snap.number,
                        block_hash=bytes(block_hash),
                        pages=snap.pages,
                        page_roots=snap.page_roots,
                        commitment=snap.commitment,
                    )
                    self._base = snap
                    for n in [n for n in self._previews if n <= number]:
                        self._previews.pop(n)
                    self._insert_height_locked(snap)
                    self.promotes += 1
            if need_rebuild:
                # commit of a block this plane never previewed (created
                # mid-run / image chain dropped): fall back to a full
                # rebuild from the now-durable backend
                _log.warning(
                    "state plane missed preview of block %d: rebuilding",
                    number,
                )
                self._bootstrap()
        except Exception as e:  # the commit path must survive plane faults
            note_swallowed("succinct.promote", e)

    def _insert_height_locked(self, snap: _Snapshot) -> None:
        if snap.number in self._heights:
            self._evict_height_locked(snap.number, "replace")
        self._heights[snap.number] = snap
        self._heights.move_to_end(snap.number)
        while len(self._heights) > self.capacity:
            old = next(iter(self._heights))
            self._evict_height_locked(old, "lru")

    def _evict_height_locked(self, number: int, reason: str) -> None:
        if self._heights.pop(number, None) is None:
            return
        for key in [k for k in self._trees if k[0] == number]:
            self._trees.pop(key)
        self.evictions[reason] = self.evictions.get(reason, 0) + 1
        REGISTRY.counter_add(
            f'fisco_state_plane_evictions_total{{reason="{reason}"}}',
            1.0,
            help="frozen state-height evictions by reason (lru/replace/"
            "identity/rollback/failover)",
        )

    # -- serving --------------------------------------------------------------

    def head_commitment(self) -> bytes | None:
        with self._lock:
            return self._base.commitment if self._base is not None else None

    def state_proof(
        self, table: str, key: bytes, number: int | None = None
    ) -> StateProofResult | None:
        return self.state_proof_batch([(table, bytes(key))], number)[0]

    def state_proof_batch(
        self, reqs: list[tuple[str, bytes]], number: int | None = None
    ) -> list[StateProofResult | None]:
        """N membership proofs against one height's commitment (default:
        the committed head). Unknown keys (and unserved heights) yield
        ``None`` at their position — absence proofs are not part of the
        fixed-page commitment's contract."""
        if len(reqs) > MAX_STATE_PROOF_BATCH:
            raise ValueError(
                f"state proof batch over {MAX_STATE_PROOF_BATCH} keys"
            )
        with self._lock:
            self.requests += len(reqs)
            if number is None:
                number = self._base.number if self._base is not None else -1
        out: list[StateProofResult | None] = [None] * len(reqs)
        snap = self._height(number)
        if snap is None:
            with self._lock:
                self.misses += len(reqs)
            return out
        with TRACER.span("succinct.serve", block=number, n=len(reqs)):
            served = 0
            for i, (table, key) in enumerate(reqs):
                kb = _key_blob(table, bytes(key))
                pg = (
                    int.from_bytes(self._host_hash(kb)[:2], "big")
                    % self.n_pages
                )
                row = snap.pages[pg].get(kb)
                if row is None:
                    continue
                tree = self._page_tree(snap, pg)
                keys_sorted = sorted(snap.pages[pg])
                leaf_idx = keys_sorted.index(kb)
                top = self._top_tree(snap)
                out[i] = StateProofResult(
                    number=number,
                    page=pg,
                    n_pages=self.n_pages,
                    leaf_index=leaf_idx,
                    n_leaves=tree.n,
                    page_items=tree.proof(leaf_idx),
                    top_items=top.proof(pg),
                    entry_bytes=row[1],
                    commitment=snap.commitment,
                )
                served += 1
        with self._lock:
            self.hits += served
            self.misses += len(reqs) - served
        REGISTRY.counter_add(
            "fisco_state_proofs_served_total",
            float(served),
            help="state membership proofs served by the StatePlane",
        )
        return out

    def _height(self, number: int) -> _Snapshot | None:
        """Identity-checked height lookup: a snapshot whose block hash no
        longer matches the CURRENT ``s_number_2_hash`` row never serves."""
        cur = self.ledger.block_hash_by_number(number)
        with self._lock:
            snap = self._heights.get(number)
            if snap is None:
                return None
            if cur is None or snap.block_hash != cur:
                self._evict_height_locked(number, "identity")
                return None
            self._heights.move_to_end(number)
            return snap

    def _page_tree(self, snap: _Snapshot, pg: int) -> MerkleTree:
        """Get-or-build the frozen page subtree under a per-(height, page)
        singleflight — concurrent proof storms for one page cost one build.
        Builds dispatch under the `proof` device lane (below sync)."""
        key = (snap.number, pg)
        while True:
            my_fut: Future | None = None
            with self._lock:
                tree = self._trees.get(key)
                if tree is not None:
                    self._trees.move_to_end(key)
                    return tree
                wait_fut = self._building.get(key)
                if wait_fut is None:
                    my_fut = self._building[key] = Future()
            if my_fut is None:
                with self._lock:
                    self.coalesced_builds += 1
                tree = wait_fut.result(timeout=120.0)
                if tree is not None:
                    return tree
                continue
            try:
                from ..device.plane import device_lane

                leaves = [lf for _, (lf, _) in sorted(snap.pages[pg].items())]
                arr = np.frombuffer(
                    b"".join(leaves), dtype=np.uint8
                ).reshape(-1, 32)
                with device_lane("proof"):
                    tree = self.suite.merkle_tree(arr)
            except BaseException as e:
                with self._lock:
                    self._building.pop(key, None)
                my_fut.set_exception(e)
                raise
            with self._lock:
                self._building.pop(key, None)
                self._trees[key] = tree
                self._trees.move_to_end(key)
                while len(self._trees) > self._tree_cap:
                    self._trees.popitem(last=False)
            my_fut.set_result(tree)
            return tree

    def _top_tree(self, snap: _Snapshot) -> MerkleTree:
        """Top tree over the page roots (n_pages leaves — cheap; built
        per serve call from the frozen roots, no cache needed)."""
        from ..device.plane import device_lane

        arr = np.frombuffer(
            b"".join(snap.page_roots), dtype=np.uint8
        ).reshape(-1, 32)
        with device_lane("proof"):
            return self.suite.merkle_tree(arr)

    # -- wiring hooks ----------------------------------------------------------

    def on_rolled_back(self, number: int) -> None:
        """2PC rollback re-drive declared ``number`` dead: evict it and
        everything above, and rebuild the base if it had advanced past."""
        with self._lock:
            for n in [n for n in self._previews if n >= number]:
                self._previews.pop(n)
            for n in [n for n in self._heights if n >= number]:
                self._evict_height_locked(n, "rollback")
            stale_base = self._base is not None and self._base.number >= number
        if stale_base:
            self._bootstrap()

    def on_failover(self) -> None:
        """Storage-backend switch: the recovered backend may disagree about
        everything — drop the whole image chain and rebuild the base."""
        with self._lock:
            self._previews.clear()
            for n in list(self._heights):
                self._evict_height_locked(n, "failover")
            self._trees.clear()
        _log.warning("state plane cleared on storage failover")
        self._bootstrap()

    def invalidate(self, number: int, reason: str = "rollback") -> None:
        with self._lock:
            self._evict_height_locked(number, reason)

    # -- introspection ---------------------------------------------------------

    def pending_builds(self) -> int:
        with self._lock:
            return len(self._building)

    def stats(self) -> dict:
        with self._lock:
            return {
                "hasher": self.hasher,
                "n_pages": self.n_pages,
                "base_number": self._base.number if self._base else None,
                "requests": self.requests,
                "hits": self.hits,
                "misses": self.misses,
                "previews": self.previews,
                "promotes": self.promotes,
                "rebuilds": self.rebuilds,
                "coalesced_builds": self.coalesced_builds,
                "evictions": dict(sorted(self.evictions.items())),
                "heights": len(self._heights),
                "capacity": self.capacity,
            }
