"""Offline storage inspection/repair tool.

The ops-side analog of the reference's storageTool
(bcos-storage/tools/storageTool.cpp: statistic / read / write / iterate /
stateSize over a stopped node's RocksDB), operating on a node's sqlite
state file. Adds `verify` — offline chain-integrity checking (header hash
linkage + number↔hash index agreement + stored tx/receipt presence per
block), which the reference leaves to a separate reader binary.

Usage (module or CLI):
    python -m fisco_bcos_tpu.tool.storage_tool state.db stat
    python -m fisco_bcos_tpu.tool.storage_tool state.db iterate s_config
    python -m fisco_bcos_tpu.tool.storage_tool state.db read s_current_state current_number
    python -m fisco_bcos_tpu.tool.storage_tool state.db write t_test 6b6579 value=abc
    python -m fisco_bcos_tpu.tool.storage_tool state.db verify
Keys and written values are UTF-8 by default; pass --hex to give them as
hex (and to print values as hex — the reference's -H flag).
"""

from __future__ import annotations

import argparse
import json
import sys

from ..storage.sqlite_storage import SQLiteStorage
from ..storage.entry import Entry


def _parse_key(raw: str, force_hex: bool) -> bytes:
    if not force_hex:
        return raw.encode()
    try:
        return bytes.fromhex(raw)
    except ValueError:
        raise SystemExit(f"--hex given but {raw!r} is not valid hex")


def _fmt(b: bytes, hex_out: bool) -> str:
    if hex_out:
        return b.hex()
    try:
        s = b.decode()
        return s if s.isprintable() else b.hex()
    except UnicodeDecodeError:
        return b.hex()


def cmd_stat(store: SQLiteStorage) -> dict:
    """Per-table row counts + byte sizes + pending 2PC slots (the
    reference's --statistic)."""
    out: dict = {"tables": {}, "pending_2pc": store.pending_numbers()}
    conn = store._conn
    for tbl, rows, size in conn.execute(
        "SELECT tbl, COUNT(*), SUM(LENGTH(k) + LENGTH(v)) FROM kv GROUP BY tbl"
    ):
        out["tables"][tbl] = {"rows": rows, "bytes": size}
    out["total_rows"] = sum(t["rows"] for t in out["tables"].values())
    out["total_bytes"] = sum(t["bytes"] for t in out["tables"].values())
    return out


def cmd_read(store: SQLiteStorage, table: str, key: bytes, hex_out: bool) -> dict:
    e = store.get_row(table, key)
    if e is None:
        return {"found": False}
    return {
        "found": True,
        "fields": {f: _fmt(v, hex_out) for f, v in sorted(e.fields.items())},
    }


def cmd_write(store: SQLiteStorage, table: str, key: bytes, fields: dict) -> dict:
    store.set_row(table, key, Entry({f: v for f, v in fields.items()}))
    return {"written": True, "table": table, "key": key.hex()}


def cmd_iterate(store: SQLiteStorage, table: str, limit: int, hex_out: bool) -> list:
    rows = []
    for k in store.get_primary_keys(table)[:limit]:
        e = store.get_row(table, k)
        rows.append(
            {
                "key": _fmt(k, hex_out),
                "fields": {} if e is None else
                {f: _fmt(v, hex_out)[:128] for f, v in sorted(e.fields.items())},
            }
        )
    return rows


def cmd_verify(store: SQLiteStorage) -> dict:
    """Offline chain-integrity check: header linkage (parent hash), stored
    number↔hash index agreement, and per-block tx/receipt presence. The
    crypto suite is auto-detected from whichever hash matches the genesis
    header's stored index (the db carries no explicit suite marker)."""
    from ..crypto.suite import ecdsa_suite, sm_suite
    from ..ledger.ledger import (
        KEY_CURRENT_NUMBER,
        SYS_CURRENT_STATE,
        SYS_HASH_2_NUMBER,
        SYS_HASH_2_RECEIPT,
        SYS_HASH_2_TX,
        SYS_NUMBER_2_HASH,
        SYS_NUMBER_2_HEADER,
        SYS_NUMBER_2_TXS,
        _decode_hash_list,
    )
    from ..protocol.block_header import BlockHeader

    problems: list[str] = []
    cur = store.get_row(SYS_CURRENT_STATE, KEY_CURRENT_NUMBER)
    if cur is None:
        return {"ok": False, "problems": ["no current_number — not a node state db"]}
    tip = int(cur.get().decode())

    g = store.get_row(SYS_NUMBER_2_HEADER, b"0")
    gidx = store.get_row(SYS_NUMBER_2_HASH, b"0")
    if g is None or gidx is None:
        return {"ok": False, "problems": ["genesis header or index missing"]}
    gh = BlockHeader.decode(g.get())
    suite = None
    for cand in (ecdsa_suite(), sm_suite()):
        if gh.hash(cand) == gidx.get():
            suite = cand
            break
    if suite is None:
        return {"ok": False, "problems": ["genesis hash matches no known suite"]}

    prev_hash = None
    for n in range(0, tip + 1):
        he = store.get_row(SYS_NUMBER_2_HEADER, str(n).encode())
        if he is None:
            problems.append(f"block {n}: header missing")
            prev_hash = None
            continue
        header = BlockHeader.decode(he.get())
        h = header.hash(suite)
        idx = store.get_row(SYS_NUMBER_2_HASH, str(n).encode())
        if idx is None or idx.get() != h:
            problems.append(f"block {n}: number->hash index mismatch")
        back = store.get_row(SYS_HASH_2_NUMBER, h)
        if back is None or back.get() != str(n).encode():
            problems.append(f"block {n}: hash->number index mismatch")
        if n > 0 and prev_hash is not None:
            parents = {p.hash for p in header.parent_info}
            if prev_hash not in parents:
                problems.append(f"block {n}: parent hash does not link block {n-1}")
        prev_hash = h
        txs = store.get_row(SYS_NUMBER_2_TXS, str(n).encode())
        if txs is not None:
            for th in _decode_hash_list(txs.get()):
                if store.get_row(SYS_HASH_2_TX, th) is None:
                    problems.append(f"block {n}: tx {th.hex()[:16]} missing")
                if store.get_row(SYS_HASH_2_RECEIPT, th) is None:
                    problems.append(f"block {n}: receipt {th.hex()[:16]} missing")
    return {"ok": not problems, "tip": tip, "suite": suite.hash_impl.name,
            "problems": problems[:50]}


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="storage_tool", description=__doc__.splitlines()[0]
    )
    ap.add_argument("db", help="path to a node's sqlite state file")
    ap.add_argument("--hex", action="store_true", help="keys/values as hex")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("stat")
    p = sub.add_parser("read")
    p.add_argument("table")
    p.add_argument("key")
    p = sub.add_parser("write")
    p.add_argument("table")
    p.add_argument("key")
    p.add_argument("fields", nargs="+", help="field=value ...")
    p = sub.add_parser("iterate")
    p.add_argument("table")
    p.add_argument("--limit", type=int, default=100)
    sub.add_parser("verify")
    args = ap.parse_args(argv)

    store = SQLiteStorage(args.db)
    try:
        if args.cmd == "stat":
            out = cmd_stat(store)
        elif args.cmd == "read":
            out = cmd_read(store, args.table, _parse_key(args.key, args.hex), args.hex)
        elif args.cmd == "write":
            fields = {}
            for f in args.fields:
                name, _, val = f.partition("=")
                fields[name] = bytes.fromhex(val) if args.hex else val.encode()
            out = cmd_write(store, args.table, _parse_key(args.key, args.hex), fields)
        elif args.cmd == "iterate":
            out = cmd_iterate(store, args.table, args.limit, args.hex)
        else:
            out = cmd_verify(store)
    finally:
        store.close()
    print(json.dumps(out, indent=2))
    if isinstance(out, dict) and out.get("ok") is False:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
