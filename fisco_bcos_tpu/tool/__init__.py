"""Boot/ops tooling — config loading and chain deployment.

Reference: bcos-tool (NodeConfig.cpp INI loading) + tools/BcosAirBuilder
(build_chain.sh deployment generator).
"""

from .config import ChainOptions, load_chain_options, load_genesis, load_keypair

__all__ = ["ChainOptions", "load_chain_options", "load_genesis", "load_keypair"]
