"""INI config loading — the bcos-tool/NodeConfig analog.

Reference: bcos-tool/src/NodeConfig.cpp:58-93 (`loadConfig` dispatching to
loadRpcConfig / loadGatewayConfig / loadTxPoolConfig / loadChainConfig /
loadSealerConfig / loadStorageConfig / loadConsensusConfig, then
`loadGenesisConfig` for the ledger/executor sections), using
boost::property_tree INI files.  This loader reads the same two files
(``config.ini`` + ``config.genesis``) with the same section/key naming and
produces the framework's dataclass configs.

The genesis file is consensus-critical: every node must derive the identical
genesis block from it (reference: Ledger::buildGenesisBlock), so parsing here
is strict — unknown consensus node lines are errors, not warnings.
"""

from __future__ import annotations

import configparser
import os
from dataclasses import dataclass, field

from ..ledger import ConsensusNode, GenesisConfig
from ..node.node import NodeConfig


@dataclass
class P2PPeer:
    host: str
    port: int


@dataclass
class ChainOptions:
    """Everything main() needs beyond NodeConfig: transports + key paths.

    Mirrors the [rpc]/[p2p]/[cert]/[security] sections of the reference
    config.ini (NodeConfig.cpp loadRpcConfig/loadGatewayConfig +
    GatewayConfig.cpp cert paths).
    """

    node: NodeConfig = field(default_factory=NodeConfig)
    # [rpc]
    rpc_listen_ip: str = "127.0.0.1"
    rpc_listen_port: int = 20200
    ws_listen_port: int = 0  # 0 -> websocket channel disabled
    # [p2p]
    p2p_listen_ip: str = "127.0.0.1"
    p2p_listen_port: int = 30300
    peers: list[P2PPeer] = field(default_factory=list)
    # [security]
    private_key_path: str = "conf/node.key"
    # [cert] — mutual TLS for P2P + RPC (bcos-boostssl/context)
    enable_ssl: bool = False
    ca_cert: str = "conf/ca.crt"
    node_cert: str = "conf/ssl.crt"
    node_key: str = "conf/ssl.key"
    # [cert] sm_* — national-secret transport dual pair
    # (GatewayConfig.cpp:304-345 SMCertConfig; used when sm_crypto=true)
    sm_ca_cert: str = "conf/sm_ca.crt"
    sm_node_cert: str = "conf/sm_ssl.crt"
    sm_node_key: str = "conf/sm_ssl.key"
    sm_ennode_cert: str = "conf/sm_enssl.crt"
    sm_ennode_key: str = "conf/sm_enssl.key"
    # [consensus] runtime knobs (engine limits come from genesis)
    consensus_timeout: float = 3.0
    sealer_interval: float = 0.05
    sync_interval: float = 0.5
    # [log]
    log_level: str = "info"


def _parser(path: str) -> configparser.ConfigParser:
    cp = configparser.ConfigParser(inline_comment_prefixes=(";", "#"))
    with open(path) as f:
        cp.read_file(f)
    return cp


def load_genesis(path: str) -> GenesisConfig:
    """Parse config.genesis (reference: NodeConfig::loadGenesisConfig —
    [chain]/[consensus]/[tx]/[version]/[executor] sections; consensus node
    lines are ``node.N=<128-hex-pubkey>:<weight>``)."""
    cp = _parser(path)
    g = GenesisConfig()
    if cp.has_section("chain"):
        g.chain_id = cp.get("chain", "chain_id", fallback=g.chain_id)
        g.group_id = cp.get("chain", "group_id", fallback=g.group_id)
    if cp.has_section("consensus"):
        g.leader_period = cp.getint(
            "consensus", "leader_period", fallback=g.leader_period
        )
        g.tx_count_limit = cp.getint(
            "consensus", "block_tx_count_limit", fallback=g.tx_count_limit
        )
        for key, val in cp.items("consensus"):
            if not key.startswith("node."):
                continue
            try:
                pub_hex, weight = val.rsplit(":", 1)
                pub = bytes.fromhex(pub_hex)
                if len(pub) != 64:
                    raise ValueError("node id must be 64 bytes")
                g.consensus_nodes.append(ConsensusNode(pub, weight=int(weight)))
            except ValueError as e:
                raise ValueError(f"bad consensus node line {key}={val}: {e}") from e
    if cp.has_section("tx"):
        g.gas_limit = cp.getint("tx", "gas_limit", fallback=g.gas_limit)
    if cp.has_section("executor"):
        g.is_wasm = cp.getboolean("executor", "is_wasm", fallback=g.is_wasm)
    if cp.has_section("version"):
        g.version = cp.getint("version", "compatibility_version", fallback=g.version)
    return g


def load_chain_options(config_path: str, genesis_path: str) -> ChainOptions:
    """Parse config.ini + config.genesis into ChainOptions.

    Relative paths inside config.ini resolve against the config file's
    directory (the reference resolves against the node dir the same way).
    """
    base = os.path.dirname(os.path.abspath(config_path))
    cp = _parser(config_path)
    opts = ChainOptions()
    opts.node.genesis = load_genesis(genesis_path)
    opts.node.chain_id = opts.node.genesis.chain_id
    opts.node.group_id = opts.node.genesis.group_id

    def respath(p: str) -> str:
        return p if os.path.isabs(p) else os.path.join(base, p)

    if cp.has_section("chain"):
        opts.node.sm_crypto = cp.getboolean("chain", "sm_crypto", fallback=False)
    if cp.has_section("security"):
        opts.private_key_path = respath(
            cp.get("security", "private_key_path", fallback=opts.private_key_path)
        )
    if cp.has_section("storage"):
        data_path = cp.get("storage", "data_path", fallback="data")
        if data_path in ("", ":memory:"):
            opts.node.db_path = ":memory:"
        else:
            d = respath(data_path)
            os.makedirs(d, exist_ok=True)
            opts.node.db_path = os.path.join(d, "state.db")
    if cp.has_section("storage_security"):
        # bcos-security DataEncryption: [storage_security] enable/data_key
        if cp.getboolean("storage_security", "enable", fallback=False):
            opts.node.data_key = cp.get(
                "storage_security", "data_key", fallback=""
            ).encode()
    if cp.has_section("txpool"):
        opts.node.pool_limit = cp.getint(
            "txpool", "limit", fallback=opts.node.pool_limit
        )
        opts.node.block_limit = cp.getint(
            "txpool", "block_limit", fallback=opts.node.block_limit
        )
    if cp.has_section("rpc"):
        opts.rpc_listen_ip = cp.get("rpc", "listen_ip", fallback=opts.rpc_listen_ip)
        opts.rpc_listen_port = cp.getint(
            "rpc", "listen_port", fallback=opts.rpc_listen_port
        )
        opts.ws_listen_port = cp.getint("rpc", "ws_port", fallback=0)
    if cp.has_section("p2p"):
        opts.p2p_listen_ip = cp.get("p2p", "listen_ip", fallback=opts.p2p_listen_ip)
        opts.p2p_listen_port = cp.getint(
            "p2p", "listen_port", fallback=opts.p2p_listen_port
        )
        for key, val in cp.items("p2p"):
            if key.startswith("node."):
                host, port = val.rsplit(":", 1)
                opts.peers.append(P2PPeer(host, int(port)))
    if cp.has_section("cert"):
        opts.enable_ssl = cp.getboolean("cert", "enable_ssl", fallback=False)
        opts.ca_cert = respath(cp.get("cert", "ca_cert", fallback=opts.ca_cert))
        opts.node_cert = respath(cp.get("cert", "node_cert", fallback=opts.node_cert))
        opts.node_key = respath(cp.get("cert", "node_key", fallback=opts.node_key))
        for f in (
            "sm_ca_cert",
            "sm_node_cert",
            "sm_node_key",
            "sm_ennode_cert",
            "sm_ennode_key",
        ):
            setattr(opts, f, respath(cp.get("cert", f, fallback=getattr(opts, f))))
    if cp.has_section("consensus"):
        opts.consensus_timeout = cp.getfloat(
            "consensus", "consensus_timeout", fallback=opts.consensus_timeout
        )
        opts.sealer_interval = cp.getfloat(
            "consensus", "sealer_interval", fallback=opts.sealer_interval
        )
    if cp.has_section("sync"):
        opts.sync_interval = cp.getfloat(
            "sync", "sync_interval", fallback=opts.sync_interval
        )
    if cp.has_section("log"):
        opts.log_level = cp.get("log", "level", fallback=opts.log_level)
    return opts


def load_keypair(path: str, suite):
    """node.key: hex-encoded secret scalar (one line).  The reference stores
    a PEM EC key (NodeConfig loadSecurityConfig); a bare scalar carries the
    same entropy without an ASN.1 dependency."""
    with open(path) as f:
        secret = int(f.read().strip(), 16)
    return suite.signature_impl.generate_keypair(secret=secret)


def save_keypair(path: str, kp) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(f"{kp.secret:064x}\n")
    os.chmod(path, 0o600)
    with open(os.path.join(os.path.dirname(path), "node.nodeid"), "w") as f:
        f.write(kp.pub.hex() + "\n")
