"""Chain deployment generator — the build_chain.sh analog.

Reference: tools/BcosAirBuilder/build_chain.sh (1,962 lines: chain CA + node
certs, node keys, config.ini/config.genesis per node, start/stop scripts).
Usage::

    python -m fisco_bcos_tpu.tool.build_chain -l 127.0.0.1:4 -o nodes \
        [--sm] [--ssl] [-p 30300,20200]

emits::

    nodes/ca/{ca.crt,ca.key}                (with --ssl)
    nodes/node<i>/config.ini
    nodes/node<i>/config.genesis
    nodes/node<i>/conf/{node.key,node.nodeid[,ssl.crt,ssl.key,ca.crt]}
    nodes/node<i>/start.sh  nodes/{start_all,stop_all}.sh
"""

from __future__ import annotations

import argparse
import os
import shutil
import stat
import sys


def _genesis_text(nodeids: list[str], chain_id: str, group_id: str) -> str:
    nodes = "\n".join(
        f"    node.{i}={nid}:1" for i, nid in enumerate(nodeids)
    )
    return f"""[chain]
    chain_id={chain_id}
    group_id={group_id}

[consensus]
    consensus_type=pbft
    block_tx_count_limit=1000
    leader_period=1
{nodes}

[tx]
    gas_limit=3000000000

[executor]
    is_wasm=false

[version]
    compatibility_version=1
"""


def _config_text(
    host: str,
    p2p_port: int,
    rpc_port: int,
    ws_port: int,
    peers: list[tuple[str, int]],
    sm: bool,
    ssl: bool,
) -> str:
    peer_lines = "\n".join(
        f"    node.{i}={h}:{p}" for i, (h, p) in enumerate(peers)
    )
    return f"""[chain]
    sm_crypto={'true' if sm else 'false'}

[security]
    private_key_path=conf/node.key

[cert]
    enable_ssl={'true' if ssl else 'false'}
    ca_cert=conf/ca.crt
    node_cert=conf/ssl.crt
    node_key=conf/ssl.key
    sm_ca_cert=conf/sm_ca.crt
    sm_node_cert=conf/sm_ssl.crt
    sm_node_key=conf/sm_ssl.key
    sm_ennode_cert=conf/sm_enssl.crt
    sm_ennode_key=conf/sm_enssl.key

[rpc]
    listen_ip={host}
    listen_port={rpc_port}
    ws_port={ws_port}

[p2p]
    listen_ip={host}
    listen_port={p2p_port}
{peer_lines}

[consensus]
    consensus_timeout=3.0
    sealer_interval=0.05

[sync]
    sync_interval=0.5

[storage]
    data_path=data

[txpool]
    limit=135000
    block_limit=600

[log]
    level=info
"""


_START_SH = """#!/bin/bash
cd "$(dirname "$0")"
nohup {python} -m fisco_bcos_tpu -c config.ini -g config.genesis \\
    >> node.log 2>&1 &
echo $! > node.pid
echo "started node (pid $(cat node.pid))"
"""

_STOP_SH = """#!/bin/bash
cd "$(dirname "$0")"
[ -f node.pid ] && kill "$(cat node.pid)" 2>/dev/null && rm -f node.pid
"""


def _write_exec(path: str, text: str) -> None:
    with open(path, "w") as f:
        f.write(text)
    os.chmod(path, os.stat(path).st_mode | stat.S_IXUSR | stat.S_IXGRP)


def build_chain(
    out_dir: str,
    count: int,
    host: str = "127.0.0.1",
    p2p_base: int = 30300,
    rpc_base: int = 20200,
    sm: bool = False,
    ssl: bool = False,
    chain_id: str = "chain0",
    group_id: str = "group0",
    ports: list[tuple[int, int]] | None = None,
) -> list[str]:
    """Generate `count` node directories under out_dir; returns their paths.
    `ports` overrides the (p2p, rpc) pair per node (tests pick free ports)."""
    from ..crypto.suite import ecdsa_suite, sm_suite

    from .config import save_keypair

    suite = sm_suite() if sm else ecdsa_suite()
    os.makedirs(out_dir, exist_ok=True)

    if ports is None:
        # third member = websocket channel (event-sub/AMOP push)
        ports = [(p2p_base + i, rpc_base + i, rpc_base + 500 + i) for i in range(count)]
    ports = [p if len(p) == 3 else (p[0], p[1], p[1] + 500) for p in ports]
    keypairs = [suite.signature_impl.generate_keypair() for _ in range(count)]
    nodeids = [kp.pub.hex() for kp in keypairs]
    genesis = _genesis_text(nodeids, chain_id, group_id)
    peers = [(host, p[0]) for p in ports]

    ca_crt = ca_key = sm_ca = None
    if ssl:
        from ..gateway.tls import generate_chain_ca

        ca_crt, ca_key = generate_chain_ca(os.path.join(out_dir, "ca"))
        if sm:
            # national-secret transport: a second, SM2 chain CA issuing the
            # TLCP dual pairs (reference build_chain.sh generates the sm_*
            # cert tree alongside the RSA/EC one when -s is set)
            from ..gateway.sm_tls import generate_sm_chain_ca

            sm_ca = generate_sm_chain_ca(os.path.join(out_dir, "ca"))

    node_dirs = []
    for i in range(count):
        ndir = os.path.join(out_dir, f"node{i}")
        conf = os.path.join(ndir, "conf")
        os.makedirs(conf, exist_ok=True)
        p2p_port, rpc_port, ws_port = ports[i]
        with open(os.path.join(ndir, "config.genesis"), "w") as f:
            f.write(genesis)
        with open(os.path.join(ndir, "config.ini"), "w") as f:
            f.write(_config_text(host, p2p_port, rpc_port, ws_port, peers, sm, ssl))
        save_keypair(os.path.join(conf, "node.key"), keypairs[i])
        if ssl:
            from ..gateway.tls import issue_node_cert

            issue_node_cert(
                ca_crt, ca_key, conf, f"node{i}", hosts=[host],
                node_id=keypairs[i].pub,
            )
            shutil.copy(ca_crt, os.path.join(conf, "ca.crt"))
            if sm_ca is not None:
                from ..gateway.sm_tls import issue_sm_node_certs

                issue_sm_node_certs(
                    sm_ca, conf, f"node{i}", node_id=keypairs[i].pub
                )
        _write_exec(
            os.path.join(ndir, "start.sh"), _START_SH.format(python=sys.executable)
        )
        _write_exec(os.path.join(ndir, "stop.sh"), _STOP_SH)
        node_dirs.append(ndir)

    _write_exec(
        os.path.join(out_dir, "start_all.sh"),
        "#!/bin/bash\ncd \"$(dirname \"$0\")\"\n"
        + "".join(f"./node{i}/start.sh\n" for i in range(count)),
    )
    _write_exec(
        os.path.join(out_dir, "stop_all.sh"),
        "#!/bin/bash\ncd \"$(dirname \"$0\")\"\n"
        + "".join(f"./node{i}/stop.sh\n" for i in range(count)),
    )
    return node_dirs


# ---------------------------------------------------------------------------
# Pro-mode deployer (the BcosBuilder/ProNodeInitializer analog)
# ---------------------------------------------------------------------------

_PRO_SVC_SH = """#!/bin/bash
cd "$(dirname "$0")"
nohup {python} -m {module} {args} > {name}.log 2>&1 &
echo $! > {name}.pid
"""

_PRO_STOP_SH = """#!/bin/bash
cd "$(dirname "$0")"
for pid in rpc.pid core.pid gateway.pid storage.pid; do
    [ -f "$pid" ] && kill "$(cat "$pid")" 2>/dev/null && rm -f "$pid"
done
exit 0
"""

# Max nodes additionally run N executor services; *.pid catches them all
_MAX_STOP_SH = """#!/bin/bash
cd "$(dirname "$0")"
for pid in *.pid; do
    [ -f "$pid" ] && kill "$(cat "$pid")" 2>/dev/null && rm -f "$pid"
done
exit 0
"""


def build_pro_chain(
    out_dir: str,
    count: int,
    host: str = "127.0.0.1",
    port_base: int = 40000,
    sm: bool = False,
    chain_id: str = "chain0",
    group_id: str = "group0",
) -> list[str]:
    """Generate a Pro-topology deployment: per node a storage service, a
    gateway service, the node core (pro_node) and an RPC front-door process,
    each with its own start script and a deterministic port block.

    Reference: tools/BcosBuilder (the python deployer that renders per-
    service config/start artifacts for the tars Pro deployment form) +
    fisco-bcos-tars-service process layout. Port block per node i:
    base+10i = storage, +1 gateway service, +2 p2p, +3 node facade,
    +4 rpc http.
    """
    from ..crypto.suite import ecdsa_suite, sm_suite

    from .config import save_keypair

    suite = sm_suite() if sm else ecdsa_suite()
    os.makedirs(out_dir, exist_ok=True)
    keypairs = [suite.signature_impl.generate_keypair() for _ in range(count)]
    genesis = _genesis_text([kp.pub.hex() for kp in keypairs], chain_id, group_id)

    def ports(i):
        b = port_base + 10 * i
        return {"storage": b, "gwsvc": b + 1, "p2p": b + 2, "facade": b + 3, "rpc": b + 4}

    node_dirs = []
    for i in range(count):
        ndir = os.path.join(out_dir, f"node{i}")
        conf = os.path.join(ndir, "conf")
        os.makedirs(conf, exist_ok=True)
        p = ports(i)
        with open(os.path.join(ndir, "config.genesis"), "w") as f:
            f.write(genesis)
        save_keypair(os.path.join(conf, "node.key"), keypairs[i])
        peers = ",".join(
            f"{host}:{ports(j)['p2p']}" for j in range(count) if j != i
        )
        sm_flag = " --sm" if sm else ""
        svcs = [
            (
                "storage",
                "fisco_bcos_tpu.service",
                f"storage --db chain.db --port {p['storage']}",
            ),
            (
                "gateway",
                "fisco_bcos_tpu.service",
                f"gateway --node-id {keypairs[i].pub.hex()} "
                f"--service-port {p['gwsvc']} --p2p-port {p['p2p']}"
                + (f" --peers {peers}" if peers else ""),
            ),
            (
                "core",
                "fisco_bcos_tpu.node.pro_node",
                f"-g config.genesis --key conf/node.key "
                f"--gateway {host}:{p['gwsvc']} --storage {host}:{p['storage']} "
                f"--facade-port {p['facade']}" + sm_flag,
            ),
            (
                "rpc",
                "fisco_bcos_tpu.service",
                f"rpc --facade {host}:{p['facade']} --port {p['rpc']}",
            ),
        ]
        for name, module, svc_args in svcs:
            _write_exec(
                os.path.join(ndir, f"start_{name}.sh"),
                _PRO_SVC_SH.format(
                    python=sys.executable, module=module, args=svc_args, name=name
                ),
            )
        _write_exec(
            os.path.join(ndir, "start.sh"),
            "#!/bin/bash\ncd \"$(dirname \"$0\")\"\n"
            "./start_storage.sh\nsleep 0.5\n./start_gateway.sh\nsleep 0.5\n"
            "./start_core.sh\nsleep 1\n./start_rpc.sh\n",
        )
        _write_exec(os.path.join(ndir, "stop.sh"), _PRO_STOP_SH)
        node_dirs.append(ndir)

    _write_exec(
        os.path.join(out_dir, "start_all.sh"),
        "#!/bin/bash\ncd \"$(dirname \"$0\")\"\n"
        + "".join(f"./node{i}/start.sh\n" for i in range(count)),
    )
    _write_exec(
        os.path.join(out_dir, "stop_all.sh"),
        "#!/bin/bash\ncd \"$(dirname \"$0\")\"\n"
        + "".join(f"./node{i}/stop.sh\n" for i in range(count)),
    )
    return node_dirs


def build_max_chain(
    out_dir: str,
    count: int,
    executors: int = 2,
    host: str = "127.0.0.1",
    port_base: int = 40000,
    sm: bool = False,
    chain_id: str = "chain0",
    group_id: str = "group0",
) -> list[str]:
    """Generate a Max-topology deployment: ONE shared storage service (the
    TiKV analog), and per consensus node a gateway service, the node core
    hosting an executor registry, an RPC front door, and a fleet of
    ``executors`` stateless executor processes that register with the core
    and heartbeat (killing one mid-block is survivable — the scheduler
    term-switches and re-executes on the survivors).

    Reference: tools/BcosBuilder max profile + fisco-bcos-tars-service
    (every subsystem its own service; TarsRemoteExecutorManager discovery).
    Port block: base = shared storage; per node i at base+20(i+1):
    +0 gateway svc, +1 p2p, +2 facade, +3 rpc, +4 registry,
    +5.. executor services.
    """
    from ..crypto.suite import ecdsa_suite, sm_suite

    from .config import save_keypair

    if not 1 <= executors <= 14:
        # the per-node port block is 20 wide (5 fixed + executor slots);
        # more executors would collide with the next node's block
        raise ValueError(f"max mode supports 1..14 executors per node, got {executors}")
    suite = sm_suite() if sm else ecdsa_suite()
    os.makedirs(out_dir, exist_ok=True)
    keypairs = [suite.signature_impl.generate_keypair() for _ in range(count)]
    genesis = _genesis_text([kp.pub.hex() for kp in keypairs], chain_id, group_id)
    sm_flag = " --sm" if sm else ""

    storage_port = port_base
    _write_exec(
        os.path.join(out_dir, "start_storage.sh"),
        _PRO_SVC_SH.format(
            python=sys.executable,
            module="fisco_bcos_tpu.service",
            args=f"storage --db max_chain.db --port {storage_port}",
            name="storage",
        ),
    )

    def ports(i):
        b = port_base + 20 * (i + 1)
        return {
            "gwsvc": b, "p2p": b + 1, "facade": b + 2, "rpc": b + 3,
            "registry": b + 4, "exec0": b + 5,
        }

    node_dirs = []
    for i in range(count):
        ndir = os.path.join(out_dir, f"node{i}")
        conf = os.path.join(ndir, "conf")
        os.makedirs(conf, exist_ok=True)
        p = ports(i)
        with open(os.path.join(ndir, "config.genesis"), "w") as f:
            f.write(genesis)
        save_keypair(os.path.join(conf, "node.key"), keypairs[i])
        peers = ",".join(
            f"{host}:{ports(j)['p2p']}" for j in range(count) if j != i
        )
        svcs = [
            (
                "gateway",
                "fisco_bcos_tpu.service",
                f"gateway --node-id {keypairs[i].pub.hex()} "
                f"--service-port {p['gwsvc']} --p2p-port {p['p2p']}"
                + (f" --peers {peers}" if peers else ""),
            ),
            (
                "core",
                "fisco_bcos_tpu.node.pro_node",
                f"-g config.genesis --key conf/node.key "
                f"--gateway {host}:{p['gwsvc']} --storage {host}:{storage_port} "
                f"--facade-port {p['facade']} "
                f"--executor-registry-port {p['registry']} "
                f"--executors {executors}" + sm_flag,
            ),
            (
                "rpc",
                "fisco_bcos_tpu.service",
                f"rpc --facade {host}:{p['facade']} --port {p['rpc']}",
            ),
        ]
        for e in range(executors):
            svcs.append(
                (
                    f"executor{e}",
                    "fisco_bcos_tpu.service",
                    f"executor --storage {host}:{storage_port} "
                    f"--port {p['exec0'] + e} --name node{i}-executor{e} "
                    f"--registry {host}:{p['registry']}" + sm_flag,
                )
            )
        for name, module, svc_args in svcs:
            _write_exec(
                os.path.join(ndir, f"start_{name}.sh"),
                _PRO_SVC_SH.format(
                    python=sys.executable, module=module, args=svc_args, name=name
                ),
            )
        exec_starts = "".join(
            f"./start_executor{e}.sh\n" for e in range(executors)
        )
        _write_exec(
            os.path.join(ndir, "start.sh"),
            "#!/bin/bash\ncd \"$(dirname \"$0\")\"\n"
            "./start_gateway.sh\nsleep 0.5\n"
            + exec_starts
            + "sleep 0.5\n./start_core.sh\nsleep 1\n./start_rpc.sh\n",
        )
        _write_exec(os.path.join(ndir, "stop.sh"), _MAX_STOP_SH)
        node_dirs.append(ndir)

    _write_exec(
        os.path.join(out_dir, "start_all.sh"),
        "#!/bin/bash\ncd \"$(dirname \"$0\")\"\n./start_storage.sh\nsleep 1\n"
        + "".join(f"./node{i}/start.sh\n" for i in range(count)),
    )
    _write_exec(
        os.path.join(out_dir, "stop_all.sh"),
        "#!/bin/bash\ncd \"$(dirname \"$0\")\"\n"
        + "".join(f"./node{i}/stop.sh\n" for i in range(count))
        + "pkill -f 'fisco_bcos_tpu.service storage' 2>/dev/null\ntrue\n",
    )
    return node_dirs


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="build_chain", description=__doc__)
    ap.add_argument("-l", "--listen", default="127.0.0.1:4", help="host:count")
    ap.add_argument("-o", "--output", default="nodes")
    ap.add_argument("-p", "--ports", default="30300,20200", help="p2p_base,rpc_base")
    ap.add_argument("--sm", action="store_true", help="SM2/SM3 national crypto")
    ap.add_argument("--ssl", action="store_true", help="mutual TLS on P2P + RPC")
    ap.add_argument("--chain-id", default="chain0")
    ap.add_argument("--group-id", default="group0")
    ap.add_argument(
        "--mode",
        choices=("air", "pro", "max"),
        default="air",
        help="air = one process per node; pro = storage/gateway/core/rpc "
        "as separate service processes per node (BcosBuilder analog); "
        "max = shared storage + per-node executor fleet with registry "
        "discovery and failover",
    )
    ap.add_argument(
        "--executors", type=int, default=2,
        help="max mode: executor services per node",
    )
    args = ap.parse_args(argv)

    host, count = args.listen.rsplit(":", 1)
    if args.mode == "max":
        if args.ssl:
            ap.error("--ssl is not supported with --mode max")
        dirs = build_max_chain(
            args.output,
            int(count),
            executors=args.executors,
            host=host,
            port_base=int(args.ports.split(",")[0]),
            sm=args.sm,
            chain_id=args.chain_id,
            group_id=args.group_id,
        )
        print(f"generated {len(dirs)} max node groups under {args.output}/")
        return 0
    if args.mode == "pro":
        if args.ssl:
            ap.error(
                "--ssl is not supported with --mode pro yet; the pro "
                "service mesh runs plaintext service RPC on localhost"
            )
        dirs = build_pro_chain(
            args.output,
            int(count),
            host=host,
            port_base=int(args.ports.split(",")[0]),
            sm=args.sm,
            chain_id=args.chain_id,
            group_id=args.group_id,
        )
        print(f"generated {len(dirs)} pro node groups under {args.output}/")
        return 0
    p2p_base, rpc_base = (int(x) for x in args.ports.split(","))
    dirs = build_chain(
        args.output,
        int(count),
        host=host,
        p2p_base=p2p_base,
        rpc_base=rpc_base,
        sm=args.sm,
        ssl=args.ssl,
        chain_id=args.chain_id,
        group_id=args.group_id,
    )
    print(f"generated {len(dirs)} nodes under {args.output}/")
    return 0


if __name__ == "__main__":
    sys.exit(main())
