"""Worker / ThreadPool / Timer — the bcos-utilities concurrency kit.

Reference: bcos-utilities/{Worker.h, ThreadPool.h, Timer.cpp,
ConcurrentQueue.h}.  Every reference module owns a named worker thread or
pool; here the same three shapes back the node runtime, gateway, and RPC:

- ``Worker``: one named thread draining a task queue (Worker.h's
  startWorking/stopWorking loop).
- ``ThreadPool``: N workers over one queue (ThreadPool.h enqueue semantics).
- ``RepeatingTimer``: fixed-interval callback with drift correction
  (Timer.cpp's restart/destroy contract).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable

from .log import get_logger

_log = get_logger("worker")


class Worker:
    """One named thread draining a task queue."""

    def __init__(self, name: str = "worker"):
        self.name = name
        self._queue: queue.SimpleQueue = queue.SimpleQueue()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, name=self.name, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._queue.put(None)
        self._thread.join(timeout=5)
        self._thread = None

    def post(self, task: Callable[[], None]) -> None:
        self._queue.put(task)

    def _run(self) -> None:
        while True:
            task = self._queue.get()
            if task is None:
                return
            try:
                task()
            except Exception:
                _log.exception("task failed on %s", self.name)


class ThreadPool:
    """N workers over one queue (ThreadPool.h)."""

    def __init__(self, size: int, name: str = "pool"):
        self._queue: queue.SimpleQueue = queue.SimpleQueue()
        self._threads = [
            threading.Thread(target=self._run, name=f"{name}-{i}", daemon=True)
            for i in range(size)
        ]
        self._started = False

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        if not self._started:
            return
        for _ in self._threads:
            self._queue.put(None)
        for t in self._threads:
            t.join(timeout=5)
        self._started = False

    def enqueue(self, task: Callable[[], None]) -> None:
        self._queue.put(task)

    def _run(self) -> None:
        while True:
            task = self._queue.get()
            if task is None:
                return
            try:
                task()
            except Exception:
                _log.exception("pool task failed")


class RepeatingTimer:
    """Fixed-interval callback on its own thread, drift-corrected."""

    def __init__(self, interval: float, callback: Callable[[], None], name: str = "timer"):
        self.interval = interval
        self.callback = callback
        self.name = name
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, name=self.name, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        nxt = time.monotonic() + self.interval
        while not self._stop.wait(max(0.0, nxt - time.monotonic())):
            nxt += self.interval
            try:
                self.callback()
            except Exception:
                _log.exception("timer %s callback failed", self.name)
