"""Fixed-bytes helpers (reference: bcos-utilities/FixedBytes.h h160/h256)."""

from __future__ import annotations


def h256(data: bytes) -> bytes:
    """Normalize to exactly 32 bytes (left-pad with zeros, error on overflow)."""
    if len(data) > 32:
        raise ValueError(f"h256 overflow: {len(data)} bytes")
    return data.rjust(32, b"\x00")


def to_hex(data: bytes, prefix: bool = True) -> str:
    return ("0x" if prefix else "") + data.hex()


def from_hex(s: str) -> bytes:
    if s.startswith(("0x", "0X")):
        s = s[2:]
    if len(s) % 2:
        s = "0" + s
    return bytes.fromhex(s)


def int_to_bytes32(v: int) -> bytes:
    return int(v).to_bytes(32, "big")


def bytes32_to_int(b: bytes) -> int:
    return int.from_bytes(b, "big")


def right160(b: bytes) -> bytes:
    """Rightmost 160 bits of a 32-byte hash — address derivation
    (reference: bcos-crypto CryptoSuite.h:56-59 calculateAddress)."""
    return b[-20:]
