"""Error type + protocol error codes.

Reference: bcos-utilities/Error.h and bcos-framework CommonError.h. The
transaction/receipt status family lives ONLY in
:class:`fisco_bcos_tpu.protocol.receipt.TransactionStatus` (one copy of
TransactionStatus.h); this enum carries the module-level error codes —
txpool admission values match TransactionStatus.h:54-63 exactly because the
reference reports them through the same numeric space.
"""

from __future__ import annotations

from enum import IntEnum


class ErrorCode(IntEnum):
    SUCCESS = 0
    # TxPool admission (reference: bcos-protocol TransactionStatus.h:54-63)
    NONCE_CHECK_FAIL = 10000
    BLOCK_LIMIT_CHECK_FAIL = 10001
    TX_POOL_FULL = 10002
    MALFORM = 10003
    ALREADY_IN_TX_POOL = 10004
    TX_ALREADY_IN_CHAIN = 10005
    INVALID_CHAIN_ID = 10006
    INVALID_GROUP_ID = 10007
    INVALID_SIGNATURE = 10008
    REQUEST_NOT_BELONG_TO_THE_GROUP = 10009
    # multi-tenant isolation (this framework's extension of the admission
    # family): per-group token-bucket quota exceeded / submitting source
    # demoted after repeated invalid-signature strikes
    OVER_GROUP_QUOTA = 10010
    SOURCE_DEMOTED = 10011
    # Scheduler / executor
    SCHEDULER_INVALID_BLOCK = 21000
    SCHEDULER_BLOCK_IN_QUEUE = 21001
    EXECUTOR_ERROR = 22000
    DEAD_LOCK = 22001
    # Consensus
    CONSENSUS_INVALID_PROPOSAL = 23000
    CONSENSUS_INVALID_VIEW = 23001
    CONSENSUS_TIMEOUT = 23002
    # Storage
    STORAGE_ERROR = 24000
    TABLE_NOT_EXIST = 24001
    TABLE_ALREADY_EXIST = 24002


class BcosError(Exception):
    def __init__(self, code: int, message: str = ""):
        super().__init__(f"[{code}] {message}")
        self.code = int(code)
        self.message = message
