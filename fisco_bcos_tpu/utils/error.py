"""Error type + protocol error codes.

Reference: bcos-utilities/Error.h and
bcos-framework/protocol/CommonError.h / TransactionStatus.h.
"""

from __future__ import annotations

from enum import IntEnum


class ErrorCode(IntEnum):
    SUCCESS = 0
    # Transaction status (reference: bcos-protocol TransactionStatus.h)
    UNKNOWN = 1
    OUT_OF_GAS_LIMIT = 2
    NOT_ENOUGH_CASH = 7
    BAD_INSTRUCTION = 10
    REVERT_INSTRUCTION = 12
    STACK_OVERFLOW = 14
    STACK_UNDERFLOW = 15
    PRECOMPILED_ERROR = 24
    # TxPool (reference: bcos-framework txpool/TxPoolTypeDef.h)
    NONCE_CHECK_FAIL = 10000
    BLOCK_LIMIT_CHECK_FAIL = 10001
    TX_POOL_ALREADY_KNOWN = 10002
    TX_POOL_NONCE_TOO_OLD = 10003
    INVALID_CHAIN_ID = 10004
    INVALID_GROUP_ID = 10005
    INVALID_SIGNATURE = 10006
    REQUIRE_PROOF = 10007
    TX_POOL_FULL = 10008
    TX_POOL_TIMEOUT = 10009
    ALREADY_IN_TX_POOL = 10010
    # Scheduler / executor
    SCHEDULER_INVALID_BLOCK = 21000
    SCHEDULER_BLOCK_IN_QUEUE = 21001
    EXECUTOR_ERROR = 22000
    DEAD_LOCK = 22001
    # Consensus
    CONSENSUS_INVALID_PROPOSAL = 23000
    CONSENSUS_INVALID_VIEW = 23001
    CONSENSUS_TIMEOUT = 23002
    # Storage
    STORAGE_ERROR = 24000
    TABLE_NOT_EXIST = 24001
    TABLE_ALREADY_EXIST = 24002


class BcosError(Exception):
    def __init__(self, code: int, message: str = ""):
        super().__init__(f"[{code}] {message}")
        self.code = int(code)
        self.message = message
