import os

from .bytesutil import (
    h256,
    to_hex,
    from_hex,
    int_to_bytes32,
    bytes32_to_int,
    right160,
)
from .error import BcosError, ErrorCode
from .log import get_logger, metric


def env_float(name: str, default: float) -> float:
    """Float env knob with fallback on unset/empty/malformed — the one
    parser every tunable shares (quota rates, plane windows, the device
    observatory's storm bounds)."""
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


__all__ = [
    "env_float",
    "h256",
    "to_hex",
    "from_hex",
    "int_to_bytes32",
    "bytes32_to_int",
    "right160",
    "BcosError",
    "ErrorCode",
    "get_logger",
    "metric",
]
