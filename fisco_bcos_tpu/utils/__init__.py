from .bytesutil import (
    h256,
    to_hex,
    from_hex,
    int_to_bytes32,
    bytes32_to_int,
    right160,
)
from .error import BcosError, ErrorCode
from .log import get_logger, metric

__all__ = [
    "h256",
    "to_hex",
    "from_hex",
    "int_to_bytes32",
    "bytes32_to_int",
    "right160",
    "BcosError",
    "ErrorCode",
    "get_logger",
    "metric",
]
