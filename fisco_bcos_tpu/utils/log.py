"""Structured logging with METRIC-style key/value stage lines.

Reference: bcos-utilities/Log.h LOG_BADGE/LOG_KV/LOG_DESC macros and the METRIC
badge (bcos-framework/Common.h:24) that the mtail sidecar scrapes into Prometheus
gauges. We emit the same shape: ``[badge] desc|k1=v1|k2=v2``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any

_FORMAT = "%(asctime)s %(levelname)s [%(name)s] %(message)s"
_configured = False
_CONFIGURE_LOCK = threading.Lock()


def _configure() -> None:
    global _configured
    if not _configured:
        # double-checked: basicConfig is NOT idempotent when two threads
        # race it before the root logger has handlers (duplicate handlers
        # double every log line from then on)
        with _CONFIGURE_LOCK:
            if not _configured:
                logging.basicConfig(level=logging.INFO, format=_FORMAT)
                _configured = True


def get_logger(name: str) -> logging.Logger:
    _configure()
    return logging.getLogger(name)


def kv_line(badge: str, desc: str, **kvs: Any) -> str:
    parts = [f"[{badge}]", desc]
    for k, v in kvs.items():
        parts.append(f"{k}={v}")
    return "|".join(parts)


def metric(logger: logging.Logger, desc: str, **kvs: Any) -> None:
    """Emit a METRIC line (scrapeable, mirrors the reference's mtail contract)."""
    logger.info(kv_line("METRIC", desc, **kvs))


def note_swallowed(site: str, exc: BaseException | None = None) -> None:
    """Observe an intentionally-swallowed error instead of erasing it.

    The except-hygiene analyzer (``fisco_bcos_tpu.analysis``) forbids broad
    handlers whose body does nothing; every tolerated failure routes through
    here so operators can see error *mass* per site even at INFO level:
    a debug log line plus ``fisco_swallowed_errors_total{site=...}``.
    """
    try:
        from .metrics import REGISTRY

        REGISTRY.counter_add(
            f'fisco_swallowed_errors_total{{site="{site}"}}',
            1.0,
            help="errors intentionally swallowed (tolerated), by site",
        )
    # analysis: allow(except-hygiene, the swallow observer itself must never raise)
    except Exception:
        pass
    if exc is not None:
        logging.getLogger("fisco.swallowed").debug(
            "swallowed at %s: %r", site, exc
        )


class StageTimer:
    """Stage-timing helper mirroring the reference's BlockTrace logs
    (e.g. DMCExecute.0..6 in bcos-scheduler BlockExecutive.cpp:849-1010)."""

    def __init__(self, logger: logging.Logger, badge: str):
        self._logger = logger
        self._badge = badge
        self._t0 = time.monotonic()
        self._last = self._t0
        self._stage = 0

    def stage(self, desc: str, **kvs: Any) -> None:
        now = time.monotonic()
        self._logger.info(
            kv_line(
                f"{self._badge}.{self._stage}",
                desc,
                stageMs=round((now - self._last) * 1e3, 3),
                totalMs=round((now - self._t0) * 1e3, 3),
                **kvs,
            )
        )
        self._last = now
        self._stage += 1
