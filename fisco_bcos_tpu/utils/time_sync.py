"""NodeTimeMaintenance — median peer clock-offset tracking.

Reference: bcos-tool/src/NodeTimeMaintenance.cpp: every peer status message
carries the sender's UTC time; the node keeps per-peer offsets, uses the
median as the chain-aligned clock, and logs a warning when local time
drifts beyond the tolerance (the reference's MAX_OFFSET, 30 min) — a
skewed clock makes a node reject honest proposals by timestamp.
"""

from __future__ import annotations

import statistics
import threading
import time

from .log import get_logger

_log = get_logger("time-sync")

MAX_OFFSET_MS = 30 * 60 * 1000  # reference MAX_OFFSET


def utc_ms() -> int:
    return int(time.time() * 1000)


class NodeTimeMaintenance:
    def __init__(self, max_peers: int = 128):
        self._offsets: dict[bytes, int] = {}
        self._lock = threading.Lock()
        self.max_peers = max_peers
        self._warned = False

    def on_peer_time(self, peer: bytes, peer_utc_ms: int) -> None:
        """Record a peer-reported clock sample (NodeTimeMaintenance::
        tryToUpdatePeerTimeInfo)."""
        if peer_utc_ms <= 0:
            return
        offset = peer_utc_ms - utc_ms()
        with self._lock:
            if peer not in self._offsets and len(self._offsets) >= self.max_peers:
                return
            self._offsets[peer] = offset
            median = int(statistics.median(self._offsets.values()))
            # test-and-set under the lock: two samples crossing the
            # threshold together must produce ONE warning, not two
            warn = abs(median) > MAX_OFFSET_MS and not self._warned
            if warn:
                self._warned = True
        if warn:
            _log.warning(
                "local clock is %d ms off the peer median — fix NTP "
                "(consensus timestamps will look invalid to peers)",
                median,
            )

    def remove_peer(self, peer: bytes) -> None:
        with self._lock:
            self._offsets.pop(peer, None)

    def median_offset_ms(self) -> int:
        with self._lock:
            if not self._offsets:
                return 0
            return int(statistics.median(self._offsets.values()))

    def aligned_time_ms(self) -> int:
        """Network-aligned clock (getAlignedTime): local + median offset."""
        return utc_ms() + self.median_offset_ms()
