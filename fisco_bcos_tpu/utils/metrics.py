"""Metrics registry with Prometheus text exposition.

Reference: the reference exports node metrics by tailing METRIC log lines
with mtail into Prometheus (tools/BcosAirBuilder/build_chain.sh:891-946
generates the mtail config, including the 0/50/100/150 ms latency histograms
for block execution and commit at :920-935).  Here the same signals are
first-class: modules register counters/gauges/histograms, and the RPC HTTP
server exposes ``GET /metrics`` in Prometheus text format — no sidecar
required (the mtail-compatible METRIC log lines from utils/log.py remain for
log-based pipelines).

Exposition follows format 0.0.4: ONE ``# HELP``/``# TYPE`` header per metric
family regardless of how many labeled samples it has, escaped help text, and
histogram families rendered as ``_bucket``/``_sum``/``_count``.
"""

from __future__ import annotations

import os
import threading
from typing import Callable

from ..observability.histogram import (
    LATENCY_BUCKETS_MS,
    Histogram,
    escape_help,
)


class MetricsRegistry:
    def __init__(self, enabled: bool = True):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, Callable[[], float] | float] = {}
        self._histograms: dict[str, Histogram] = {}
        self._help: dict[str, str] = {}
        # master switch (observability.set_enabled): when off, every write
        # is a cheap early return — the bench overhead A/B baseline
        self.enabled = enabled

    def counter_add(self, name: str, value: float = 1.0, help: str = "") -> None:
        if not self.enabled:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value
            if help:
                self._help.setdefault(name.split("{")[0], help)

    def gauge_set(self, name: str, value: float, help: str = "") -> None:
        if not self.enabled:
            return
        with self._lock:
            self._gauges[name] = value
            if help:
                self._help.setdefault(name.split("{")[0], help)

    def gauge_fn(self, name: str, fn: Callable[[], float], help: str = "") -> None:
        """Register a pull-time gauge (evaluated at scrape)."""
        if not self.enabled:
            return
        with self._lock:
            self._gauges[name] = fn
            if help:
                self._help.setdefault(name.split("{")[0], help)

    # -- histograms ----------------------------------------------------------

    def histogram(
        self, name: str, buckets=LATENCY_BUCKETS_MS, help: str = ""
    ) -> Histogram:
        """Get-or-create the histogram family `name` (buckets/help only
        apply on first registration)."""
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name, buckets, help)
            return h

    def observe(
        self,
        name: str,
        value: float,
        buckets=LATENCY_BUCKETS_MS,
        help: str = "",
        exemplar: str | None = None,
        **labels,
    ) -> None:
        """One-call histogram observation (labels as kwargs). ``exemplar``
        (a trace-id hex) ties this sample's bucket to a concrete trace in
        the OpenMetrics exemplar rendering."""
        if not self.enabled:
            return
        self.histogram(name, buckets, help).observe(
            value, labels or None, exemplar=exemplar
        )

    def counters_matching(self, base: str) -> dict[str, float]:
        """Snapshot of every counter series whose name starts with ``base``
        (full labeled name -> value) — programmatic artifact access (the
        scenario runner embeds isolation counters in its JSON)."""
        with self._lock:
            return {
                name: v
                for name, v in self._counters.items()
                if name.startswith(base)
            }

    # -- exposition ----------------------------------------------------------

    def render(self, openmetrics: bool = False) -> str:
        """Prometheus text exposition format 0.0.4 — each family's
        ``# HELP``/``# TYPE`` emitted exactly once, help text escaped.

        ``openmetrics=True`` renders the OpenMetrics variant: histogram
        exemplars included and a ``# EOF`` terminator — only served when
        the scraper negotiated ``application/openmetrics-text`` (the 0.0.4
        parser rejects exemplar suffixes)."""
        if self is globals().get("REGISTRY"):
            # pull the tracer's span-drop tallies in at scrape time so a
            # /metrics-only consumer still sees ring-evict/sampling drops
            try:
                from ..observability.tracer import TRACER

                TRACER.flush_drop_metrics()
            except Exception as e:
                from .log import note_swallowed

                note_swallowed("metrics.flush_drops", e)
        lines: list[str] = []
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = list(self._histograms.values())
            helps = dict(self._help)

        def emit_family(samples: dict[str, float], mtype: str) -> None:
            by_base: dict[str, list[str]] = {}
            for name in samples:
                by_base.setdefault(name.split("{")[0], []).append(name)
            for base in sorted(by_base):
                # OpenMetrics names the counter FAMILY without the _total
                # suffix (samples keep it); a strict parser rejects a TYPE
                # line whose name ends in _total
                family = base
                if (
                    openmetrics
                    and mtype == "counter"
                    and family.endswith("_total")
                ):
                    family = family[: -len("_total")]
                if base in helps:
                    lines.append(f"# HELP {family} {escape_help(helps[base])}")
                lines.append(f"# TYPE {family} {mtype}")
                for name in sorted(by_base[base]):
                    lines.append(f"{name} {samples[name]:g}")

        emit_family(counters, "counter")
        gauge_vals: dict[str, float] = {}
        for name, val in gauges.items():
            if callable(val):
                try:
                    val = float(val())
                except Exception as e:
                    # a broken pull-gauge drops its sample, not the scrape
                    from .log import note_swallowed

                    note_swallowed("metrics.gauge_eval", e)
                    continue
            gauge_vals[name] = val
        emit_family(gauge_vals, "gauge")
        for h in sorted(histograms, key=lambda h: h.name):
            h.render_into(lines, with_exemplars=openmetrics)
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"


# process-wide default registry (modules import and use directly);
# FISCO_TELEMETRY=0 starts it disabled (observability.set_enabled flips it)
REGISTRY = MetricsRegistry(enabled=os.environ.get("FISCO_TELEMETRY", "1") != "0")


def bind_node_metrics(node, registry: MetricsRegistry | None = None) -> MetricsRegistry:
    """Register the standard node gauges (block height, pool size, view —
    the signals the reference's mtail config extracts) and pre-register the
    mtail-contract block latency histograms so an idle node's scrape already
    shows the families."""
    reg = registry or REGISTRY
    reg.gauge_fn(
        "fisco_block_number", lambda: float(node.block_number()),
        help="committed chain head",
    )
    reg.gauge_fn(
        "fisco_txpool_pending", lambda: float(node.txpool.pending_count()),
        help="pending pool transactions",
    )
    reg.gauge_fn(
        "fisco_pbft_view", lambda: float(node.engine.view), help="current PBFT view"
    )
    reg.gauge_fn(
        "fisco_committee_size",
        lambda: float(node.pbft_config.committee_size),
        help="consensus committee size",
    )
    # the two mtail-bucket histograms (build_chain.sh:920-935); the
    # scheduler observes into the SAME process REGISTRY families
    reg.histogram(
        "fisco_block_execute_latency_ms",
        help="block execution wall latency (mtail block-exec buckets)",
    )
    reg.histogram(
        "fisco_block_commit_latency_ms",
        help="block commit wall latency (mtail block-commit buckets)",
    )
    return reg
