"""Metrics registry with Prometheus text exposition.

Reference: the reference exports node metrics by tailing METRIC log lines
with mtail into Prometheus (tools/BcosAirBuilder/build_chain.sh:891-946
generates the mtail config).  Here the same signals are first-class: modules
register counters/gauges, and the RPC HTTP server exposes ``GET /metrics``
in Prometheus text format — no sidecar required (the mtail-compatible METRIC
log lines from utils/log.py remain for log-based pipelines).
"""

from __future__ import annotations

import threading
from typing import Callable


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, Callable[[], float] | float] = {}
        self._help: dict[str, str] = {}

    def counter_add(self, name: str, value: float = 1.0, help: str = "") -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value
            if help:
                self._help.setdefault(name, help)

    def gauge_set(self, name: str, value: float, help: str = "") -> None:
        with self._lock:
            self._gauges[name] = value
            if help:
                self._help.setdefault(name, help)

    def gauge_fn(self, name: str, fn: Callable[[], float], help: str = "") -> None:
        """Register a pull-time gauge (evaluated at scrape)."""
        with self._lock:
            self._gauges[name] = fn
            if help:
                self._help.setdefault(name, help)

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: list[str] = []
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            helps = dict(self._help)
        for name, val in sorted(counters.items()):
            base = name.split("{")[0]
            if base in helps:
                lines.append(f"# HELP {base} {helps[base]}")
            lines.append(f"# TYPE {base} counter")
            lines.append(f"{name} {val:g}")
        for name, val in sorted(gauges.items()):
            base = name.split("{")[0]
            if callable(val):
                try:
                    val = float(val())
                except Exception:
                    continue
            if base in helps:
                lines.append(f"# HELP {base} {helps[base]}")
            lines.append(f"# TYPE {base} gauge")
            lines.append(f"{name} {val:g}")
        return "\n".join(lines) + "\n"


# process-wide default registry (modules import and use directly)
REGISTRY = MetricsRegistry()


def bind_node_metrics(node, registry: MetricsRegistry | None = None) -> MetricsRegistry:
    """Register the standard node gauges (block height, pool size, view —
    the signals the reference's mtail config extracts)."""
    reg = registry or REGISTRY
    reg.gauge_fn(
        "fisco_block_number", lambda: float(node.block_number()),
        help="committed chain head",
    )
    reg.gauge_fn(
        "fisco_txpool_pending", lambda: float(node.txpool.pending_count()),
        help="pending pool transactions",
    )
    reg.gauge_fn(
        "fisco_pbft_view", lambda: float(node.engine.view), help="current PBFT view"
    )
    reg.gauge_fn(
        "fisco_committee_size",
        lambda: float(node.pbft_config.committee_size),
        help="consensus committee size",
    )
    return reg
