"""Scenario lab — seed-deterministic adversarial + multi-tenant workloads.

ROADMAP's "Scenario lab" item: every bench run so far was one well-behaved
flood on a single group, so the fault-injection (resilience/faults.py),
tracing and isolation layers had never met the traffic a
millions-of-users deployment actually sees. This package generates that
traffic as composable, *bit-deterministic* workload primitives
(:mod:`workloads`), names canned compositions (:data:`SCENARIOS`) and
drives them against a live multi-group chain (:mod:`runner`) emitting a
per-group TPS/latency artifact through the same telemetry machinery the
bench uses (``bench.py --scenario <name>``).

Seed contract: ``scenario.events(seed)`` is a pure function of
``(scenario, seed)`` — same seed, same byte-identical transaction/event
sequence (:meth:`Scenario.digest` proves it; tool/check_scenarios.py and
tests/test_scenarios.py assert it). All randomness flows from
``random.Random`` instances derived from the seed; signatures are RFC6979
deterministic; nothing reads clocks or global RNGs during generation.
"""

from .base import (
    SCENARIOS,
    Scenario,
    SubmitTxs,
    WorkloadContext,
    get_scenario,
    list_scenarios,
)
from .big_committee import run_big_committee_bench
from .byzantine import (
    ATTACK_NAMES,
    ByzantineHarness,
    ByzantineReplica,
    run_byzantine_bench,
    run_byzantine_scenario,
)
from .proof_storm import run_proof_storm_bench
from .runner import ScenarioRunner, run_isolation_bench
from .wire import (
    WireHarness,
    run_wire_bench,
    run_wire_catalog,
    run_wire_colluders,
    run_wire_partition,
)

__all__ = [
    "ATTACK_NAMES",
    "ByzantineHarness",
    "ByzantineReplica",
    "SCENARIOS",
    "Scenario",
    "ScenarioRunner",
    "SubmitTxs",
    "WireHarness",
    "WorkloadContext",
    "get_scenario",
    "list_scenarios",
    "run_big_committee_bench",
    "run_byzantine_bench",
    "run_byzantine_scenario",
    "run_isolation_bench",
    "run_proof_storm_bench",
    "run_wire_bench",
    "run_wire_catalog",
    "run_wire_colluders",
    "run_wire_partition",
]
