"""Byzantine consensus-message scenarios — one adversary inside a live
f=1-tolerant committee (ISSUE 15 tentpole).

The :class:`ByzantineReplica` owns a REAL committee member's keypair and
front (it is indistinguishable from an honest replica on the wire) and
drives the real PBFT engine handlers of its peers over the scenario
runner's mesh topology — per-host :class:`~fisco_bcos_tpu.gateway.group.
GroupGateway` muxes on one :class:`~fisco_bcos_tpu.front.InprocGateway`
transport, queued (``auto=False``) so each attack's frame interleaving is
seed-deterministic. The catalog covers the cheap attacks 2302.00418's
committee-vote model and ByzCoin's equivocation analysis name:

- ``equivocation`` — two signed pre-prepares at one (number, view);
- ``stale_view_replay`` — the adversary's recorded frames re-injected
  after the committee moved to a newer view;
- ``vote_conflict`` — two different PREPARE votes from one signer;
- ``fabricated_prepared_cert`` — a view-change carrying a prepared claim
  whose "proof" is one self-signed PREPARE (no quorum);
- ``forged_qc_vote`` — a vote with a garbage QC signature under the
  adversary's own identity PLUS a vote forged under a victim's index.

Every attack must be *detected* (``fisco_consensus_evidence_total{kind}``
and the :data:`~fisco_bcos_tpu.consensus.audit.EVIDENCE` board), the
attacker demoted through the existing strike/quota board, and the honest
committee must keep committing — the
:func:`~fisco_bcos_tpu.consensus.audit.audit_chain` safety auditor is the
final gate of every run. ``run_byzantine_bench`` measures the liveness
cost: honest commit throughput under attack vs. a clean flood of the same
shape (the bench gate accepts ≥0.5x).
"""

from __future__ import annotations

import time

from ..consensus.audit import EVIDENCE, audit_chain, validator_source
from ..consensus.messages import PacketType, PBFTMessage, ViewChangePayload
from ..front.front import ModuleID
from ..protocol.block import Block
from ..protocol.block_header import BlockHeader, ParentInfo
from ..utils.log import get_logger
from .base import WorkloadContext

_log = get_logger("byzantine")

ATTACK_NAMES = (
    "equivocation",
    "stale_view_replay",
    "vote_conflict",
    "fabricated_prepared_cert",
    "forged_qc_vote",
)

# attack -> evidence kinds its detection must raise
ATTACK_EVIDENCE = {
    "equivocation": ("equivocation",),
    "stale_view_replay": ("stale_view_replay",),
    "vote_conflict": ("vote_conflict",),
    "fabricated_prepared_cert": ("fabricated_prepared_cert",),
    "forged_qc_vote": ("bad_qc_vote", "forged_qc_vote"),
}


class ByzantineReplica:
    """The adversary: a legitimate committee member that crafts and signs
    arbitrary consensus frames. Everything it sends authenticates — that
    is the threat model; garbage from a non-member dies at the signature
    check and needs no catalog."""

    def __init__(self, node):
        self.node = node
        self.cfg = node.pbft_config
        self.suite = node.suite
        self.keypair = node.keypair
        self.recorded: list[bytes] = []  # frames kept for replay attacks

    @property
    def index(self) -> int:
        return self.cfg.my_index

    def sign(self, msg: PBFTMessage) -> PBFTMessage:
        msg.generated_from = self.index
        msg.sign(self.suite, self.keypair)
        return msg

    def broadcast(self, msg_or_frame, record: bool = False) -> None:
        frame = (
            msg_or_frame
            if isinstance(msg_or_frame, (bytes, bytearray))
            else msg_or_frame.encode()
        )
        if record:
            self.recorded.append(bytes(frame))
        self.node.front.broadcast(ModuleID.PBFT, bytes(frame))

    def craft_block(self, number: int, parent_hash: bytes, salt: int) -> Block:
        """A well-formed empty proposal at `number` — passes every
        verification gate (no txs to check), distinct per ``salt``."""
        header = BlockHeader(
            version=1,
            number=number,
            parent_info=[ParentInfo(number - 1, parent_hash)],
            timestamp=1_700_000_000_000 + salt,  # deterministic, distinct
            sealer=self.index,
            sealer_list=[n.node_id for n in self.cfg.nodes],
            consensus_weights=[n.weight for n in self.cfg.nodes],
        )
        return Block(header=header, tx_metadata=[])


class ByzantineHarness:
    """One n-host committee on the queued in-proc mesh, one adversary.

    The drive loop is the scenario runner's: submit at the leader, gossip,
    seal, drain the queue — every delivery explicit so attack frames can
    be interleaved at exact points.
    """

    def __init__(
        self,
        seed: int = 0,
        hosts: int = 4,
        with_qc: bool = True,
        block_cap: int = 2000,
        group: str = "group0",
    ):
        from ..crypto.suite import ecdsa_suite
        from ..front import InprocGateway
        from ..gateway.group import GroupGateway
        from ..ledger import ConsensusNode, GenesisConfig
        from ..node import Node, NodeConfig

        self.seed = int(seed)
        self.group = group
        suite = ecdsa_suite()
        secrets = [0xB12A_0000 + seed * 101 + i for i in range(hosts)]
        keypairs = [
            suite.signature_impl.generate_keypair(secret=s) for s in secrets
        ]
        committee = []
        for i, kp in enumerate(keypairs):
            qc_pub = b""
            if with_qc:
                from ..consensus.qc import qc_pub_for

                qc_pub = qc_pub_for(secrets[i])
            committee.append(ConsensusNode(kp.pub, weight=1, qc_pub=qc_pub))
        self.transport = InprocGateway(auto=False)
        self.nodes = []
        self._muxes: dict[bytes, GroupGateway] = {}
        for kp in keypairs:
            mux = GroupGateway(kp.pub)
            self.transport.connect(mux)
            self._muxes[kp.pub] = mux
            cfg = NodeConfig(
                group_id=group,
                genesis=GenesisConfig(
                    group_id=group,
                    consensus_nodes=list(committee),
                    tx_count_limit=block_cap,
                ),
            )
            self.nodes.append(Node(cfg, keypair=kp, front=mux.register_group(group)))
        # the adversary: committee index seed % n — stable under the
        # sorted-committee reordering because we select BY index
        self.adv_index = self.seed % hosts
        self.adversary = ByzantineReplica(self._node_at(self.adv_index))
        self.honest = [n for n in self.nodes if n is not self.adversary.node]
        self.ctx = WorkloadContext(suite=suite)
        self._nonce = 0

    # -- plumbing -------------------------------------------------------------

    def _node_at(self, index: int):
        return next(
            n for n in self.nodes if n.pbft_config.my_index == index
        )

    def silence(self, node) -> None:
        """Cut one node off the mesh (its GroupGateway mux, NOT its group
        front — reconnecting the front would replace the group facade and
        wedge the node's framing)."""
        self.transport.disconnect(node.node_id)

    def rejoin(self, node) -> None:
        """Undo :meth:`silence` — reconnects the node's original mux so
        the group envelope wiring survives the round trip."""
        self.transport.connect(self._muxes[node.node_id])

    def deliver(self) -> int:
        return self.transport.deliver_all(max_rounds=200)

    def reconcile(self) -> None:
        """Bring stragglers back before the next honest round. The
        adversary's own node is the usual laggard — it never receives the
        broadcasts it sends, so an attack at its own leader height leaves
        it behind by a block and (after view changes) behind in view;
        block sync recovers the chain, the recover round recovers the
        view (a lagging node rejects NEW_VIEW from what it computes as a
        wrong leader — by design — and must ask the committee instead)."""
        for _ in range(5):
            if len({n.block_number() for n in self.nodes}) == 1:
                break
            for n in self.nodes:
                n.block_sync.maintain()
            self.deliver()
        top_view = max(n.engine.view for n in self.honest)
        for n in self.nodes:
            if n.engine.view < top_view:
                n.engine.request_recover()
        self.deliver()

    def view(self) -> int:
        return self.honest[0].engine.view

    def height(self) -> int:
        return max(n.block_number() for n in self.honest)

    def leader_for(self, number: int):
        idx = self.honest[0].pbft_config.leader_index(number, self.view())
        return self._node_at(idx)

    def mint_txs(self, n: int) -> list:
        txs = []
        for _ in range(n):
            self._nonce += 1
            txs.append(
                self.ctx.signed_tx(
                    0xFEED + (self._nonce % 7),
                    self.group,
                    f"byz-{self.seed}-{self._nonce}",
                    b"\x10" * 20,
                    b"",
                )
            )
        return txs

    def commit_block(self, n_txs: int = 4) -> bool:
        """One honest round: submit at the leader, gossip, seal, drain."""
        self.reconcile()
        number = self.height() + 1
        leader = self.leader_for(number)
        txs = self.mint_txs(n_txs)
        results = leader.txpool.submit_batch(txs)
        if any(r.status != 0 for r in results):
            return False
        leader.tx_sync.maintain()
        self.deliver()  # gossip lands before the proposal references it
        ok = leader.sealer.seal_and_submit()
        self.deliver()
        return ok and self.height() >= number

    def commit_until_leader(self, index: int, max_blocks: int = 8) -> int:
        """Advance the chain until `index` leads the next height."""
        cfg = self.honest[0].pbft_config
        for _ in range(max_blocks):
            number = self.height() + 1
            if cfg.leader_index(number, self.view()) == index:
                return number
            if not self.commit_block():
                break
        number = self.height() + 1
        if cfg.leader_index(number, self.view()) != index:
            raise RuntimeError(f"could not rotate leadership to {index}")
        return number

    def in_flight_proposal(self) -> tuple[int, bytes]:
        """Seal (but do not drain) the next honest proposal; returns
        (number, proposal_hash) with the pre-prepare still queued —
        the window vote attacks inject into."""
        self.reconcile()
        number = self.height() + 1
        leader = self.leader_for(number)
        txs = self.mint_txs(3)
        results = leader.txpool.submit_batch(txs)
        assert all(r.status == 0 for r in results)
        leader.tx_sync.maintain()
        self.deliver()
        assert leader.sealer.seal_and_submit()
        cache = leader.engine._caches.get(number)
        assert cache is not None and cache.pre_prepare is not None
        return number, cache.pre_prepare.proposal_hash

    # -- the attack catalog ---------------------------------------------------

    def attack_equivocation(self) -> None:
        """Two signed pre-prepares at one (number, view), as the leader."""
        adv = self.adversary
        number = self.commit_until_leader(adv.index)
        parent = self.honest[0].ledger.block_hash_by_number(number - 1) or b""
        view = self.view()
        frames = []
        for salt in (1, 2):
            block = adv.craft_block(number, parent, salt)
            msg = PBFTMessage(
                packet_type=PacketType.PRE_PREPARE,
                view=view,
                number=number,
                proposal_hash=block.header.hash(adv.suite),
                proposal_data=block.encode(),
            )
            frames.append(adv.sign(msg))
        adv.broadcast(frames[0])  # the one the committee will commit
        adv.broadcast(frames[1])  # the equivocation
        self.deliver()

    def attack_stale_view_replay(self) -> None:
        """Record frames at the current view, force a view change, replay
        them — the replayer (transport peer) is charged, not the frames'
        signer."""
        adv = self.adversary
        number = self.height() + 1
        view = self.view()
        vote = PBFTMessage(
            packet_type=PacketType.PREPARE,
            view=view,
            number=number,
            proposal_hash=b"\x5a" * 32,
        )
        adv.sign(vote)
        adv.broadcast(vote, record=True)
        self.deliver()
        # the committee times out and moves on (quorum of honest VCs)
        for n in self.honest:
            n.engine.on_timeout()
        self.deliver()
        assert self.view() > view, "view change did not complete"
        # re-inject the recorded pre-view-change frames
        for frame in self.adversary.recorded:
            adv.broadcast(frame)
        self.deliver()

    def attack_vote_conflict(self) -> None:
        """Vote twice — different hashes — at one (number, view): the
        fake vote lands first, the genuine one (same signer) conflicts
        with it at every honest receiver."""
        adv = self.adversary
        number, real_hash = self.in_flight_proposal()
        view = self.view()
        fake = adv.sign(
            PBFTMessage(
                packet_type=PacketType.PREPARE,
                view=view,
                number=number,
                proposal_hash=b"\xfa" * 32,
            )
        )
        genuine = adv.sign(
            PBFTMessage(
                packet_type=PacketType.PREPARE,
                view=view,
                number=number,
                proposal_hash=real_hash,
            )
        )
        adv.broadcast(fake)
        adv.broadcast(genuine)
        self.deliver()

    def attack_fabricated_prepared_cert(self) -> None:
        """Claim a prepared proposal in view change with a one-vote
        'proof' — steering the new view onto an unprepared block."""
        adv = self.adversary
        # the fabricated VC is judged by the NEW view's leader (and then
        # by every replica via its NEW_VIEW proof set); a node never
        # receives its own broadcasts, so advance the chain until that
        # leader is honest
        cfg = self.honest[0].pbft_config
        while cfg.leader_index(self.height() + 1, self.view() + 1) == adv.index:
            assert self.commit_block()
        number = self.height() + 1
        view = self.view()
        parent = self.honest[0].ledger.block_hash_by_number(number - 1) or b""
        fake_block = adv.craft_block(number, parent, 77)
        fake_hash = fake_block.header.hash(adv.suite)
        lone_prepare = adv.sign(
            PBFTMessage(
                packet_type=PacketType.PREPARE,
                view=view,
                number=number,
                proposal_hash=fake_hash,
            )
        )
        vc = PBFTMessage(
            packet_type=PacketType.VIEW_CHANGE,
            view=view + 1,
            number=self.honest[0].engine.committed_number,
            payload=ViewChangePayload(
                committed_number=self.honest[0].engine.committed_number,
                prepared_view=view,
                prepared_proposal=fake_block.encode(),
                prepare_proof=[lone_prepare.encode()],
            ).encode(),
        )
        adv.sign(vc)
        adv.broadcast(vc)  # queued ahead of the honest view changes
        for n in self.honest:
            n.engine.on_timeout()
        self.deliver()
        assert self.view() > view, "view change did not complete"

    def attack_forged_qc_vote(self) -> None:
        """Two QC-vote abuses while a proposal is mid-vote: a garbage QC
        signature under the adversary's own (authenticated) identity, and
        a vote forged under a victim's index. The first must strike the
        adversary; the second must be dropped WITHOUT striking the
        victim."""
        adv = self.adversary
        number, real_hash = self.in_flight_proposal()
        view = self.view()
        bad = PBFTMessage(
            packet_type=PacketType.PREPARE,
            view=view,
            number=number,
            proposal_hash=real_hash,
        )
        adv.sign(bad)
        bad.qc_sig = b"\x66" * 64  # authenticated packet, garbage QC vote
        adv.broadcast(bad)
        victim_idx = next(
            i
            for i in range(len(adv.cfg.nodes))
            if i != adv.index
        )
        forged = PBFTMessage(
            packet_type=PacketType.PREPARE,
            view=view,
            number=number,
            proposal_hash=real_hash,
        )
        forged.generated_from = victim_idx
        forged.signature = b"\x13" * adv.suite.signature_impl.sig_len
        forged.qc_sig = b"\x37" * 64
        adv.broadcast(forged)
        self.deliver()

    def run_attack(self, name: str) -> dict:
        """Execute one cataloged attack; returns the detection delta."""
        if name not in ATTACK_NAMES:
            raise ValueError(f"unknown attack {name!r} (known: {ATTACK_NAMES})")
        before = EVIDENCE.counts()
        getattr(self, f"attack_{name}")()
        after = EVIDENCE.counts()
        delta = {
            k: after.get(k, 0) - before.get(k, 0)
            for k in ATTACK_EVIDENCE[name]
        }
        return {
            "attack": name,
            "evidence_delta": delta,
            "detected": all(v > 0 for v in delta.values()),
        }

    # -- verdicts -------------------------------------------------------------

    def adversary_source(self) -> str:
        return validator_source(self.adversary.node.node_id)

    def adversary_demoted(self) -> bool:
        from ..consensus.audit import EVIDENCE_GROUP
        from ..txpool.quota import get_quotas

        return get_quotas().demoted(EVIDENCE_GROUP, self.adversary_source())

    def audit(self, prior_views=None) -> dict:
        # the adversary's NODE runs honest engine code — its committed
        # chain is audited too (it may simply be shorter)
        return audit_chain(self.nodes, prior_views=prior_views)

    def catch_up(self) -> None:
        """Final convergence before the audit (alias of reconcile)."""
        self.reconcile()


def run_byzantine_scenario(
    seed: int = 0,
    scale: float = 1.0,
    attacks=ATTACK_NAMES,
    hosts: int = 4,
    deadline_s: float | None = None,
) -> dict:
    """The full catalog against one committee, honest blocks interleaved
    between attacks; returns the artifact dict (per-attack detection,
    evidence counts, demotion, audit report, liveness)."""
    from ..resilience import HEALTH
    from ..txpool.quota import get_quotas

    get_quotas().reset()
    HEALTH.reset()
    EVIDENCE.reset()
    deadline = (
        time.perf_counter() + deadline_s if deadline_s is not None else None
    )
    h = ByzantineHarness(seed=seed, hosts=hosts)
    # a couple of clean blocks first: evidence must start at zero on a
    # healthy chain (the byzantine-off passthrough the criteria pin)
    for _ in range(2):
        h.commit_block(max(1, int(4 * scale)))
    assert EVIDENCE.count() == 0, "clean blocks raised evidence"
    results = []
    t0 = time.perf_counter()
    h0 = h.height()
    for name in attacks:
        results.append(h.run_attack(name))
        h.commit_block(max(1, int(4 * scale)))  # honest progress after each
        if deadline is not None and time.perf_counter() > deadline:
            break
    dt = time.perf_counter() - t0
    h.catch_up()
    audit = h.audit()
    quotas = get_quotas()
    doc = {
        "scenario": "byzantine",
        "seed": seed,
        "scale": scale,
        "adversary_index": h.adv_index,
        "attacks": results,
        # same vacuous-truth guard as run_byzantine_bench: a deadline-
        # truncated run must not claim the whole requested catalog passed
        "all_detected": (
            len(results) == len(attacks)
            and all(r["detected"] for r in results)
        ),
        "evidence_counts": EVIDENCE.counts(),
        "evidence": EVIDENCE.snapshot()[-32:],
        "adversary_demoted": h.adversary_demoted(),
        "quotas": quotas.snapshot(),
        "honest_height": h.height(),
        "blocks_during_attacks": h.height() - h0,
        "attack_window_s": round(dt, 3),
        "audit": audit,
    }
    return doc


def _flood_leg(
    h: ByzantineHarness,
    n_blocks: int,
    txs_per_block: int,
    deadline: float | None = None,
) -> float:
    """Commit up to `n_blocks` honest blocks (stopping at `deadline`, a
    perf_counter stamp); returns committed tx/s (measured as the honest
    ledger's total-tx delta — blocks committed while rotating leadership
    inside an attack count too)."""
    ledger = h.honest[0].ledger
    t0 = time.perf_counter()
    before = ledger.total_transaction_count()
    for _ in range(n_blocks):
        h.commit_block(txs_per_block)
        if deadline is not None and time.perf_counter() > deadline:
            break
    dt = time.perf_counter() - t0
    committed = ledger.total_transaction_count() - before
    return committed / dt if dt > 0 else 0.0


def run_byzantine_bench(
    seed: int = 0,
    scale: float = 1.0,
    deadline_s: float | None = None,
    hosts: int = 4,
) -> dict:
    """The acceptance bench: a clean flood leg, then the same flood with
    the whole attack catalog interleaved — the honest commit rate under
    attack must hold ≥0.5x clean, every attack must be detected, the
    adversary demoted, and the safety auditor green on both legs.

    Never raises: like the sibling scenario benches, a harness failure
    comes back as ``doc["error"]`` (full metric shape, failing values) so
    the bench round still emits error-annotated metric lines instead of
    silently dropping the scenario."""
    try:
        return _run_byzantine_bench(seed, scale, deadline_s, hosts)
    except Exception as e:  # noqa: BLE001 — reported through the artifact
        _log.exception("byzantine bench failed")
        bad_audit = {"ok": False, "violations": [f"bench error: {e}"]}
        return {
            "scenario": "byzantine-bench",
            "seed": seed,
            "scale": scale,
            "error": str(e),
            "clean_tps": 0.0,
            "byzantine_tps": 0.0,
            "liveness_ratio": 0.0,
            "attacks": [],
            "all_detected": False,
            "adversary_demoted": False,
            "evidence_counts": EVIDENCE.counts(),
            "audit_clean": bad_audit,
            "audit_byzantine": bad_audit,
        }


def _run_byzantine_bench(
    seed: int, scale: float, deadline_s: float | None, hosts: int
) -> dict:
    from ..resilience import HEALTH
    from ..txpool.quota import get_quotas

    n_blocks = max(2, int(6 * scale))
    txs = max(2, int(16 * scale))
    # both legs' budgets anchored at ENTRY: the clean leg gets half the
    # child budget and the attacked leg the rest — a slow host truncates
    # block counts rather than eating the bench round's emit reserve
    t_entry = time.perf_counter()
    clean_deadline = (
        t_entry + deadline_s / 2 if deadline_s is not None else None
    )

    get_quotas().reset()
    HEALTH.reset()
    EVIDENCE.reset()
    clean = ByzantineHarness(seed=seed, hosts=hosts)
    clean_tps = _flood_leg(clean, n_blocks, txs, deadline=clean_deadline)
    clean_audit = clean.audit()
    assert EVIDENCE.count() == 0, "clean flood raised evidence"

    get_quotas().reset()
    HEALTH.reset()
    byz = ByzantineHarness(seed=seed, hosts=hosts)
    deadline = (
        t_entry + deadline_s if deadline_s is not None else None
    )
    attack_results = []
    ledger = byz.honest[0].ledger
    t0 = time.perf_counter()
    before = ledger.total_transaction_count()
    blocks_done = 0
    for i in range(n_blocks):
        if i < len(ATTACK_NAMES):
            attack_results.append(byz.run_attack(ATTACK_NAMES[i]))
        byz.commit_block(txs)
        blocks_done += 1
        if deadline is not None and time.perf_counter() > deadline:
            break
    # any cataloged attack the block budget didn't reach yet runs now —
    # still under the deadline: the child must not eat the bench round's
    # emit reserve (partial catalogs report honestly as fewer attacks)
    for name in ATTACK_NAMES[blocks_done:]:
        if deadline is not None and time.perf_counter() > deadline:
            break
        attack_results.append(byz.run_attack(name))
        byz.commit_block(txs)
    dt = time.perf_counter() - t0
    byz_tps = (ledger.total_transaction_count() - before) / dt if dt > 0 else 0.0
    byz.catch_up()
    byz_audit = byz.audit()
    ratio = byz_tps / clean_tps if clean_tps > 0 else 0.0
    return {
        "scenario": "byzantine-bench",
        "seed": seed,
        "scale": scale,
        "clean_tps": round(clean_tps, 2),
        "byzantine_tps": round(byz_tps, 2),
        "liveness_ratio": round(ratio, 3),
        "attacks": attack_results,
        # a deadline-truncated catalog must NOT pass vacuously: all means
        # every cataloged attack ran AND was detected
        "all_detected": (
            len(attack_results) == len(ATTACK_NAMES)
            and all(r["detected"] for r in attack_results)
        ),
        "adversary_demoted": byz.adversary_demoted(),
        "evidence_counts": EVIDENCE.counts(),
        "audit_clean": clean_audit,
        "audit_byzantine": byz_audit,
    }
