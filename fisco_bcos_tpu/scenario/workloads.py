"""Workload primitives + the canned scenario catalog.

Each primitive is a generator of :class:`~.base.SubmitTxs` batches, pure
in ``(ctx, rng, ...)`` — all randomness comes from the passed
``random.Random``, all signing is RFC6979-deterministic, so the emitted
transaction bytes replay exactly for a given seed (the scenario lab's
core contract).

The catalog at the bottom names the compositions the issue calls for:
invalid-signature storms, mempool churn with duplicate/replacement spam,
hot-contract contention floods (the DMC/key-lock worst case), cross-group
traffic, sync storms from lagging peers, and the two-tenant ``isolation``
scenario the acceptance bench runs (abuser + victim on one node).
"""

from __future__ import annotations

import random
from typing import Iterator

from ..executor.precompiled import DAG_TRANSFER_ADDRESS
from .base import Scenario, SubmitTxs, WorkloadContext, register

# distinct deterministic key spaces per role so primitives never collide
_SECRET_FLOOD = 0x51E9A
_SECRET_HOT = 0x68C7
_SECRET_CHURN = 0xC4A12
_SECRET_SYNC = 0x57AC


def _add_call(ctx: WorkloadContext, user: str, amount: int = 100) -> bytes:
    return ctx.codec.encode_call("userAdd(string,uint256)", user, amount)


def _transfer_call(ctx: WorkloadContext, a: str, b: str, amount: int) -> bytes:
    return ctx.codec.encode_call("userTransfer(string,string,uint256)", a, b, amount)


def valid_flood(
    ctx: WorkloadContext,
    rng: random.Random,
    group: str,
    n: int,
    batch: int = 64,
    tag: str = "flood",
    source: str = "local",
    lane: str = "admission",
) -> Iterator[SubmitTxs]:
    """The standard well-behaved load: unique userAdd parallel-transfer txs
    (what every bench round ran until now — here it is the *victim* traffic
    the adversarial primitives run against)."""
    txs = []
    for i in range(n):
        txs.append(
            ctx.signed_tx(
                _SECRET_FLOOD,
                group,
                nonce=f"{tag}-{group}-{i}",
                to=DAG_TRANSFER_ADDRESS,
                input=_add_call(ctx, f"u-{tag}-{group}-{i}", 1 + rng.randrange(100)),
            )
        )
        if len(txs) >= batch:
            yield SubmitTxs(group, txs, source=source, lane=lane)
            txs = []
    if txs:
        yield SubmitTxs(group, txs, source=source, lane=lane)


def invalid_sig_storm(
    ctx: WorkloadContext,
    rng: random.Random,
    group: str,
    n: int,
    batch: int = 64,
    tag: str = "storm",
    source: str = "spammer",
) -> Iterator[SubmitTxs]:
    """Statically-admissible txs with seeded-garbage signatures: the
    worst-case admission spam (every tx reaches the device verify unless
    quotas/strike demotion shed the source first)."""
    txs = []
    for i in range(n):
        txs.append(
            ctx.garbage_sig_tx(
                rng,
                group,
                nonce=f"{tag}-bad-{group}-{i}",
                to=DAG_TRANSFER_ADDRESS,
                input=_add_call(ctx, f"x-{tag}-{i}"),
            )
        )
        if len(txs) >= batch:
            yield SubmitTxs(group, txs, source=source)
            txs = []
    if txs:
        yield SubmitTxs(group, txs, source=source)


def mempool_churn(
    ctx: WorkloadContext,
    rng: random.Random,
    group: str,
    n: int,
    batch: int = 32,
    tag: str = "churn",
    source: str = "churner",
) -> Iterator[SubmitTxs]:
    """Duplicate/replacement spam: every unique tx is re-submitted
    ``1..3`` extra times (exact duplicates → ``ALREADY_IN_TX_POOL``) and
    interleaved with *nonce-replacement* attempts — a different payload
    under an already-pooled nonce, which the pool must also refuse (the
    reference's nonce checkers; accepting it would let spam evict paid
    traffic). The pool's dup gates absorb all of it without device work."""
    txs: list = []
    for i in range(n):
        nonce = f"{tag}-{group}-{i}"
        tx = ctx.signed_tx(
            _SECRET_CHURN, group, nonce=nonce,
            to=DAG_TRANSFER_ADDRESS, input=_add_call(ctx, f"c-{tag}-{i}"),
        )
        txs.append(tx)
        for _dup in range(1 + rng.randrange(3)):
            txs.append(tx)  # exact duplicate object: same bytes on the wire
        # replacement spam: same nonce, different input
        txs.append(
            ctx.signed_tx(
                _SECRET_CHURN, group, nonce=nonce,
                to=DAG_TRANSFER_ADDRESS,
                input=_add_call(ctx, f"c-{tag}-{i}-replaced", 7),
            )
        )
        if len(txs) >= batch:
            yield SubmitTxs(group, txs, source=source)
            txs = []
    if txs:
        yield SubmitTxs(group, txs, source=source)


def hot_contract_flood(
    ctx: WorkloadContext,
    rng: random.Random,
    group: str,
    n: int,
    batch: int = 64,
    hot_users: int = 4,
    tag: str = "hot",
    source: str = "local",
) -> Iterator[SubmitTxs]:
    """The DMC/key-lock worst case: after seeding a tiny user set, every
    transfer touches the same few storage keys, so parallel execution
    degenerates to serialized key-lock rounds — the contention profile the
    DAG/DMC executor has to survive, generated on demand."""
    users = [f"hot-{tag}-{u}" for u in range(hot_users)]
    setup = [
        ctx.signed_tx(
            _SECRET_HOT, group, nonce=f"{tag}-seed-{group}-{u}",
            to=DAG_TRANSFER_ADDRESS, input=_add_call(ctx, users[u], 1_000_000),
        )
        for u in range(hot_users)
    ]
    yield SubmitTxs(group, setup, source=source)
    txs = []
    for i in range(n):
        a = rng.randrange(hot_users)
        b = (a + 1 + rng.randrange(hot_users - 1)) % hot_users if hot_users > 1 else a
        txs.append(
            ctx.signed_tx(
                _SECRET_HOT, group, nonce=f"{tag}-{group}-{i}",
                to=DAG_TRANSFER_ADDRESS,
                input=_transfer_call(ctx, users[a], users[b], 1),
            )
        )
        if len(txs) >= batch:
            yield SubmitTxs(group, txs, source=source)
            txs = []
    if txs:
        yield SubmitTxs(group, txs, source=source)


def sync_storm(
    ctx: WorkloadContext,
    rng: random.Random,
    group: str,
    n: int,
    batch: int = 48,
    peers: int = 3,
    tag: str = "sync",
) -> Iterator[SubmitTxs]:
    """Lagging peers flushing their backlogs at once: valid txs arriving on
    the plane's lowest-priority *sync* lane from several peer sources —
    composed with a delay fault plan this reproduces the gossip burst that
    follows a partition healing."""
    txs = []
    peer = 0
    for i in range(n):
        txs.append(
            ctx.signed_tx(
                _SECRET_SYNC, group, nonce=f"{tag}-{group}-{i}",
                to=DAG_TRANSFER_ADDRESS, input=_add_call(ctx, f"s-{tag}-{i}"),
            )
        )
        if len(txs) >= batch:
            yield SubmitTxs(
                group, txs, source=f"peer:{tag}-{peer}", lane="sync"
            )
            peer = (peer + 1) % peers
            txs = []
    if txs:
        yield SubmitTxs(group, txs, source=f"peer:{tag}-{peer}", lane="sync")


# ---------------------------------------------------------------------------
# the catalog
# ---------------------------------------------------------------------------

# base sizes at scale=1.0 — tier-1 tests run scale<=0.2, the bench scales up
_N = 192


def _sub_rng(rng: random.Random, k: int) -> random.Random:
    """Per-stream RNG forked arithmetically (never via hash())."""
    return random.Random(rng.randrange(1 << 62) * 4 + k % 4)


register(Scenario(
    name="flood",
    description="single-group well-behaved flood (the solo baseline)",
    groups=("group0",),
    build=lambda ctx, rng, s: [
        valid_flood(ctx, _sub_rng(rng, 0), "group0", int(_N * s) or 1),
    ],
))

register(Scenario(
    name="invalid-sig-storm",
    description="garbage-signature spam racing a small honest flood",
    groups=("group0",),
    build=lambda ctx, rng, s: [
        invalid_sig_storm(ctx, _sub_rng(rng, 0), "group0", int(2 * _N * s) or 1),
        valid_flood(ctx, _sub_rng(rng, 1), "group0", int(_N * s // 2) or 1),
    ],
))

register(Scenario(
    name="mempool-churn",
    description="duplicate + nonce-replacement spam over an honest flood",
    groups=("group0",),
    build=lambda ctx, rng, s: [
        mempool_churn(ctx, _sub_rng(rng, 0), "group0", int(_N * s) or 1),
        valid_flood(ctx, _sub_rng(rng, 1), "group0", int(_N * s // 2) or 1),
    ],
))

register(Scenario(
    name="hot-contract",
    description="key-lock contention flood (DMC worst case) on one contract",
    groups=("group0",),
    build=lambda ctx, rng, s: [
        hot_contract_flood(ctx, _sub_rng(rng, 0), "group0", int(_N * s) or 1),
    ],
))

register(Scenario(
    name="cross-group",
    description="independent valid floods on two groups of one host set",
    groups=("group0", "group1"),
    build=lambda ctx, rng, s: [
        valid_flood(ctx, _sub_rng(rng, 0), "group0", int(_N * s) or 1),
        valid_flood(ctx, _sub_rng(rng, 1), "group1", int(_N * s) or 1),
    ],
))

register(Scenario(
    name="sync-storm",
    description="lagging peers flushing sync backlogs under delayed gossip",
    groups=("group0",),
    # every 3rd gateway send is delayed 5ms — the healing-partition shape;
    # seed= is overridden by the scenario seed at plan build time
    fault_spec="delay@send:gw,p=0.34,ms=5",
    build=lambda ctx, rng, s: [
        sync_storm(ctx, _sub_rng(rng, 0), "group0", int(2 * _N * s) or 1),
        valid_flood(ctx, _sub_rng(rng, 1), "group0", int(_N * s // 2) or 1),
    ],
))

register(Scenario(
    name="isolation",
    description="group A floods invalid-signature spam while group B runs "
    "the standard flood on the same node — the multi-tenant acceptance case",
    groups=("groupA", "groupB"),
    abusive_groups=("groupA",),
    # the quota is what sheds A's spam at the door; B stays un-throttled
    # because its honest rate sits far below the same per-group budget
    quota_rate=4000.0,
    build=lambda ctx, rng, s: [
        invalid_sig_storm(
            ctx, _sub_rng(rng, 0), "groupA", int(4 * _N * s) or 1, batch=96,
        ),
        valid_flood(ctx, _sub_rng(rng, 1), "groupB", int(_N * s) or 1),
    ],
))
