"""Scenario runner — drive a generated workload against a live chain and
emit the per-group artifact.

Deployment shape: one host set (default 4, the BASELINE PBFT quorum), one
:class:`~fisco_bcos_tpu.gateway.group.GroupGateway` mux per host over one
in-proc transport, one :class:`~fisco_bcos_tpu.node.Node` per (host,
group) — the multi-group topology of tests/test_multigroup.py at bench
scale. Every group shares the process's ONE DevicePlane and ONE
:class:`~fisco_bcos_tpu.txpool.quota.AdmissionQuotas` policer, which is
the point: the scenarios exist to prove (or break) the isolation between
tenants of shared machinery.

Event driving: batches submit at the group's next-height leader (the
test_multigroup pattern — gossip via ``tx_sync.maintain()`` fills the
replicas), sealing interleaves with submission so pools never grow
unboundedly, and a final drain loop commits the tail. Per-group stats
count every admission verdict, per-tx commit latency (submit→commit wall
time) and committed TPS over the measured window.

The artifact is JSON-ready: per-group breakdowns, the quota policer's
shed/demotion snapshot, plane stats, the health registry, and the
determinism digest of everything submitted.
"""

from __future__ import annotations

import time

from ..utils.log import get_logger
from .base import Scenario, SubmitTxs, get_scenario

_log = get_logger("scenario")


class _GroupStats:
    __slots__ = (
        "submitted", "admitted", "rejected", "committed", "blocks",
        "latencies_ms", "t_submit",
    )

    def __init__(self):
        self.submitted = 0
        self.admitted = 0
        self.rejected: dict[str, int] = {}
        self.committed = 0
        self.blocks = 0
        self.latencies_ms: list[float] = []
        # admitted tx hash -> submit wall time (consumed at commit)
        self.t_submit: dict[bytes, float] = {}


def _pctl(xs: list[float], q: float) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


class ScenarioRunner:
    """One scenario run on a fresh chain. ``scale`` multiplies workload
    sizes; ``seal_every`` batches a seal pass between that many submit
    events (1 = seal eagerly, larger = deeper pools / bigger blocks)."""

    def __init__(
        self,
        scenario: Scenario | str,
        seed: int = 0,
        hosts: int = 4,
        scale: float = 1.0,
        seal_every: int = 4,
        block_cap: int = 2000,
        deadline_s: float | None = None,
    ):
        self.scenario = (
            get_scenario(scenario) if isinstance(scenario, str) else scenario
        )
        self.seed = int(seed)
        self.hosts = int(hosts)
        self.scale = float(scale)
        self.seal_every = max(1, int(seal_every))
        self.block_cap = int(block_cap)
        self.deadline_s = deadline_s
        self.error: str | None = None

    # -- chain construction ---------------------------------------------------

    def _build_chain(self):
        from ..front import InprocGateway
        from ..gateway.group import GroupGateway
        from ..ledger import ConsensusNode, GenesisConfig
        from ..node import Node, NodeConfig

        suite_secrets = [0x5CE9_0000 + i for i in range(self.hosts)]
        from ..crypto.suite import ecdsa_suite

        suite = ecdsa_suite()
        keypairs = [
            suite.signature_impl.generate_keypair(secret=s) for s in suite_secrets
        ]
        committee = [ConsensusNode(kp.pub, weight=1) for kp in keypairs]
        transport = InprocGateway(auto=True)
        hosts = []
        for kp in keypairs:
            mux = GroupGateway(kp.pub)
            transport.connect(mux)
            nodes = {}
            for g in self.scenario.groups:
                cfg = NodeConfig(
                    group_id=g,
                    admission_rate=self.scenario.quota_rate,
                    genesis=GenesisConfig(
                        group_id=g,
                        consensus_nodes=list(committee),
                        tx_count_limit=self.block_cap,
                    ),
                )
                nodes[g] = Node(cfg, keypair=kp, front=mux.register_group(g))
            hosts.append({"mux": mux, "nodes": nodes})
        return hosts

    def _leader(self, hosts, group: str):
        any_node = hosts[0]["nodes"][group]
        number = any_node.block_number() + 1
        idx = any_node.pbft_config.leader_index(number, 0)
        target = any_node.pbft_config.nodes[idx].node_id
        return next(
            h["nodes"][group]
            for h in hosts
            if h["nodes"][group].node_id == target
        )

    # -- driving --------------------------------------------------------------

    def _seal_group(self, hosts, group: str, stats: _GroupStats) -> bool:
        """One sealing attempt for the group's next height; on commit,
        settle latency for every tx that left the pool."""
        leader = self._leader(hosts, group)
        if leader.txpool.unsealed_count() == 0:
            return False
        before = leader.block_number()
        if not leader.sealer.seal_and_submit():
            return False
        after = leader.block_number()
        if after <= before:
            return False
        now = time.perf_counter()
        # settle committed txs from the LEDGER's record of the new blocks
        # (leaders rotate per height, so pool membership on any one node is
        # not a commit witness)
        for number in range(before + 1, after + 1):
            stats.blocks += 1
            for h in leader.ledger.tx_hashes_by_number(number):
                t0 = stats.t_submit.pop(h, None)
                if t0 is not None:
                    stats.latencies_ms.append((now - t0) * 1e3)
                    stats.committed += 1
        return True

    def run(self) -> dict:
        """Execute the scenario; returns the artifact dict."""
        import hashlib

        from ..resilience import HEALTH
        from ..resilience.faults import clear_fault_plan, install_fault_plan
        from ..txpool.quota import get_quotas

        t_wall = time.perf_counter()
        deadline = (
            t_wall + self.deadline_s if self.deadline_s is not None else None
        )
        hosts = self._build_chain()
        stats = {g: _GroupStats() for g in self.scenario.groups}
        digest = hashlib.sha256()
        plan = self.scenario.fault_plan(self.seed)
        if plan is not None:
            install_fault_plan(plan)
        try:
            t0 = time.perf_counter()
            n_events = 0
            for ev in self.scenario.events(self.seed, self.scale):
                self._apply(hosts, ev, stats[ev.group], digest)
                n_events += 1
                if n_events % self.seal_every == 0:
                    for g in self.scenario.groups:
                        self._seal_group(hosts, g, stats[g])
                if deadline is not None and time.perf_counter() > deadline:
                    self.error = "scenario stopped at wall-clock deadline"
                    break
            # drain: commit the tail (a stalled group must not hang the
            # run). Gate on EVERY host's pool, not host 0's replica — the
            # submissions landed at the rotating leader and gossip may lag
            # (sync-storm's delay plan), so an empty host-0 pool does not
            # mean the group is drained.
            for g in self.scenario.groups:
                stalls = 0
                while (
                    any(
                        h["nodes"][g].txpool.unsealed_count() > 0
                        for h in hosts
                    )
                    and stalls < 3
                ):
                    if deadline is not None and time.perf_counter() > deadline:
                        self.error = self.error or "drain hit deadline"
                        break
                    if not self._seal_group(hosts, g, stats[g]):
                        stalls += 1
            dt = time.perf_counter() - t0
        finally:
            if plan is not None:
                clear_fault_plan()
        quotas = get_quotas()
        groups_doc = {}
        for g, st in stats.items():
            tip = hosts[0]["nodes"][g].block_number()
            heights = {h["nodes"][g].block_number() for h in hosts}
            if len(heights) != 1:
                self.error = self.error or (
                    f"group {g} replicas diverged: heights {sorted(heights)}"
                )
            groups_doc[g] = {
                "abusive": g in self.scenario.abusive_groups,
                "submitted": st.submitted,
                "admitted": st.admitted,
                "rejected": dict(sorted(st.rejected.items())),
                "committed": st.committed,
                "blocks": st.blocks,
                "height": tip,
                "tps": round(st.committed / dt, 2) if dt > 0 else 0.0,
                "latency_ms_p50": round(_pctl(st.latencies_ms, 0.50), 2),
                "latency_ms_p95": round(_pctl(st.latencies_ms, 0.95), 2),
            }
        doc = {
            "scenario": self.scenario.name,
            "seed": self.seed,
            "scale": self.scale,
            "hosts": self.hosts,
            "wall_s": round(time.perf_counter() - t_wall, 3),
            "measured_s": round(dt, 3),
            "events": n_events,
            "groups": groups_doc,
            "quotas": quotas.snapshot(),
            "health": HEALTH.snapshot(),
            "faults_injected": plan.injected if plan is not None else 0,
            "determinism_digest": digest.hexdigest(),
        }
        from ..device.plane import get_plane, plane_enabled

        if plane_enabled():
            plane = get_plane()
            plane.drain(10.0)
            doc["device_plane"] = plane.stats()
        if self.error:
            doc["error"] = self.error
        return doc

    @staticmethod
    def _reset_shared_state() -> None:
        """Fresh policer/health state so back-to-back runs in one process
        (the isolation bench's solo + combined legs) don't bleed quota
        debt, demotions or degradations into each other."""
        from ..resilience import HEALTH
        from ..txpool.quota import get_quotas

        get_quotas().reset()
        HEALTH.reset()

    def _apply(self, hosts, ev: SubmitTxs, st: _GroupStats, digest) -> None:
        from ..txpool.txpool import _REJECT_REASON
        from ..utils.error import ErrorCode

        digest.update(ev.encode())
        node = self._leader(hosts, ev.group)
        t0 = time.perf_counter()
        results = node.txpool.submit_batch(ev.txs, lane=ev.lane, source=ev.source)
        st.submitted += len(ev.txs)
        for r in results:
            if r.status == ErrorCode.SUCCESS:
                st.admitted += 1
                st.t_submit[r.tx_hash] = t0
            else:
                reason = _REJECT_REASON.get(r.status, "static")
                st.rejected[reason] = st.rejected.get(reason, 0) + 1
        # gossip payloads so replicas can execute whatever gets sealed
        node.tx_sync.maintain()


def run_isolation_bench(
    seed: int = 0,
    hosts: int = 4,
    scale: float = 1.0,
    deadline_s: float | None = None,
) -> dict:
    """The ISSUE 6 acceptance bench: victim group B solo, then B again
    while group A floods invalid-signature spam on the same node. Emits
    both artifacts plus the ratio the criterion pins (combined/solo
    committed TPS must stay >= 0.7) and the admission-shed counters that
    prove the abuse died at the door, not in the pipeline.
    """
    from ..utils.metrics import REGISTRY
    from .base import Scenario
    from . import workloads

    iso = get_scenario("isolation")
    victim, abuser = "groupB", "groupA"
    solo = Scenario(
        name="isolation-solo",
        description="the isolation victim's workload with no abuser present",
        groups=(victim,),
        quota_rate=iso.quota_rate,  # same knobs, only the abuser is absent
        build=lambda ctx, rng, s: [
            workloads.valid_flood(
                ctx, workloads._sub_rng(rng, 1), victim,
                int(workloads._N * s) or 1,
            ),
        ],
    )
    split = (0.45, 0.55)  # solo is smaller: no spam to shed
    solo_deadline = deadline_s * split[0] if deadline_s is not None else None
    comb_deadline = deadline_s * split[1] if deadline_s is not None else None

    ScenarioRunner._reset_shared_state()
    solo_doc = ScenarioRunner(
        solo, seed=seed, hosts=hosts, scale=scale, deadline_s=solo_deadline
    ).run()
    ScenarioRunner._reset_shared_state()
    comb_doc = ScenarioRunner(
        iso, seed=seed, hosts=hosts, scale=scale, deadline_s=comb_deadline
    ).run()

    solo_tps = solo_doc["groups"][victim]["tps"]
    comb_tps = comb_doc["groups"][victim]["tps"]
    ratio = comb_tps / solo_tps if solo_tps > 0 else 0.0
    shed = REGISTRY.counters_matching("fisco_ratelimit_dropped_total")
    doc = {
        "scenario": "isolation-bench",
        "seed": seed,
        "victim_group": victim,
        "abuser_group": abuser,
        "victim_tps_solo": solo_tps,
        "victim_tps_combined": comb_tps,
        "victim_ratio": round(ratio, 3),
        "abuse_shed_counters": shed,
        "solo": solo_doc,
        "combined": comb_doc,
    }
    abuser_doc = comb_doc["groups"][abuser]
    shed_total = sum(
        v for k, v in shed.items() if f'group="{abuser}"' in k
    )
    if shed_total <= 0 and abuser_doc["rejected"].get("sig", 0) == 0:
        doc["error"] = "no abuse was shed or rejected — isolation unproven"
    return doc
