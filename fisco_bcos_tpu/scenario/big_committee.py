"""big-committee: QC bytes + verify time flat in committee size (ISSUE 12
acceptance bench).

Three legs, one artifact:

1. **Quorum-layer sweep** — for n = 4 and n = 64: a deterministic
   committee signs the same header hash, the votes are sealed into a
   quorum certificate per round, and the certificate is aggregate-
   verified. Measured per committee size: encoded QC bytes, aggregate
   verify wall-ms (through the BLS scheme's DevicePlane-routed pairing
   check — the host reference on CPU backends), certificate build ms,
   and the n-sequential per-vote verification baseline the aggregate
   replaces. Acceptance: n=64 QC bytes within 1.1x of n=4, and the
   aggregate verify beating n sequential verifies at n=64.
2. **Ed25519 comparison row** — the cheap rung's concatenated
   certificate at both sizes, showing exactly the O(n) byte growth the
   BLS rung removes.
3. **Integration leg** — a real 4-node PBFT chain with a QC-capable BLS
   committee commits blocks end-to-end; the committed headers must carry
   the constant-size QC record (and no signature_list), replicas must
   agree, and the committed QC bytes land in the artifact.

Committees are derived from the seed (deterministic secrets -> derived
QC keypairs), so the sweep is reproducible; wall-clock timings are the
measurement, not part of the determinism contract.
"""

from __future__ import annotations

import hashlib
import os
import time

from ..utils.log import get_logger
from .runner import _pctl

_log = get_logger("scenario")


def _sweep_committee(scheme, n: int, seed: int, rounds: int, deadline=None) -> dict:
    """One committee size: sign -> seal -> aggregate-verify per round,
    plus the sequential per-vote baseline (one round's worth)."""
    kps = [scheme.derive_keypair(0x51C0_0000 + seed * 100_000 + i) for i in range(n)]
    pubs = [kp.pub for kp in kps]
    build_ms, verify_ms, sign_ms, qc_bytes = [], [], [], 0
    ok_all = True
    done_rounds = 0
    for r in range(rounds):
        if deadline is not None and time.perf_counter() > deadline:
            break
        msg = hashlib.sha256(b"big-committee:%d:%d:%d" % (seed, n, r)).digest()
        t0 = time.perf_counter()
        sigs = {i: scheme.sign_vote(kp, msg) for i, kp in enumerate(kps)}
        sign_ms.append((time.perf_counter() - t0) * 1e3 / n)
        t0 = time.perf_counter()
        cert = scheme.build_cert(sigs, n)
        build_ms.append((time.perf_counter() - t0) * 1e3)
        qc_bytes = len(cert.encode())
        t0 = time.perf_counter()
        ok_all &= scheme.verify_cert(cert, pubs, msg)
        verify_ms.append((time.perf_counter() - t0) * 1e3)
        done_rounds += 1
    # the baseline the aggregate replaces: n sequential per-vote verifies
    # (the single most expensive leg at n=64 — it honors the deadline too,
    # or a tight bench budget would kill the child and lose the artifact)
    sequential_ms = 0.0
    if deadline is None or time.perf_counter() < deadline:
        msg = hashlib.sha256(b"big-committee:%d:%d:base" % (seed, n)).digest()
        sigs = {i: scheme.sign_vote(kp, msg) for i, kp in enumerate(kps)}
        t0 = time.perf_counter()
        seq_ok = all(
            scheme.verify_one(pubs[i], msg, sigs[i]) for i in range(n)
        )
        sequential_ms = (time.perf_counter() - t0) * 1e3
        ok_all &= seq_ok
    agg = _pctl(verify_ms, 0.5)
    return {
        "n": n,
        "rounds": done_rounds,
        "qc_bytes": qc_bytes,
        "verify_ms_p50": round(agg, 3),
        "verify_ms": [round(v, 3) for v in verify_ms],
        "build_ms_p50": round(_pctl(build_ms, 0.5), 3),
        "sign_ms_per_vote": round(_pctl(sign_ms, 0.5), 3),
        "sequential_verify_ms": round(sequential_ms, 3),
        "aggregate_speedup_vs_sequential": round(sequential_ms / agg, 2)
        if agg > 0
        else 0.0,
        "all_verified": ok_all,
    }


def _chain_leg(seed: int, blocks: int = 2) -> dict:
    """End-to-end: a 4-node chain whose committee registered BLS qc_pubs
    commits real blocks; committed headers must carry the constant-size
    QC record."""
    from ..codec.abi import ABICodec
    from ..consensus.qc import QuorumCert, qc_pub_for
    from ..crypto.suite import ecdsa_suite
    from ..executor.precompiled import DAG_TRANSFER_ADDRESS
    from ..front import InprocGateway
    from ..ledger import ConsensusNode, GenesisConfig
    from ..node import Node, NodeConfig
    from ..protocol.transaction import TransactionFactory

    suite = ecdsa_suite()
    codec = ABICodec(suite.hash)
    secrets = [0xB1C0_0000 + seed * 1000 + i for i in range(4)]
    keypairs = [suite.signature_impl.generate_keypair(secret=s) for s in secrets]
    committee = [
        ConsensusNode(kp.pub, weight=1, qc_pub=qc_pub_for(s))
        for kp, s in zip(keypairs, secrets)
    ]
    gw = InprocGateway(auto=True)
    nodes = []
    for kp in keypairs:
        cfg = NodeConfig(genesis=GenesisConfig(consensus_nodes=list(committee)))
        node = Node(cfg, keypair=kp)
        gw.connect(node.front)
        nodes.append(node)
    fac = TransactionFactory(suite)
    sender = suite.signature_impl.generate_keypair(secret=0xB1C0_FFFF)
    committed_qc_bytes = []
    t0 = time.perf_counter()
    for b in range(blocks):
        height = nodes[0].block_number() + 1
        idx = nodes[0].pbft_config.leader_index(height, 0)
        leader = next(
            nd
            for nd in nodes
            if nd.node_id == nodes[0].pbft_config.nodes[idx].node_id
        )
        txs = [
            fac.create_signed(
                sender,
                chain_id="chain0",
                group_id="group0",
                block_limit=500,
                nonce=f"bigc-{seed}-{b}-{i}",
                to=DAG_TRANSFER_ADDRESS,
                input=codec.encode_call(
                    "userAdd(string,uint256)", f"u{b}-{i}", 1
                ),
            )
            for i in range(3)
        ]
        leader.txpool.submit_batch(txs)
        leader.tx_sync.maintain()
        leader.sealer.seal_and_submit()
        header = leader.ledger.header_by_number(leader.block_number())
        if header is not None and header.qc:
            cert = QuorumCert.decode(header.qc)
            committed_qc_bytes.append(len(header.qc))
            assert cert.scheme == "bls", cert.scheme
    heights = {nd.block_number() for nd in nodes}
    return {
        "blocks_committed": nodes[0].block_number(),
        "wall_s": round(time.perf_counter() - t0, 3),
        "heights_equal": len(heights) == 1,
        "committed_qc_bytes": committed_qc_bytes,
        "headers_carry_qc": len(committed_qc_bytes) == nodes[0].block_number(),
    }


def run_big_committee_bench(
    seed: int = 0, scale: float = 1.0, deadline_s: float | None = None
) -> dict:
    """The ISSUE 12 acceptance bench; returns the artifact dict."""
    from ..consensus.qc import get_scheme

    t_wall = time.perf_counter()
    deadline = t_wall + deadline_s if deadline_s is not None else None
    rounds = max(2, int(3 * scale))
    bls = get_scheme("bls")
    ed = get_scheme("ed25519")
    doc: dict = {"scenario": "big-committee", "seed": seed, "scheme": "bls"}
    sweep = {}
    for n in (4, 64):
        sweep[str(n)] = _sweep_committee(bls, n, seed, rounds, deadline)
    doc["committees"] = sweep
    b4, b64 = sweep["4"]["qc_bytes"], sweep["64"]["qc_bytes"]
    v4, v64 = sweep["4"]["verify_ms_p50"], sweep["64"]["verify_ms_p50"]
    doc["qc_bytes_ratio_64_vs_4"] = round(b64 / b4, 3) if b4 else 0.0
    doc["verify_ms_ratio_64_vs_4"] = round(v64 / v4, 3) if v4 else 0.0
    doc["aggregate_speedup_vs_sequential_n64"] = sweep["64"][
        "aggregate_speedup_vs_sequential"
    ]
    # the cheap rung's byte growth, for contrast
    doc["ed25519"] = {
        str(n): _sweep_committee(ed, n, seed, rounds=1, deadline=deadline)[
            "qc_bytes"
        ]
        for n in (4, 64)
    }
    errors = []
    for n, d in sweep.items():
        if not d["all_verified"]:
            errors.append(f"n={n}: a quorum failed verification")
    if deadline is None or time.perf_counter() < deadline:
        prev_qc = os.environ.get("FISCO_QC")
        prev_scheme = os.environ.get("FISCO_QC_SCHEME")
        os.environ["FISCO_QC"] = "1"
        os.environ["FISCO_QC_SCHEME"] = "bls"
        try:
            doc["chain"] = _chain_leg(seed)
            if not doc["chain"]["headers_carry_qc"]:
                errors.append("chain leg committed headers without QC records")
            if not doc["chain"]["heights_equal"]:
                errors.append("chain leg replicas diverged")
        finally:
            for key, prev in (
                ("FISCO_QC", prev_qc),
                ("FISCO_QC_SCHEME", prev_scheme),
            ):
                if prev is None:
                    os.environ.pop(key, None)
                else:
                    os.environ[key] = prev
    else:
        errors.append("deadline hit before the chain leg")
    doc["wall_s"] = round(time.perf_counter() - t_wall, 3)
    if errors:
        doc["error"] = "; ".join(errors)
    return doc
