"""Real-wire chaos mesh — the byzantine catalog over TCP (ISSUE 17).

:class:`WireHarness` boots an n-node committee on REAL sockets: one
:class:`~fisco_bcos_tpu.gateway.tcp.TcpGateway` per node, each bound to
its own loopback address (``127.0.0.<i+1>``) so host identity is a
first-class property of every link — exactly what the partition fault
family (:meth:`~fisco_bcos_tpu.resilience.faults.FaultPlan.partition`)
cuts on. The attack semantics are inherited wholesale from
:class:`~.byzantine.ByzantineHarness`; only the transport changes: the
in-proc queue's explicit ``deliver_all`` becomes a quiescence wait
(reader threads deliver asynchronously, so "drained" means the fleet's
observable state stopped moving).

Beyond the catalog, the wire plane adds what only a real transport can
exercise:

- **partition/heal** — a seeded bidirectional cut between host sets; the
  majority side keeps committing (view-changing over isolated leaders),
  the minority stalls, and on heal the laggards block-sync back while
  severed links re-establish through the gateway's
  :class:`~fisco_bcos_tpu.resilience.retry.RetryPolicy` redial;
- **evidence-gossip convergence** — each node runs its own
  :class:`~fisco_bcos_tpu.consensus.gossip.EvidenceGossip`, and
  :meth:`WireHarness.await_convergence` measures (in settle rounds) how
  long a detection made anywhere takes to reach every honest node;
- **colluding adversaries** — :func:`run_wire_colluders` drives TWO
  cooperating byzantine members (equivocation + forged QC votes) inside
  an n=7 committee and gates on agreement, double demotion, and quorum
  membership surviving the demotions.

Every run ends at the same gate as the in-proc catalog: the
:func:`~fisco_bcos_tpu.consensus.audit.audit_chain` safety auditor over
ALL nodes after heal/catch-up.
"""

from __future__ import annotations

import time

from ..consensus.audit import EVIDENCE, EVIDENCE_GROUP, validator_source
from ..consensus.messages import PacketType, PBFTMessage, ViewChangePayload
from ..gateway.tcp import TcpGateway
from ..resilience import HEALTH
from ..resilience.faults import (
    FaultPlan,
    clear_fault_plan,
    install_fault_plan,
)
from ..txpool.quota import get_quotas
from ..utils.log import get_logger
from .base import WorkloadContext
from .byzantine import ATTACK_NAMES, ByzantineHarness, ByzantineReplica

_log = get_logger("wire")

# attacks whose evidence family gossips (stale_view_replay is
# indistinguishable from lag and never gossips; forged_qc_vote's FORGED
# frame convicts nobody, but its garbage-own-signature half raises
# bad_qc_vote which does)
GOSSIPED_ATTACKS = (
    "equivocation",
    "vote_conflict",
    "fabricated_prepared_cert",
    "forged_qc_vote",
)


class WireHarness(ByzantineHarness):
    """The :class:`ByzantineHarness` contract over real TCP sockets.

    Node i binds ``127.0.0.<i+1>`` (the whole 127/8 block routes on
    loopback), dials a full mesh, and runs live reader threads — attack
    frames, votes, gossip and block sync all ride genuine sockets.
    """

    def __init__(
        self,
        seed: int = 0,
        hosts: int = 4,
        with_qc: bool = True,
        block_cap: int = 2000,
        group: str = "group0",
        tick_s: float = 0.015,
        quiet_ticks: int = 3,
        settle_timeout_s: float = 8.0,
    ):
        from ..crypto.suite import ecdsa_suite
        from ..ledger import ConsensusNode, GenesisConfig
        from ..node import Node, NodeConfig

        self.seed = int(seed)
        self.group = group
        self.tick_s = tick_s
        self.quiet_ticks = quiet_ticks
        self.settle_timeout_s = settle_timeout_s
        suite = ecdsa_suite()
        secrets = [0xA17E_0000 + seed * 131 + i for i in range(hosts)]
        keypairs = [
            suite.signature_impl.generate_keypair(secret=s) for s in secrets
        ]
        committee = []
        for i, kp in enumerate(keypairs):
            qc_pub = b""
            if with_qc:
                from ..consensus.qc import qc_pub_for

                qc_pub = qc_pub_for(secrets[i])
            committee.append(ConsensusNode(kp.pub, weight=1, qc_pub=qc_pub))
        self.transport = None  # no in-proc queue on the wire
        self.nodes = []
        self.gateways: list[TcpGateway] = []
        for i, kp in enumerate(keypairs):
            gw = TcpGateway(
                kp.pub, host=f"127.0.0.{i + 1}", port=0, heartbeat_interval=0
            )
            cfg = NodeConfig(
                group_id=group,
                genesis=GenesisConfig(
                    group_id=group,
                    consensus_nodes=list(committee),
                    tx_count_limit=block_cap,
                ),
            )
            node = Node(cfg, keypair=kp)
            gw.connect(node.front)
            gw.start()
            self.nodes.append(node)
            self.gateways.append(gw)
        for i, gw in enumerate(self.gateways):
            for other in self.gateways[i + 1 :]:
                if not gw.connect_peer(other.host, other.port):
                    raise RuntimeError(
                        f"dial {gw.host} -> {other.host}:{other.port} failed"
                    )
        self.await_mesh()
        self.adv_index = self.seed % hosts
        self.adversary = ByzantineReplica(self._node_at(self.adv_index))
        self.honest = [n for n in self.nodes if n is not self.adversary.node]
        self.ctx = WorkloadContext(suite=suite)
        self._nonce = 0

    # -- wire plumbing --------------------------------------------------------

    def gateway_of(self, node) -> TcpGateway:
        return self.gateways[self.nodes.index(node)]

    def host_of(self, node) -> str:
        return self.gateway_of(node).host

    def await_mesh(
        self, expect: "dict | None" = None, timeout_s: float = 10.0
    ) -> None:
        """Block until every gateway sees its expected peer count
        (default: the full mesh, n-1 each)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            short = [
                gw
                for gw in self.gateways
                if len(gw.peers())
                < (expect or {}).get(gw.host, len(self.gateways) - 1)
            ]
            if not short:
                return
            time.sleep(0.01)
        raise TimeoutError(
            "mesh incomplete: "
            + ", ".join(
                f"{gw.host} sees {len(gw.peers())}" for gw in self.gateways
            )
        )

    def _fingerprint(self) -> tuple:
        """Everything externally observable that in-flight frames still
        change — two identical consecutive reads mean the wire is quiet.
        Vote-cache sizes and the off-lock verify queue are included so a
        round mid-quorum (or parked in a slow aggregate check) never
        reads as idle."""
        rows = []
        for n in self.nodes:
            g = n.engine.gossip
            try:
                votes = sum(
                    len(c.prepares) + len(c.commits) + len(c.checkpoints)
                    for c in list(n.engine._caches.values())
                )
            except RuntimeError:  # cache dict mutated underfoot: not quiet
                votes = -1
            rows.append(
                (
                    n.block_number(),
                    n.engine.view,
                    n.engine.committed_number,
                    len(n.engine._caches),
                    votes,
                    len(n.engine._verify_keys),
                    n.txpool.pending_count(),
                    sum(g.stats.values()) if g is not None else 0,
                )
            )
        return (tuple(rows), EVIDENCE.count())

    def deliver(self) -> int:
        """The wire's ``deliver_all``: wait until the fleet's observable
        state holds still for ``quiet_ticks`` consecutive ticks. Reader
        threads deliver for real; this only decides when they're done."""
        last, stable = None, 0
        deadline = time.monotonic() + self.settle_timeout_s
        while time.monotonic() < deadline:
            time.sleep(self.tick_s)
            cur = self._fingerprint()
            if cur == last:
                stable += 1
                if stable >= self.quiet_ticks:
                    return 0
            else:
                stable = 0
                last = cur
        return 0  # noisy but alive: callers assert on state, not on quiet

    def await_height(self, number: int, among=None, timeout_s=None) -> bool:
        """Condition-wait for the chain to reach `number` on every node in
        `among` (default: the honest set). The quiet-wait in
        :meth:`deliver` can close mid-round (a slow QC aggregate check has
        no externally visible progress), so round completion is awaited on
        the CONDITION, never inferred from wire silence."""
        among = among if among is not None else self.honest
        deadline = time.monotonic() + (timeout_s or self.settle_timeout_s)
        while time.monotonic() < deadline:
            # EVERY node in the set, durably: attack code reads parent
            # hashes out of honest ledgers right after a commit round
            if min(n.block_number() for n in among) >= number:
                return True
            time.sleep(self.tick_s)
        return False

    def commit_block(self, n_txs: int = 4, max_view_changes: int = 2) -> bool:
        """One honest round over the wire: submit at the leader, gossip,
        seal, then WAIT for the height (not for wire silence). A stalled
        round — e.g. the round an attack just equivocated or vote-split,
        whose leader may even be the demoted adversary — is rotated past
        with a view change and retried, exactly as live PBFT recovers."""
        self.reconcile()
        number = self.height() + 1
        for _ in range(1 + max_view_changes):
            leader = self.leader_for(number)
            txs = self.mint_txs(n_txs)
            results = leader.txpool.submit_batch(txs)
            if any(r.status != 0 for r in results):
                return False
            leader.tx_sync.maintain()
            self.deliver()  # gossip lands before the proposal references it
            sealed = leader.sealer.seal_and_submit()
            if self.await_height(
                number, timeout_s=None if sealed else self.settle_timeout_s / 2
            ):
                return True
            try:
                self.force_view_change()
            except TimeoutError:
                return False
            self.reconcile()
            # the stalled round may have completed during the view change
            number = self.height() + 1
        return self.await_height(number)

    def force_view_change(self, timeout_s=None) -> int:
        """The committee times out and converges on a strictly higher
        view with nobody left mid-view-change. Over a real wire the
        sequential on_timeout loop races the completing quorum: a node
        that already accepted NEW_VIEW(v+1) gets timed out AGAIN toward
        v+2 and strands itself (timeout_state forever, quorum for v+2
        never forms). Stragglers are rescued the way live PBFT would —
        the rest of the committee times out onto the straggler's target
        view until everyone re-converges."""
        start = self.view()
        for n in self.honest:
            n.engine.on_timeout()
        deadline = time.monotonic() + (timeout_s or self.settle_timeout_s)
        while time.monotonic() < deadline:
            self.deliver()
            views = {n.engine.view for n in self.honest}
            stuck = [n for n in self.honest if n.engine.timeout_state]
            if not stuck and len(views) == 1 and min(views) > start:
                return min(views)
            if stuck:
                target = max(n.engine.to_view for n in stuck)
                for n in self.honest:
                    if not n.engine.timeout_state and n.engine.view < target:
                        n.engine.on_timeout()
        raise TimeoutError(
            "view change did not converge from "
            f"{start}: views={[n.engine.view for n in self.honest]} "
            f"timed_out={[n.engine.timeout_state for n in self.honest]}"
        )

    def attack_stale_view_replay(self) -> None:
        """Wire variant: identical frames and detection semantics, but the
        committee's timeout is driven through :meth:`force_view_change`
        (the parent's bare on_timeout loop strands stragglers on a live
        wire — see there)."""
        adv = self.adversary
        number = self.height() + 1
        view = self.view()
        vote = PBFTMessage(
            packet_type=PacketType.PREPARE,
            view=view,
            number=number,
            proposal_hash=b"\x5a" * 32,
        )
        adv.sign(vote)
        adv.broadcast(vote, record=True)
        self.deliver()
        assert self.force_view_change() > view
        for frame in adv.recorded:
            adv.broadcast(frame)
        self.deliver()

    def attack_fabricated_prepared_cert(self) -> None:
        """Wire variant: the fabricated view change must be ON the honest
        nodes' VC cache BEFORE the committee times out — async delivery
        can otherwise complete the honest quorum first and the forged
        cert is never judged (the queued transport ordered this for
        free)."""
        adv = self.adversary
        cfg = self.honest[0].pbft_config
        while cfg.leader_index(self.height() + 1, self.view() + 1) == adv.index:
            assert self.commit_block()
        number = self.height() + 1
        view = self.view()
        parent = self.honest[0].ledger.block_hash_by_number(number - 1) or b""
        fake_block = adv.craft_block(number, parent, 77)
        fake_hash = fake_block.header.hash(adv.suite)
        lone_prepare = adv.sign(
            PBFTMessage(
                packet_type=PacketType.PREPARE,
                view=view,
                number=number,
                proposal_hash=fake_hash,
            )
        )
        vc = PBFTMessage(
            packet_type=PacketType.VIEW_CHANGE,
            view=view + 1,
            number=self.honest[0].engine.committed_number,
            payload=ViewChangePayload(
                committed_number=self.honest[0].engine.committed_number,
                prepared_view=view,
                prepared_proposal=fake_block.encode(),
                prepare_proof=[lone_prepare.encode()],
            ).encode(),
        )
        adv.sign(vc)
        adv.broadcast(vc)
        self.deliver()  # the fabricated VC lands on every cache first
        assert self.force_view_change() > view

    def silence(self, node) -> None:  # pragma: no cover - guard rail
        raise RuntimeError("wire mesh: use cut()/heal(), not silence()")

    def rejoin(self, node) -> None:  # pragma: no cover - guard rail
        raise RuntimeError("wire mesh: use cut()/heal(), not rejoin()")

    def stop(self) -> None:
        try:
            # quiesce first: a reader thread torn down mid-QC-aggregate
            # would linger past the gateway joins and die inside native
            # code at interpreter exit
            self.deliver()
        except Exception:  # analysis: allow(except-hygiene, best-effort quiesce on teardown — nodes may already be crash-halted)
            pass
        for n in self.nodes:
            n.stop()
        for gw in self.gateways:
            gw.stop()

    # -- partition family -----------------------------------------------------

    def cut(self, minority, heal_ms: float = 0.0) -> FaultPlan:
        """Partition `minority` (nodes) off the rest of the committee:
        installs a seeded :class:`FaultPlan` whose ``partition`` rule
        refuses every dial/send/recv across the cut (timed heal when
        ``heal_ms`` > 0, else :meth:`heal` on demand)."""
        minority_hosts = [self.host_of(n) for n in minority]
        majority_hosts = [
            gw.host for gw in self.gateways if gw.host not in minority_hosts
        ]
        plan = FaultPlan(seed=self.seed).partition(
            majority_hosts, minority_hosts, heal_ms=heal_ms
        )
        install_fault_plan(plan)
        return plan

    def heal(self, plan: FaultPlan) -> None:
        """Heal the cut and re-establish the full mesh. The gateways'
        RetryPolicy redials recover links the partition dropped while
        their attempt budgets last; anything they gave up on is re-dialed
        here (the operator's 'plug the cable back in')."""
        plan.heal_partitions()
        for i, gw in enumerate(self.gateways):
            have = set(gw.peers())
            for j, other in enumerate(self.gateways):
                if i != j and other.node_id not in have:
                    gw.connect_peer(other.host, other.port)
        self.await_mesh()

    def commit_block_among(
        self, alive, n_txs: int = 3, max_view_changes: int = 8
    ) -> bool:
        """One committed block using only the `alive` side of a cut,
        view-changing past leaders stranded on the other side."""
        for _ in range(max_view_changes):
            number = max(n.block_number() for n in alive) + 1
            view = max(n.engine.view for n in alive)
            cfg = alive[0].pbft_config
            idx = cfg.leader_index(number, view)
            leader = next(
                (n for n in alive if n.pbft_config.my_index == idx), None
            )
            if leader is None:
                # the scheduled leader is across the cut: rotate the view
                for n in alive:
                    n.engine.on_timeout()
                self.deliver()
                continue
            txs = self.mint_txs(n_txs)
            results = leader.txpool.submit_batch(txs)
            if any(r.status != 0 for r in results):
                return False
            leader.tx_sync.maintain()
            self.deliver()
            if leader.sealer.seal_and_submit() and self.await_height(
                number, among=alive
            ):
                return True
        return False

    # -- wire-adapted vote attacks --------------------------------------------
    #
    # The queued in-proc harness holds a round open: `in_flight_proposal`
    # seals a proposal whose frames sit in the queue while the attack
    # injects votes "mid-round". Real reader threads race the round to
    # completion in milliseconds, so the window must be CREATED, not held:
    # the adversary rotates itself into leadership, crafts its own
    # proposal (knowing the hash before the committee does), and plants
    # its conflicting/bad votes on the wire AHEAD of the pre-prepare —
    # per-link FIFO guarantees every receiver caches the attack votes at
    # (number, view) before the round can possibly finish.

    def _leader_window(self) -> tuple[int, int, PBFTMessage, bytes]:
        """Rotate the adversary into leadership and seal ITS proposal
        locally; returns (number, view, signed pre-prepare, hash) with
        nothing on the wire yet."""
        adv = self.adversary
        number = self.commit_until_leader(adv.index)
        parent = self.honest[0].ledger.block_hash_by_number(number - 1) or b""
        block = adv.craft_block(number, parent, 9)
        view = self.view()
        pp = adv.sign(
            PBFTMessage(
                packet_type=PacketType.PRE_PREPARE,
                view=view,
                number=number,
                proposal_hash=block.header.hash(adv.suite),
                proposal_data=block.encode(),
            )
        )
        return number, view, pp, pp.proposal_hash

    def attack_vote_conflict(self) -> None:
        """Wire variant: fake and genuine PREPAREs land back-to-back
        BEFORE the proposal they vote on — the conflict is cached at every
        honest receiver before the round starts."""
        adv = self.adversary
        number, view, pp, real_hash = self._leader_window()
        fake = adv.sign(
            PBFTMessage(
                packet_type=PacketType.PREPARE,
                view=view,
                number=number,
                proposal_hash=b"\xfa" * 32,
            )
        )
        genuine = adv.sign(
            PBFTMessage(
                packet_type=PacketType.PREPARE,
                view=view,
                number=number,
                proposal_hash=real_hash,
            )
        )
        adv.broadcast(fake)
        adv.broadcast(genuine)
        adv.broadcast(pp)  # the committee commits this one
        self.deliver()

    def attack_forged_qc_vote(self) -> None:
        """Wire variant of the two QC-vote abuses: the garbage-own-sig
        vote and the forged-victim vote are planted ahead of the
        adversary's own proposal, so the off-lock aggregate check finds
        the bad share in its first quorum snapshot."""
        adv = self.adversary
        number, view, pp, real_hash = self._leader_window()
        bad = PBFTMessage(
            packet_type=PacketType.PREPARE,
            view=view,
            number=number,
            proposal_hash=real_hash,
        )
        adv.sign(bad)
        bad.qc_sig = b"\x66" * 64  # authenticated packet, garbage QC vote
        victim_idx = next(
            i for i in range(len(adv.cfg.nodes)) if i != adv.index
        )
        forged = PBFTMessage(
            packet_type=PacketType.PREPARE,
            view=view,
            number=number,
            proposal_hash=real_hash,
        )
        forged.generated_from = victim_idx
        forged.signature = b"\x13" * adv.suite.signature_impl.sig_len
        forged.qc_sig = b"\x37" * 64
        adv.broadcast(bad)
        adv.broadcast(forged)
        adv.broadcast(pp)
        self.deliver()

    # -- evidence-gossip convergence ------------------------------------------

    def gossip_convergence(self, offender_id: bytes | None = None, among=None) -> dict:
        """Which honest nodes have locally confirmed the offender (their
        own detection or a re-verified gossip record)."""
        offender = (offender_id or self.adversary.node.node_id).hex()
        rows = {}
        for n in among if among is not None else self.honest:
            g = n.engine.gossip
            rows[n.engine.crash_scope or n.node_id.hex()[:8]] = bool(
                g is not None and offender in g.confirmed_offenders
            )
        return {"offender": offender, "confirmed": rows, "all": all(rows.values())}

    def await_convergence(
        self,
        offender_id: bytes | None = None,
        among=None,
        timeout_s: float = 5.0,
    ) -> int:
        """Settle rounds until EVERY honest node confirms the offender;
        -1 on timeout. The bounded-rounds claim of the gossip design is
        measured here, not assumed."""
        rounds = 0
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.gossip_convergence(offender_id, among)["all"]:
                return rounds
            time.sleep(self.tick_s)
            rounds += 1
        return -1


def _reset_boards() -> None:
    get_quotas().reset()
    HEALTH.reset()
    EVIDENCE.reset()
    clear_fault_plan()


def run_wire_catalog(
    seed: int = 0,
    hosts: int = 4,
    attacks=ATTACK_NAMES,
    deadline_s: float | None = None,
) -> dict:
    """The full byzantine catalog over real TCP: every attack detected,
    the offender's demotion converged committee-wide through gossip
    (measured in rounds), the safety auditor green at the end."""
    _reset_boards()
    deadline = (
        time.perf_counter() + deadline_s if deadline_s is not None else None
    )
    h = WireHarness(seed=seed, hosts=hosts)
    try:
        for _ in range(2):
            if not h.commit_block(2):
                raise RuntimeError("clean wire round failed")
        assert EVIDENCE.count() == 0, "clean wire blocks raised evidence"
        results = []
        offender = h.adversary.node.node_id
        for name in attacks:
            r = h.run_attack(name)
            if name in GOSSIPED_ATTACKS:
                r["convergence_rounds"] = h.await_convergence(offender)
                r["gossip"] = h.gossip_convergence(offender)
            results.append(r)
            h.commit_block(2)
            if deadline is not None and time.perf_counter() > deadline:
                break
        h.catch_up()
        audit = h.audit()
        gossiped = [r for r in results if "gossip" in r]
        return {
            "scenario": "byzantine-wire",
            "seed": seed,
            "hosts": hosts,
            "adversary_index": h.adv_index,
            "attacks": results,
            "all_detected": (
                len(results) == len(attacks)
                and all(r["detected"] for r in results)
            ),
            "gossip_converged": (
                len(gossiped) > 0
                and all(
                    r["gossip"]["all"] and r["convergence_rounds"] >= 0
                    for r in gossiped
                )
            ),
            "convergence_rounds_max": max(
                (r["convergence_rounds"] for r in gossiped), default=-1
            ),
            "adversary_demoted": h.adversary_demoted(),
            "evidence_counts": EVIDENCE.counts(),
            "honest_height": h.height(),
            "audit": audit,
        }
    finally:
        h.stop()
        clear_fault_plan()


def run_wire_partition(
    seed: int = 0,
    hosts: int = 4,
    blocks_during: int = 2,
    heal_ms: float = 0.0,
) -> dict:
    """Partition/heal over real sockets: the minority host is cut off,
    the majority keeps committing (view-changing past stranded leaders),
    and on heal the laggard block-syncs back before the audit gate."""
    _reset_boards()
    h = WireHarness(seed=seed, hosts=hosts)
    try:
        if not h.commit_block(2):
            raise RuntimeError("pre-partition round failed")
        minority = [h._node_at((h.adv_index + 1) % hosts)]
        majority = [n for n in h.nodes if n not in minority]
        base = h.height()
        plan = h.cut(minority, heal_ms=heal_ms)
        committed = 0
        for _ in range(blocks_during):
            if h.commit_block_among(majority):
                committed += 1
        minority_height = minority[0].block_number()
        if heal_ms > 0:
            time.sleep(max(0.0, heal_ms / 1e3))
            h.heal(plan)  # re-dial abandoned links; the cut itself timed out
        else:
            h.heal(plan)
        # laggard recovery is block sync's job: drive maintain rounds
        # until the minority's durable chain catches the majority head
        deadline = time.monotonic() + h.settle_timeout_s
        while time.monotonic() < deadline:
            h.reconcile()
            if len({n.block_number() for n in h.nodes}) == 1:
                break
        heights = {n.block_number() for n in h.nodes}
        post = h.commit_block(2)
        audit = h.audit()
        return {
            "scenario": "wire-partition",
            "seed": seed,
            "hosts": hosts,
            "minority_host": h.host_of(minority[0]),
            "majority_committed": committed,
            "minority_stalled": minority_height <= base,
            "resynced": len(heights) == 1,
            "post_heal_commit": bool(post),
            "reconnects": plan.injected,
            "heights": sorted(n.block_number() for n in h.nodes),
            "audit": audit,
        }
    finally:
        h.stop()
        clear_fault_plan()


def run_wire_colluders(seed: int = 0, hosts: int = 7) -> dict:
    """Two colluding adversaries inside an n=7 committee: one
    equivocates as leader, the other forges QC votes — agreement must
    hold, BOTH must be demoted on every honest node, and the demotions
    must never cost quorum membership (demoted members' valid votes
    still count, so the 5 honest + 2 demoted committee keeps its 2f+1)."""
    _reset_boards()
    h = WireHarness(seed=seed, hosts=hosts)
    try:
        adv_a = h.adversary
        adv_b = ByzantineReplica(h._node_at((h.adv_index + 1) % hosts))
        h.honest = [
            n for n in h.nodes if n not in (adv_a.node, adv_b.node)
        ]
        if not h.commit_block(2):
            raise RuntimeError("clean n=7 round failed")
        r_a = h.run_attack("equivocation")
        conv_a = h.await_convergence(adv_a.node.node_id)
        h.adversary = adv_b  # the colluder takes the stage
        before = EVIDENCE.counts()
        h.attack_forged_qc_vote()
        after = EVIDENCE.counts()
        h.adversary = adv_a
        # the colluder's garbage-own-signature vote MUST always be caught
        # (bad_qc_vote): its slot is never re-voted, so the quorum
        # snapshot is guaranteed to judge it. The forged-victim frame is
        # dropped either way; its unattributable forged_qc_vote record
        # only lands when a quorum snapshot beats the victim's genuine
        # vote to the slot — deterministic at n=4 (pinned by the catalog),
        # a race at n=7's slower quorum, so it does not gate THIS run.
        delta = {
            k: after.get(k, 0) - before.get(k, 0)
            for k in ("bad_qc_vote", "forged_qc_vote")
        }
        r_b = {
            "attack": "forged_qc_vote",
            "evidence_delta": delta,
            "detected": delta["bad_qc_vote"] > 0,
        }
        conv_b = h.await_convergence(adv_b.node.node_id)
        # agreement + liveness with both adversaries demoted: the honest
        # majority (5 of 7) keeps committing and every node converges
        live = all(h.commit_block(2) for _ in range(2))
        h.catch_up()
        audit = h.audit()
        quotas = get_quotas()
        demoted = {
            "a": quotas.demoted(
                EVIDENCE_GROUP, validator_source(adv_a.node.node_id)
            ),
            "b": quotas.demoted(
                EVIDENCE_GROUP, validator_source(adv_b.node.node_id)
            ),
        }
        honest_undemoted = not any(
            quotas.demoted(EVIDENCE_GROUP, validator_source(n.node_id))
            for n in h.honest
        )
        return {
            "scenario": "wire-colluders",
            "seed": seed,
            "hosts": hosts,
            "attacks": [r_a, r_b],
            "all_detected": r_a["detected"] and r_b["detected"],
            "convergence_rounds": {"a": conv_a, "b": conv_b},
            "both_demoted": demoted["a"] and demoted["b"],
            "demoted": demoted,
            "honest_undemoted": honest_undemoted,
            "liveness_after_demotion": bool(live),
            "honest_height": h.height(),
            "audit": audit,
        }
    finally:
        h.stop()
        clear_fault_plan()


def run_wire_bench(
    seed: int = 0,
    scale: float = 1.0,
    deadline_s: float | None = None,
    hosts: int = 4,
) -> dict:
    """``bench.py --scenario byzantine-wire``: a clean TCP flood leg,
    then the catalog-under-attack leg on a fresh mesh — emits the
    liveness ratio and the measured evidence-convergence rounds. Never
    raises; failures come back as ``doc['error']`` with zeroed metrics."""
    try:
        return _run_wire_bench(seed, scale, deadline_s, hosts)
    except Exception as e:  # noqa: BLE001 — reported through the artifact
        _log.exception("byzantine-wire bench failed")
        return {
            "scenario": "byzantine-wire",
            "seed": seed,
            "scale": scale,
            "error": str(e),
            "clean_tps": 0.0,
            "byzantine_tps": 0.0,
            "liveness_ratio": 0.0,
            "all_detected": False,
            "gossip_converged": False,
            "convergence_rounds_max": -1,
            "adversary_demoted": False,
            "audit": {"ok": False, "violations": [f"bench error: {e}"]},
        }


def _run_wire_bench(
    seed: int, scale: float, deadline_s: float | None, hosts: int
) -> dict:
    n_blocks = max(2, int(4 * scale))
    txs = max(2, int(8 * scale))
    t_entry = time.perf_counter()

    _reset_boards()
    clean = WireHarness(seed=seed, hosts=hosts)
    try:
        ledger = clean.honest[0].ledger
        t0 = time.perf_counter()
        before = ledger.total_transaction_count()
        clean_deadline = (
            t_entry + deadline_s / 3 if deadline_s is not None else None
        )
        for _ in range(n_blocks):
            clean.commit_block(txs)
            if (
                clean_deadline is not None
                and time.perf_counter() > clean_deadline
            ):
                break
        dt = time.perf_counter() - t0
        clean_tps = (
            (ledger.total_transaction_count() - before) / dt if dt > 0 else 0.0
        )
        clean_audit = clean.audit()
    finally:
        clean.stop()
    assert EVIDENCE.count() == 0, "clean wire flood raised evidence"

    catalog_deadline = (
        deadline_s - (time.perf_counter() - t_entry)
        if deadline_s is not None
        else None
    )
    _reset_boards()
    byz = WireHarness(seed=seed, hosts=hosts)
    try:
        ledger = byz.honest[0].ledger
        offender = byz.adversary.node.node_id
        t0 = time.perf_counter()
        before = ledger.total_transaction_count()
        results, rounds = [], []
        for name in ATTACK_NAMES:
            results.append(byz.run_attack(name))
            if name in GOSSIPED_ATTACKS:
                rounds.append(byz.await_convergence(offender))
            byz.commit_block(txs)
            if (
                catalog_deadline is not None
                and time.perf_counter() - t0 > catalog_deadline
            ):
                break
        dt = time.perf_counter() - t0
        byz_tps = (
            (ledger.total_transaction_count() - before) / dt if dt > 0 else 0.0
        )
        byz.catch_up()
        byz_audit = byz.audit()
        demoted = byz.adversary_demoted()
    finally:
        byz.stop()
        clear_fault_plan()
    ratio = byz_tps / clean_tps if clean_tps > 0 else 0.0
    return {
        "scenario": "byzantine-wire",
        "seed": seed,
        "scale": scale,
        "hosts": hosts,
        "clean_tps": round(clean_tps, 2),
        "byzantine_tps": round(byz_tps, 2),
        "liveness_ratio": round(ratio, 3),
        "attacks": results,
        "all_detected": (
            len(results) == len(ATTACK_NAMES)
            and all(r["detected"] for r in results)
        ),
        "gossip_converged": bool(rounds) and all(r >= 0 for r in rounds),
        "convergence_rounds_max": max(rounds, default=-1),
        "adversary_demoted": demoted,
        "evidence_counts": EVIDENCE.counts(),
        "audit_clean": clean_audit,
        "audit_byzantine": byz_audit,
    }
