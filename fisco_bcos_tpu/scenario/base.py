"""Scenario core: the event model, the composition engine, the registry.

A *workload primitive* (:mod:`.workloads`) is a generator function that
yields :class:`SubmitTxs` events from a :class:`WorkloadContext` and a
``random.Random``. A :class:`Scenario` names a set of primitives over a
set of chain groups, optionally composed with a fault-plan spec
(:mod:`fisco_bcos_tpu.resilience.faults` grammar, seeded from the scenario
seed) and a suggested per-group admission quota; :meth:`Scenario.events`
interleaves the primitives' streams with a seeded round-robin picker so
the merged sequence — not just each stream — is deterministic.

Derived RNGs use plain integer arithmetic (``seed * K + index``), never
``hash()`` (string hashing is salted per process and would break the
bit-determinism the acceptance criteria pin).
"""

from __future__ import annotations

import hashlib
import random
from dataclasses import dataclass, field
from typing import Callable, Iterator

from ..crypto.suite import CryptoSuite, KeyPair, ecdsa_suite
from ..protocol.transaction import Transaction, TransactionFactory

# one arbitrary odd multiplier keeps per-stream RNGs independent of the
# master interleaver without builtin hash()
_SEED_STRIDE = 1_000_003


@dataclass
class SubmitTxs:
    """One admission batch: which group's pool, claimed by which source
    (strike accounting), on which device-plane lane."""

    group: str
    txs: list[Transaction]
    source: str = "local"
    lane: str = "admission"

    def encode(self) -> bytes:
        """Canonical bytes for determinism digests: the signed data + the
        signature — everything the chain can observe. (``import_time`` is
        node-local arrival metadata, zeroed by the context anyway.)"""
        head = f"{self.group}|{self.source}|{self.lane}|".encode()
        return head + b"".join(t.encode_data() + t.signature for t in self.txs)


class WorkloadContext:
    """Everything a primitive needs to mint transactions deterministically:
    the crypto suite, an ABI codec, deterministic keypairs by secret, and
    the chain/group identifiers the validator will check."""

    def __init__(
        self,
        suite: CryptoSuite | None = None,
        chain_id: str = "chain0",
        block_limit: int = 500,
    ):
        from ..codec.abi import ABICodec

        self.suite = suite if suite is not None else ecdsa_suite()
        self.codec = ABICodec(self.suite.hash)
        self.factory = TransactionFactory(self.suite)
        self.chain_id = chain_id
        self.block_limit = block_limit
        self._keys: dict[int, KeyPair] = {}

    def keypair(self, secret: int) -> KeyPair:
        kp = self._keys.get(secret)
        if kp is None:
            kp = self._keys[secret] = self.suite.signature_impl.generate_keypair(
                secret=secret
            )
        return kp

    def signed_tx(
        self, secret: int, group: str, nonce: str, to: bytes, input: bytes
    ) -> Transaction:
        """RFC6979 signing — byte-identical for identical inputs. The
        factory's wall-clock ``import_time`` is zeroed (it is node-local
        arrival metadata, not part of the hash preimage) so even the full
        wire encoding replays bit-identically."""
        tx = self.factory.create_signed(
            self.keypair(secret),
            chain_id=self.chain_id,
            group_id=group,
            block_limit=self.block_limit,
            nonce=nonce,
            to=to,
            input=input,
        )
        tx.import_time = 0
        return tx

    def garbage_sig_tx(
        self, rng: random.Random, group: str, nonce: str, to: bytes, input: bytes
    ) -> Transaction:
        """A statically-admissible tx with a seeded-garbage signature of the
        right length: it passes every cheap gate and fails only at the
        device verify — the worst-case admission spam, because the node
        pays crypto for it unless quotas/strikes shed the source first."""
        tx = self.factory.create(
            chain_id=self.chain_id,
            group_id=group,
            block_limit=self.block_limit,
            nonce=nonce,
            to=to,
            input=input,
        )
        tx.signature = bytes(
            rng.getrandbits(8) for _ in range(self.suite.signature_impl.sig_len)
        )
        tx.import_time = 0
        return tx


# a primitive: (ctx, rng) -> iterator of SubmitTxs
Workload = Callable[[WorkloadContext, random.Random], Iterator[SubmitTxs]]


@dataclass
class Scenario:
    """A named, composable traffic shape.

    ``build(ctx, scale)`` returns the list of workload generators (already
    bound to per-stream RNG seeds is the caller's job — see
    :meth:`events`); ``fault_spec`` is a :func:`FaultPlan.from_spec`
    grammar string whose seed is overridden by the scenario seed, so fault
    firing replays with the traffic; ``quota_rate`` is the per-group
    admission rate (txs/s) the runner configures when the scenario is
    about isolation (0 = leave quotas alone).
    """

    name: str
    description: str
    groups: tuple[str, ...]
    build: Callable[[WorkloadContext, random.Random, float], list[Iterator[SubmitTxs]]]
    fault_spec: str | None = None
    quota_rate: float = 0.0
    # groups whose traffic is hostile (artifact labeling + victim math)
    abusive_groups: tuple[str, ...] = field(default=())

    def events(self, seed: int, scale: float = 1.0) -> Iterator[SubmitTxs]:
        """The deterministic merged event stream. ``scale`` multiplies
        workload sizes (primitives read it, the interleave is unaffected
        beyond stream lengths)."""
        ctx = WorkloadContext()
        master = random.Random(seed)
        streams = self.build(ctx, random.Random(seed * _SEED_STRIDE + 1), scale)
        live = list(streams)
        while live:
            idx = master.randrange(len(live))
            try:
                yield next(live[idx])
            except StopIteration:
                live.pop(idx)

    def fault_plan(self, seed: int):
        """The composed fault plan (None when the scenario runs clean)."""
        if not self.fault_spec:
            return None
        from ..resilience.faults import FaultPlan

        plan = FaultPlan.from_spec(self.fault_spec)
        plan.seed = seed
        plan._rng = random.Random(seed)
        return plan

    def digest(self, seed: int, scale: float = 1.0) -> str:
        """sha256 over the canonical encoding of every event — the
        bit-determinism witness (same seed ⇒ same digest, across runs and
        processes)."""
        h = hashlib.sha256()
        for ev in self.events(seed, scale):
            h.update(ev.encode())
        return h.hexdigest()


SCENARIOS: dict[str, Scenario] = {}


def register(s: Scenario) -> Scenario:
    SCENARIOS[s.name] = s
    return s


def get_scenario(name: str) -> Scenario:
    try:
        return SCENARIOS[name]
    except KeyError:
        known = ", ".join(sorted(SCENARIOS))
        raise KeyError(f"unknown scenario {name!r} (known: {known})") from None


def list_scenarios() -> list[tuple[str, str]]:
    return [(s.name, s.description) for _n, s in sorted(SCENARIOS.items())]


# the canned compositions register on import (workloads imports base, so
# the registration lives there to avoid a cycle)
from . import workloads as _workloads  # noqa: E402,F401  (registration side effect)
