"""proof-storm: ~10^5 queued light clients hammer batched proofs WHILE the
chain floods (ISSUE 7 acceptance bench).

Three legs, one artifact:

1. **Solo flood** — the standard valid flood alone; its committed TPS is
   the baseline the combined leg's write path is measured against (the
   same solo-vs-combined shape as the isolation bench).
2. **Combined** — the same flood re-runs while ``workers`` client threads
   drain a queue of ``clients`` proof requests (default ``10^5 x scale``)
   in ``batch``-sized ``proof_batch`` calls against the leader's
   ProofPlane — tx and receipt kinds mixed, every K-th served proof
   re-verified against the ledger's own header root (zero tolerated
   failures). Measured: proofs/sec over the hammer window, per-batch
   latency p50/p95, the plane's cache hit ratio, and the flood's committed
   TPS concurrent with the storm.
3. **Direct baseline** — the pre-ProofPlane path: per-request
   ``Ledger.tx_proof`` full rebuilds on a bare (plane-less) ledger over
   the same chain. ``speedup_vs_direct`` is the acceptance number
   (criterion: >= 50x at 10^5 queued clients).
4. **Succinct lanes (ISSUE 18)** — the state-proof lane: membership
   proofs/sec off a `StatePlane` snapshot bootstrapped from the storm
   chain's durable storage, every sampled proof client-verified against
   the commitment; and the header-sync figure: headers/sec through ONE
   aggregate multi-pairing admission of a BLS-QC'd chain vs the old
   one-pairing-check-per-header loop (``FISCO_BENCH_SYNC_HEADERS``).

Read traffic needs no bit-determinism (it never touches chain state); the
flood events keep the scenario lab's seed contract via the shared
workload primitives.
"""

from __future__ import annotations

import hashlib
import os
import random
import threading
import time

from ..utils.log import get_logger
from . import workloads
from .base import Scenario
from .runner import ScenarioRunner, _GroupStats, _pctl

_log = get_logger("scenario")

_GROUP = "group0"
# flood sizing: 6x the standard catalog flood so the storm has several
# hundreds-of-txs blocks to serve at scale 1 — the block profile the
# reference's headline TPS produces, and the one where the per-request
# rebuild baseline actually hurts (rebuild cost is O(block size))
_FLOOD_N = 6 * workloads._N
_SEAL_EVERY = 12  # deeper pools -> bigger blocks -> bigger frozen trees
_VERIFY_EVERY = 13  # re-verify every 13th served proof against the root


def _flood_scenario() -> Scenario:
    return Scenario(
        name="proof-storm-flood",
        description="the proof storm's write-path flood (both legs)",
        groups=(_GROUP,),
        build=lambda ctx, rng, s: [
            workloads.valid_flood(
                ctx, workloads._sub_rng(rng, 0), _GROUP, int(_FLOOD_N * s) or 1
            ),
        ],
    )


class _HashFeed:
    """Committed tx hashes, refreshed from the ledger as the chain grows
    (the population the simulated clients draw their requests from)."""

    def __init__(self, ledger):
        self.ledger = ledger
        self.hashes: list[bytes] = []
        self._seen_height = 0
        self._lock = threading.Lock()

    def refresh(self) -> int:
        head = self.ledger.block_number()
        with self._lock:
            for n in range(self._seen_height + 1, head + 1):
                self.hashes.extend(self.ledger.tx_hashes_by_number(n))
            self._seen_height = max(self._seen_height, head)
            return len(self.hashes)

    def sample(self, rng: random.Random, k: int) -> list[bytes]:
        with self._lock:
            if not self.hashes:
                return []
            return [self.hashes[rng.randrange(len(self.hashes))] for _ in range(k)]


class _Hammer:
    """The simulated light-client fleet: ``clients`` queued batch requests
    drained by ``workers`` threads against one node's ProofPlane."""

    def __init__(self, node, feed, clients, workers, batch, seed, deadline):
        self.node = node
        self.feed = feed
        self.clients = int(clients)
        self.workers = int(workers)
        self.batch = int(batch)
        self.seed = seed
        self.deadline = deadline
        self.served = 0
        self.batches = 0
        self.verify_failures = 0
        self.not_found = 0
        self.latencies_ms: list[float] = []
        self.t_first: float | None = None
        self.t_last: float | None = None
        self._claimed = 0
        self._lock = threading.Lock()
        self._threads: list[threading.Thread] = []

    def _claim(self) -> int:
        """Claim one queued batch's worth of clients; 0 = queue drained."""
        with self._lock:
            left = self.clients - self._claimed
            take = min(self.batch, left)
            self._claimed += take
            return take

    def _verify(self, tx_hash: bytes, kind: str, res) -> None:
        from ..ops.merkle import MerkleTree

        number, items, idx, n = res
        ok = False
        header = self.node.ledger.header_by_number(number)
        if header is not None:
            if kind == "tx":
                leaf, root = tx_hash, header.txs_root
            else:
                rc = self.node.ledger.receipt_by_hash(tx_hash)
                leaf = rc.hash(self.node.suite) if rc is not None else None
                root = header.receipts_root
            ok = leaf is not None and MerkleTree.verify_proof(
                leaf, idx, n, items, root, hasher=self.node.suite.hash_impl.name
            )
        if not ok:
            with self._lock:
                self.verify_failures += 1

    def _run(self, widx: int) -> None:
        rng = random.Random(self.seed * 7919 + widx)
        plane = self.node.proof_plane
        ledger = self.node.ledger
        t_start = time.perf_counter()
        while True:
            if self.deadline is not None and time.perf_counter() > self.deadline:
                return
            take = self._claim()
            if take == 0:
                return
            hashes = self.feed.sample(rng, take)
            if not hashes:
                with self._lock:
                    self._claimed -= take  # put the claim back
                if time.perf_counter() - t_start > 60.0:
                    return  # the chain never committed anything: give up
                self.feed.refresh()
                time.sleep(0.005)  # chain has no committed txs yet
                continue
            kind = "receipt" if rng.randrange(4) == 0 else "tx"
            t0 = time.perf_counter()
            if plane is not None:
                results = plane.proof_batch(hashes, kind)
            else:  # FISCO_PROOF_PLANE=0: the direct path, honestly measured
                results = ledger.proof_batch_direct(hashes, kind)
            t1 = time.perf_counter()
            to_verify = []
            with self._lock:
                if self.t_first is None:
                    self.t_first = t0
                self.t_last = t1
                self.batches += 1
                self.latencies_ms.append((t1 - t0) * 1e3)
                for h, res in zip(hashes, results):
                    if res is None:
                        self.not_found += 1
                        continue
                    self.served += 1
                    if self.served % _VERIFY_EVERY == 0:
                        to_verify.append((h, res))
            # verification is client-side work and must not convoy the
            # other workers through the tally lock (it reads storage and
            # re-hashes the whole path)
            for h, res in to_verify:
                self._verify(h, kind, res)

    def start(self) -> None:
        for i in range(self.workers):
            t = threading.Thread(
                target=self._run, args=(i,), name=f"proof-client-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)

    def join(self, timeout: float | None = None) -> None:
        deadline = time.monotonic() + timeout if timeout is not None else None
        for t in self._threads:
            left = None if deadline is None else max(deadline - time.monotonic(), 0.1)
            t.join(left)

    def window_s(self) -> float:
        if self.t_first is None or self.t_last is None:
            return 0.0
        return max(self.t_last - self.t_first, 1e-9)


def _steady_state_pps(node, feed, batch: int, budget_s: float = 2.5) -> float:
    """Cached-path proofs/sec measured the same way the direct baseline is
    (single caller, idle chain, no client-side re-verification) — the
    apples-to-apples numerator for ``speedup_vs_direct``. The concurrent
    storm number stays in ``proofs_per_s``; this one isolates the serve
    cost itself."""
    plane = node.proof_plane
    if plane is None:
        return 0.0
    rng = random.Random(0x57EAD)
    served = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < budget_s:
        hashes = feed.sample(rng, batch)
        if not hashes:
            break
        served += sum(1 for r in plane.proof_batch(hashes, "tx") if r is not None)
    dt = time.perf_counter() - t0
    return served / dt if dt > 0 and served else 0.0


def _direct_baseline(node, feed, budget_s: float = 3.0) -> float:
    """Proofs/sec of the pre-ProofPlane path: per-request full rebuilds on
    a bare ledger (no plane attached) over the same committed chain."""
    from ..ledger import Ledger

    bare = Ledger(node.storage, node.suite)  # proof_plane stays None
    rng = random.Random(0xD12EC7)
    sample = feed.sample(rng, 64)
    if not sample:
        return 0.0
    done = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < budget_s:
        h = sample[done % len(sample)]
        if bare.tx_proof(h) is None:
            break
        done += 1
        if done >= 512:
            break
    dt = time.perf_counter() - t0
    return done / dt if dt > 0 and done else 0.0


def _state_proof_lane(node, batch: int, budget_s: float = 2.5) -> dict | None:
    """ISSUE 18 state lane: membership proofs/sec off a StatePlane snapshot
    bootstrapped from the storm chain's durable storage (the succinct read
    surface next to the tx/receipt lanes), sampled proofs client-verified
    against the commitment."""
    from ..succinct.state_plane import (
        EXCLUDED_TABLES,
        StatePlane,
        verify_state_proof,
    )

    if not hasattr(node.storage, "traverse"):
        return None
    plane = StatePlane(node.ledger, node.suite, backend=node.storage)
    keys = [
        (t, bytes(k))
        for t, k, e in node.storage.traverse()
        if not e.deleted and t not in EXCLUDED_TABLES
    ]
    head = plane.head_commitment()
    if not keys or head is None:
        return None
    rng = random.Random(0x57A7E)
    served = verified = failures = 0
    lat: list[float] = []
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < budget_s:
        reqs = [
            keys[rng.randrange(len(keys))]
            for _ in range(min(batch, len(keys)))
        ]
        tb = time.perf_counter()
        results = plane.state_proof_batch(reqs)
        lat.append((time.perf_counter() - tb) * 1e3)
        for (table, key), res in zip(reqs, results):
            if res is None:
                continue
            served += 1
            if served % _VERIFY_EVERY == 0:
                verified += 1
                if not verify_state_proof(
                    table, key, res, head,
                    hasher=plane.hasher, n_pages=plane.n_pages,
                ):
                    failures += 1
    dt = time.perf_counter() - t0
    return {
        "committed_keys": len(keys),
        "proofs_served": served,
        "proofs_per_s": round(served / dt, 2) if dt > 0 and served else 0.0,
        "batch_latency_ms_p50": round(_pctl(lat, 0.50), 3),
        "batch_latency_ms_p95": round(_pctl(lat, 0.95), 3),
        "verified": verified,
        "verify_failures": failures,
        "plane": plane.stats(),
    }


def _header_sync_lane(suite, n_headers: int | None = None) -> dict:
    """ISSUE 18 sync lane: headers/sec through ONE aggregate multi-pairing
    admission vs the old per-header pairing loop, over a freshly signed
    single-sealer BLS-QC'd chain (the cheapest aggregatable shape)."""
    from ..consensus.block_validator import BlockValidator
    from ..consensus.qc import get_scheme
    from ..ledger.ledger import ConsensusNode
    from ..protocol.block_header import BlockHeader, ParentInfo
    from ..succinct.sync import verify_header_batch

    if n_headers is None:
        n_headers = int(
            os.environ.get("FISCO_BENCH_SYNC_HEADERS", "16") or 16
        )
    scheme = get_scheme("bls")
    kp = scheme.derive_keypair(0xBE7C4)
    node_id = b"\x5b" * 64
    committee = [ConsensusNode(node_id, weight=1, qc_pub=kp.pub)]
    headers = []
    prev = suite.hash(b"proof-storm-sync")
    for i in range(1, n_headers + 1):
        h = BlockHeader(
            number=i,
            parent_info=[ParentInfo(i - 1, prev)],
            sealer_list=[node_id],
            consensus_weights=[1],
            timestamp=1_000 + i,
        )
        h.qc = scheme.build_cert(
            {0: scheme.sign_vote(kp, h.hash(suite))}, 1
        ).encode()
        headers.append(h)
        prev = h.hash(suite)
    validator = BlockValidator(suite)
    t0 = time.perf_counter()
    agg_ok = verify_header_batch(headers, committee, validator)
    agg_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    seq_ok = all(
        verify_header_batch([h], committee, validator) for h in headers
    )
    seq_s = time.perf_counter() - t0
    doc = {
        "headers": n_headers,
        "aggregate_s": round(agg_s, 3),
        "headers_per_s": round(n_headers / agg_s, 2) if agg_s > 0 else 0.0,
        "sequential_s": round(seq_s, 3),
        "headers_per_s_sequential": round(n_headers / seq_s, 2)
        if seq_s > 0
        else 0.0,
        "speedup_vs_per_header": round(seq_s / agg_s, 2)
        if agg_s > 0
        else 0.0,
    }
    if not (agg_ok and seq_ok):
        doc["error"] = "an honest BLS header chain was rejected"
    return doc


def run_proof_storm_bench(
    seed: int = 0,
    hosts: int = 4,
    scale: float = 1.0,
    deadline_s: float | None = None,
    workers: int | None = None,
    clients: int | None = None,
    batch: int | None = None,
) -> dict:
    """The ISSUE 7 acceptance bench; returns the artifact dict."""
    if workers is None:
        workers = int(os.environ.get("FISCO_PROOF_WORKERS", "8") or 8)
    if clients is None:
        clients = max(int(100_000 * scale), 64)
    if batch is None:
        batch = int(os.environ.get("FISCO_PROOF_BATCH", "16") or 16)
    t_wall = time.perf_counter()
    deadline = t_wall + deadline_s if deadline_s is not None else None
    scen = _flood_scenario()

    # -- leg 1: solo flood ---------------------------------------------------
    ScenarioRunner._reset_shared_state()
    solo_deadline = (
        None if deadline_s is None else max(deadline_s * 0.35, 10.0)
    )
    solo_doc = ScenarioRunner(
        scen, seed=seed, hosts=hosts, scale=scale, seal_every=_SEAL_EVERY,
        deadline_s=solo_deadline,
    ).run()
    solo_tps = solo_doc["groups"][_GROUP]["tps"]
    # a truncated solo leg distorts the flood-ratio baseline: flag it on
    # the artifact so the acceptance gate reads as degraded, not clean
    error = (
        f"solo leg: {solo_doc['error']}" if solo_doc.get("error") else None
    )

    # -- leg 2: flood + proof storm -------------------------------------------
    ScenarioRunner._reset_shared_state()
    runner = ScenarioRunner(
        scen, seed=seed, hosts=hosts, scale=scale, seal_every=_SEAL_EVERY
    )
    chain = runner._build_chain()
    node0 = chain[0]["nodes"][_GROUP]
    feed = _HashFeed(node0.ledger)
    hammer = _Hammer(node0, feed, clients, workers, batch, seed, deadline)
    stats = _GroupStats()

    t0 = time.perf_counter()
    n_events = 0
    started = False
    digest = hashlib.sha256()
    for ev in scen.events(seed, scale):
        runner._apply(chain, ev, stats, digest)
        n_events += 1
        if n_events % runner.seal_every == 0:
            runner._seal_group(chain, _GROUP, stats)
            if feed.refresh() and not started:
                hammer.start()  # the storm begins once there is a chain
                started = True
        if deadline is not None and time.perf_counter() > deadline:
            error = error or "flood stopped at wall-clock deadline"
            break
    stalls = 0
    while (
        any(h["nodes"][_GROUP].txpool.unsealed_count() > 0 for h in chain)
        and stalls < 3
    ):
        if deadline is not None and time.perf_counter() > deadline:
            error = error or "drain hit deadline"
            break
        if not runner._seal_group(chain, _GROUP, stats):
            stalls += 1
    flood_dt = time.perf_counter() - t0
    feed.refresh()
    if not started:
        hammer.start()
    # let the remaining queued clients drain (the flood is done; the storm
    # keeps hammering the now-static chain — steady-state cache behavior)
    join_budget = (
        max(deadline - time.perf_counter(), 1.0) if deadline is not None else 600.0
    )
    hammer.join(join_budget)
    combined_tps = stats.committed / flood_dt if flood_dt > 0 else 0.0

    # -- leg 3: steady-state cached rate vs the direct per-request baseline ----
    # (both single-caller on the now-idle chain — the flood-concurrent storm
    # rate above keeps the contention story, this pair isolates serve cost)
    steady_pps = _steady_state_pps(node0, feed, batch)
    direct_pps = _direct_baseline(node0, feed)

    # -- leg 4 (ISSUE 18): succinct state lane + constant-work header sync ----
    # (skipped, flagged, when the wall-clock budget is already gone)
    state_lane = None
    sync_lane = None
    if deadline is None or time.perf_counter() < deadline:
        state_lane = _state_proof_lane(node0, batch)
        sync_lane = _header_sync_lane(node0.suite)
        if state_lane and state_lane.get("verify_failures"):
            error = error or (
                f"{state_lane['verify_failures']} state proofs failed "
                "client-side verification"
            )
        if sync_lane.get("error"):
            error = error or f"header sync lane: {sync_lane['error']}"
    else:
        error = error or "succinct lanes skipped at wall-clock deadline"

    plane = node0.proof_plane
    window = hammer.window_s()
    pps = hammer.served / window if window > 0 else 0.0
    ratio = combined_tps / solo_tps if solo_tps > 0 else 0.0
    doc = {
        "scenario": "proof-storm",
        "seed": seed,
        "scale": scale,
        "hosts": hosts,
        "queued_clients": clients,
        "proof_batch_size": batch,
        "workers": workers,
        "proofs_served": hammer.served,
        "proofs_not_found": hammer.not_found,
        "proof_batches": hammer.batches,
        "proofs_per_s": round(pps, 2),
        "proof_batch_latency_ms_p50": round(_pctl(hammer.latencies_ms, 0.50), 3),
        "proof_batch_latency_ms_p95": round(_pctl(hammer.latencies_ms, 0.95), 3),
        "verify_failures": hammer.verify_failures,
        "cache_hit_ratio": round(plane.cache_hit_ratio(), 4) if plane else 0.0,
        "proof_plane": plane.stats() if plane else None,
        "proofs_per_s_steady": round(steady_pps, 2),
        "direct_baseline_proofs_per_s": round(direct_pps, 2),
        "speedup_vs_direct": round(steady_pps / direct_pps, 2)
        if direct_pps > 0
        else 0.0,
        "state_proofs": state_lane,
        "header_sync": sync_lane,
        "flood": {
            "solo_tps": solo_tps,
            "with_proofs_tps": round(combined_tps, 2),
            "ratio": round(ratio, 3),
            "committed": stats.committed,
            "blocks": stats.blocks,
            "chain_txs": len(feed.hashes),
        },
        "wall_s": round(time.perf_counter() - t_wall, 3),
        "solo": solo_doc,
    }
    if hammer.served == 0:
        error = error or "no proofs were served — storm never started"
    if hammer.verify_failures:
        error = error or f"{hammer.verify_failures} served proofs failed verification"
    if error:
        doc["error"] = error
    return doc
