"""ctypes binding for the host-native crypto core (native/fisco_native.cpp).

Reference role: the wedpr-Rust/OpenSSL FFI layer of bcos-crypto.  The shared
library is built on demand with g++ (baked into the image; pybind11 is not —
ctypes keeps the dependency surface at zero).  Every consumer falls back to
the pure-Python crypto/ref implementations when the toolchain is missing, so
the native layer is a pure accelerator, never a requirement — and the test
suite asserts bit-identical outputs between both.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from .utils.log import get_logger

_log = get_logger("native")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_REPO, "native", "fisco_native.cpp")
_LIB = os.path.join(_REPO, "native", "libfisco_native.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> bool:
    try:
        res = subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", _LIB, _SRC],
            capture_output=True,
            text=True,
            timeout=120,
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        _log.info("native build unavailable: %s", e)
        return False
    if res.returncode != 0:
        _log.warning("native build failed:\n%s", res.stderr[-2000:])
        return False
    return True


def load() -> ctypes.CDLL | None:
    """The shared library, building it on first use; None when unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("FISCO_NO_NATIVE"):
            return None
        if not os.path.exists(_LIB) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_LIB)
        ):
            if not os.path.exists(_SRC) or not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as e:
            _log.warning("native load failed: %s", e)
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        for name in ("fisco_keccak256", "fisco_sha256", "fisco_sm3"):
            fn = getattr(lib, name)
            fn.argtypes = [u8p, ctypes.c_size_t, u8p]
            fn.restype = None
        lib.fisco_sm4_cbc.argtypes = [
            u8p, u8p, u8p, ctypes.c_size_t, u8p, ctypes.c_int,
        ]
        lib.fisco_sm4_cbc.restype = None
        _lib = lib
        _log.info("native crypto core loaded (%s)", _LIB)
        return _lib


def _hash_via(name: str, data: bytes) -> bytes | None:
    lib = load()
    if lib is None:
        return None
    out = (ctypes.c_uint8 * 32)()
    buf = (ctypes.c_uint8 * max(1, len(data))).from_buffer_copy(data or b"\x00")
    getattr(lib, name)(buf, len(data), out)
    return bytes(out)


def keccak256(data: bytes) -> bytes | None:
    return _hash_via("fisco_keccak256", data)


def sha256(data: bytes) -> bytes | None:
    return _hash_via("fisco_sha256", data)


def sm3(data: bytes) -> bytes | None:
    return _hash_via("fisco_sm3", data)


def sm4_cbc(key: bytes, iv: bytes, data: bytes, decrypt: bool) -> bytes | None:
    """CBC over whole blocks (no padding — callers do PKCS7)."""
    lib = load()
    if lib is None or len(data) % 16:
        return None
    n = len(data) // 16
    out = (ctypes.c_uint8 * len(data))()
    kbuf = (ctypes.c_uint8 * 16).from_buffer_copy(key)
    ivbuf = (ctypes.c_uint8 * 16).from_buffer_copy(iv)
    ibuf = (ctypes.c_uint8 * max(1, len(data))).from_buffer_copy(data or b"\x00")
    lib.fisco_sm4_cbc(kbuf, ivbuf, ibuf, n, out, 1 if decrypt else 0)
    return bytes(out)
