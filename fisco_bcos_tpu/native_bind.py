"""ctypes binding for the host-native crypto core (native/fisco_native.cpp).

Reference role: the wedpr-Rust/OpenSSL FFI layer of bcos-crypto.  The shared
library is built on demand with g++ (baked into the image; pybind11 is not —
ctypes keeps the dependency surface at zero).  Every consumer falls back to
the pure-Python crypto/ref implementations when the toolchain is missing, so
the native layer is a pure accelerator, never a requirement — and the test
suite asserts bit-identical outputs between both.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

from .utils.log import get_logger

_log = get_logger("native")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_REPO, "native", "fisco_native.cpp")
_LIB = os.path.join(_REPO, "native", "libfisco_native.so")
_ISA_TAG = _LIB + ".isa"  # host-ISA signature of the existing build

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _host_isa() -> str:
    """Stable signature of this host's instruction set. The library is built
    with -march=native (2x on the 4x64 Montgomery core via mulx/adx), so a
    build moved to a different CPU — shared volume, docker image — must be
    rebuilt, not executed: a SIGILL would kill the process instead of
    falling back to crypto/ref."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    return hashlib.sha256(
                        " ".join(sorted(line.split()[2:])).encode()
                    ).hexdigest()[:16]
    except OSError:
        pass
    import platform

    return platform.machine()


def _build() -> bool:
    # -fopenmp parallelizes the batch loops across host cores; a toolchain
    # without libgomp still gets the single-threaded library
    base = ["g++", "-O3", "-march=native", "-funroll-loops", "-shared",
            "-fPIC", "-o", _LIB, _SRC]
    try:
        res = subprocess.run(
            base[:1] + ["-fopenmp"] + base[1:],
            capture_output=True,
            text=True,
            timeout=120,
        )
        if res.returncode != 0:
            res = subprocess.run(base, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        _log.info("native build unavailable: %s", e)
        return False
    if res.returncode != 0:
        _log.warning("native build failed:\n%s", res.stderr[-2000:])
        return False
    try:
        with open(_ISA_TAG, "w") as f:
            f.write(_host_isa())
    except OSError:
        pass
    return True


def _isa_tag() -> str | None:
    try:
        with open(_ISA_TAG) as f:
            return f.read().strip()
    except OSError:
        return None


def _needs_rebuild() -> bool:
    if not os.path.exists(_LIB):
        return True
    if os.path.exists(_SRC) and os.path.getmtime(_SRC) > os.path.getmtime(_LIB):
        return True
    tag = _isa_tag()
    if tag is not None and tag != _host_isa():
        return True  # -march=native artifact from a different CPU: SIGILL risk
    if tag is None:
        # unknown provenance: rebuild when we can; when we can't (source-less
        # packaged install), load() refuses it — the library was built with
        # -march=native and a wrong-CPU copy SIGILLs, which no symbol guard
        # can catch. Packaged installs must ship the .isa tag beside the .so.
        return True
    return False


def load() -> ctypes.CDLL | None:
    """The shared library, building it on first use; None when unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("FISCO_NO_NATIVE"):
            return None
        if _needs_rebuild():
            if not os.path.exists(_SRC):
                if os.path.exists(_LIB):
                    _log.warning(
                        "prebuilt %s has no matching .isa tag and no source "
                        "to rebuild from; refusing to load it (-march=native "
                        "artifacts SIGILL on other CPUs) — using pure-Python "
                        "crypto instead", _LIB,
                    )
                return None
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as e:
            _log.warning("native load failed: %s", e)
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        try:
            _bind_symbols(lib, u8p)
        except AttributeError as e:
            # a stale .so missing newer symbols: disable rather than crash
            # every later call (the mtime/ISA checks normally prevent this,
            # but a source-less packaged install can still hit it)
            _log.warning("native library is stale, ignoring it: %s", e)
            return None
        _lib = lib
        _log.info("native crypto core loaded (%s)", _LIB)
        return _lib


# EVM fast-prefix engine callback signatures (native/fisco_native.cpp)
_U8P = ctypes.POINTER(ctypes.c_uint8)
EVM_SLOAD_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p, _U8P, _U8P)
EVM_SSTORE_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p, _U8P, _U8P)
EVM_LOG_FN = ctypes.CFUNCTYPE(
    None, ctypes.c_void_p, _U8P, ctypes.c_int, _U8P, ctypes.c_size_t
)
EVM_RESULT_FN = ctypes.CFUNCTYPE(
    None, ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
    ctypes.c_int64, _U8P, ctypes.c_size_t, _U8P, ctypes.c_size_t, _U8P,
    ctypes.c_size_t,
)


def evm_run(code: bytes, calldata: bytes, self_addr: bytes, caller: bytes,
            origin: bytes, value: int, gas: int, block_number: int,
            timestamp: int, gas_limit: int, static_flag: bool,
            sload, sstore, log):
    """Run the native EVM fast-prefix engine. Returns
    ("done", status, gas_left, output) or
    ("escape", pc, gas_left, [stack ints bottom-first], memory bytes),
    or None when the native library is unavailable.

    sload(slot32)->bytes32, sstore(slot32, val32), log(topics list, data)
    are plain-Python callbacks (closures over the host overlay)."""
    lib = load()
    if lib is None:
        return None
    result: list = []
    cb_err: list = []

    def _sload(_ctx, slot_p, out_p):
        try:
            v = sload(ctypes.string_at(slot_p, 32))
            ctypes.memmove(out_p, v, 32)
        except Exception as e:  # ctypes swallows callback exceptions
            cb_err.append(e)
            ctypes.memmove(out_p, b"\x00" * 32, 32)

    def _sstore(_ctx, slot_p, val_p):
        try:
            sstore(ctypes.string_at(slot_p, 32), ctypes.string_at(val_p, 32))
        except Exception as e:
            cb_err.append(e)

    def _log(_ctx, topics_p, ntopics, data_p, dlen):
        try:
            raw = ctypes.string_at(topics_p, 32 * ntopics) if ntopics else b""
            topics = [raw[32 * t : 32 * t + 32] for t in range(ntopics)]
            log(topics, ctypes.string_at(data_p, dlen) if dlen else b"")
        except Exception as e:
            cb_err.append(e)

    def _result(_ctx, kind, status, pc, gas_left, stack_p, n_stack, mem_p,
                mem_len, out_p, out_len):
        try:
            if kind == 0:
                result.append(
                    ("done", status, gas_left,
                     ctypes.string_at(out_p, out_len) if out_len else b"")
                )
            else:
                raw = ctypes.string_at(stack_p, n_stack * 32) if n_stack else b""
                stack = [
                    int.from_bytes(raw[i * 32 : i * 32 + 32], "big")
                    for i in range(n_stack)
                ]
                memory = ctypes.string_at(mem_p, mem_len) if mem_len else b""
                result.append(("escape", pc, gas_left, stack, memory))
        except Exception as e:
            cb_err.append(e)

    lib.fisco_evm_run(
        _buf(code or b"\x00"), len(code),
        _buf(calldata or b"\x00"), len(calldata),
        _buf(self_addr.rjust(20, b"\x00")[:20]),
        _buf(caller.rjust(20, b"\x00")[:20]),
        _buf(origin.rjust(20, b"\x00")[:20]),
        _buf(value.to_bytes(32, "big")),
        gas, block_number, timestamp, gas_limit,
        1 if static_flag else 0, None,
        EVM_SLOAD_FN(_sload), EVM_SSTORE_FN(_sstore), EVM_LOG_FN(_log),
        EVM_RESULT_FN(_result),
    )
    if cb_err:
        raise cb_err[0]
    return result[0] if result else None


def _bind_symbols(lib: ctypes.CDLL, u8p) -> None:
    for name in ("fisco_keccak256", "fisco_sha256", "fisco_sm3"):
        fn = getattr(lib, name)
        fn.argtypes = [u8p, ctypes.c_size_t, u8p]
        fn.restype = None
    lib.fisco_sm4_cbc.argtypes = [
        u8p, u8p, u8p, ctypes.c_size_t, u8p, ctypes.c_int,
    ]
    lib.fisco_sm4_cbc.restype = None
    lib.fisco_secp256k1_verify.argtypes = [u8p, u8p, u8p, u8p]
    lib.fisco_secp256k1_verify.restype = ctypes.c_int
    lib.fisco_secp256k1_recover.argtypes = [u8p, u8p, u8p, ctypes.c_int, u8p]
    lib.fisco_secp256k1_recover.restype = ctypes.c_int
    lib.fisco_secp256k1_sign.argtypes = [
        u8p, u8p, u8p, u8p, ctypes.POINTER(ctypes.c_int),
    ]
    lib.fisco_secp256k1_sign.restype = ctypes.c_int
    lib.fisco_sm2_verify.argtypes = [u8p, u8p, u8p, u8p]
    lib.fisco_sm2_verify.restype = ctypes.c_int
    lib.fisco_sm2_sign.argtypes = [u8p, u8p, u8p, u8p]
    lib.fisco_sm2_sign.restype = ctypes.c_int
    lib.fisco_ec_pubkey.argtypes = [ctypes.c_int, u8p, u8p]
    lib.fisco_ec_pubkey.restype = ctypes.c_int
    lib.fisco_secp256k1_verify_batch.argtypes = [
        ctypes.c_size_t, u8p, u8p, u8p, u8p, u8p,
    ]
    lib.fisco_secp256k1_verify_batch.restype = None
    lib.fisco_secp256k1_recover_batch.argtypes = [
        ctypes.c_size_t, u8p, u8p, u8p, u8p, u8p, u8p,
    ]
    lib.fisco_secp256k1_recover_batch.restype = None
    lib.fisco_sm2_verify_batch.argtypes = [
        ctypes.c_size_t, u8p, u8p, u8p, u8p, u8p,
    ]
    lib.fisco_sm2_verify_batch.restype = None
    lib.fisco_ed25519_verify.argtypes = [u8p, u8p, ctypes.c_size_t, u8p]
    lib.fisco_ed25519_verify.restype = ctypes.c_int
    lib.fisco_ed25519_pubkey.argtypes = [u8p, u8p]
    lib.fisco_ed25519_pubkey.restype = ctypes.c_int
    lib.fisco_ed25519_sign.argtypes = [u8p, u8p, ctypes.c_size_t, u8p]
    lib.fisco_ed25519_sign.restype = ctypes.c_int
    lib.fisco_evm_run.argtypes = [
        u8p, ctypes.c_size_t,  # code
        u8p, ctypes.c_size_t,  # calldata
        u8p, u8p, u8p,         # self, caller, origin
        u8p,                   # value (32B be)
        ctypes.c_int64,        # gas
        ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,  # number/ts/limit
        ctypes.c_int,          # static flag
        ctypes.c_void_p,       # ctx (unused; callbacks close over state)
        EVM_SLOAD_FN, EVM_SSTORE_FN, EVM_LOG_FN, EVM_RESULT_FN,
    ]
    lib.fisco_evm_run.restype = ctypes.c_int


def _hash_via(name: str, data: bytes) -> bytes | None:
    lib = load()
    if lib is None:
        return None
    out = (ctypes.c_uint8 * 32)()
    buf = (ctypes.c_uint8 * max(1, len(data))).from_buffer_copy(data or b"\x00")
    getattr(lib, name)(buf, len(data), out)
    return bytes(out)


def keccak256(data: bytes) -> bytes | None:
    return _hash_via("fisco_keccak256", data)


def sha256(data: bytes) -> bytes | None:
    return _hash_via("fisco_sha256", data)


def sm3(data: bytes) -> bytes | None:
    return _hash_via("fisco_sm3", data)


def sm4_cbc(key: bytes, iv: bytes, data: bytes, decrypt: bool) -> bytes | None:
    """CBC over whole blocks (no padding — callers do PKCS7)."""
    lib = load()
    if lib is None or len(data) % 16:
        return None
    n = len(data) // 16
    out = (ctypes.c_uint8 * len(data))()
    kbuf = (ctypes.c_uint8 * 16).from_buffer_copy(key)
    ivbuf = (ctypes.c_uint8 * 16).from_buffer_copy(iv)
    ibuf = (ctypes.c_uint8 * max(1, len(data))).from_buffer_copy(data or b"\x00")
    lib.fisco_sm4_cbc(kbuf, ivbuf, ibuf, n, out, 1 if decrypt else 0)
    return bytes(out)


# ---------------------------------------------------------------------------
# Elliptic-curve single-item paths (the wedpr_secp256k1_* / SM2 EVP analog).
# All wrappers return None when the native core is unavailable so callers can
# fall back to crypto/ref; verified results are plain bool/bytes.
# ---------------------------------------------------------------------------


def _b32(v: int | bytes) -> bytes:
    return v if isinstance(v, bytes) else v.to_bytes(32, "big")


def _buf(data: bytes):
    return (ctypes.c_uint8 * len(data)).from_buffer_copy(data)


def secp256k1_verify(z: bytes, r: int, s: int, pub: bytes) -> bool | None:
    lib = load()
    if lib is None:
        return None
    if not (0 <= r < 1 << 256 and 0 <= s < 1 << 256) or len(pub) != 64:
        return False
    return bool(
        lib.fisco_secp256k1_verify(_buf(z), _buf(_b32(r)), _buf(_b32(s)), _buf(pub))
    )


def secp256k1_recover(z: bytes, r: int, s: int, v: int) -> bytes | None:
    """Recovered 64-byte pubkey, b"" when the signature is unrecoverable,
    None when the native core is unavailable."""
    lib = load()
    if lib is None:
        return None
    if not (0 <= r < 1 << 256 and 0 <= s < 1 << 256):
        return b""
    out = (ctypes.c_uint8 * 64)()
    ok = lib.fisco_secp256k1_recover(
        _buf(z), _buf(_b32(r)), _buf(_b32(s)), int(v), out
    )
    return bytes(out) if ok else b""


def secp256k1_sign(z: bytes, d: int) -> tuple[int, int, int] | None:
    lib = load()
    if lib is None:
        return None
    r_out = (ctypes.c_uint8 * 32)()
    s_out = (ctypes.c_uint8 * 32)()
    v_out = ctypes.c_int(0)
    ok = lib.fisco_secp256k1_sign(
        _buf(z), _buf(_b32(d)), r_out, s_out, ctypes.byref(v_out)
    )
    if not ok:
        return None
    return (
        int.from_bytes(bytes(r_out), "big"),
        int.from_bytes(bytes(s_out), "big"),
        v_out.value,
    )


def sm2_verify(e: bytes, r: int, s: int, pub: bytes) -> bool | None:
    """e = SM3(ZA ‖ M) — the caller computes the SM2 digest prefix."""
    lib = load()
    if lib is None:
        return None
    if not (0 <= r < 1 << 256 and 0 <= s < 1 << 256) or len(pub) != 64:
        return False
    return bool(lib.fisco_sm2_verify(_buf(e), _buf(_b32(r)), _buf(_b32(s)), _buf(pub)))


def sm2_sign(e: bytes, d: int) -> tuple[int, int] | None:
    lib = load()
    if lib is None:
        return None
    r_out = (ctypes.c_uint8 * 32)()
    s_out = (ctypes.c_uint8 * 32)()
    ok = lib.fisco_sm2_sign(_buf(e), _buf(_b32(d)), r_out, s_out)
    if not ok:
        return None
    return (int.from_bytes(bytes(r_out), "big"), int.from_bytes(bytes(s_out), "big"))


def ec_pubkey(curve: str, d: int) -> bytes | None:
    lib = load()
    if lib is None:
        return None
    out = (ctypes.c_uint8 * 64)()
    ok = lib.fisco_ec_pubkey(1 if curve == "sm2" else 0, _buf(_b32(d)), out)
    return bytes(out) if ok else None


def secp256k1_verify_batch(zs: bytes, rs: bytes, ss: bytes, pubs: bytes, n: int):
    """n-item loop in one native call — the honest CPU baseline for bench.py.
    Returns a list[bool] or None when unavailable."""
    lib = load()
    if lib is None:
        return None
    out = (ctypes.c_uint8 * n)()
    lib.fisco_secp256k1_verify_batch(n, _buf(zs), _buf(rs), _buf(ss), _buf(pubs), out)
    return [bool(b) for b in out]


def secp256k1_recover_batch(zs: bytes, rs: bytes, ss: bytes, vs: bytes, n: int):
    lib = load()
    if lib is None:
        return None
    pubs_out = (ctypes.c_uint8 * (64 * n))()
    ok_out = (ctypes.c_uint8 * n)()
    lib.fisco_secp256k1_recover_batch(
        n, _buf(zs), _buf(rs), _buf(ss), _buf(vs), pubs_out, ok_out
    )
    return bytes(pubs_out), [bool(b) for b in ok_out]


def ed25519_verify(pub: bytes, msg: bytes, sig: bytes) -> bool | None:
    lib = load()
    if lib is None:
        return None
    if len(pub) != 32 or len(sig) != 64:
        return False
    return bool(
        lib.fisco_ed25519_verify(
            _buf(pub), _buf(msg or b"\x00"), len(msg), _buf(sig)
        )
    )


def ed25519_pubkey(seed: bytes) -> bytes | None:
    lib = load()
    if lib is None or len(seed) != 32:
        return None
    out = (ctypes.c_uint8 * 32)()
    if not lib.fisco_ed25519_pubkey(_buf(seed), out):
        return None  # native failure: caller falls back to crypto/ref
    return bytes(out)


def ed25519_sign(seed: bytes, msg: bytes) -> bytes | None:
    lib = load()
    if lib is None or len(seed) != 32:
        return None
    out = (ctypes.c_uint8 * 64)()
    if not lib.fisco_ed25519_sign(_buf(seed), _buf(msg or b"\x00"), len(msg), out):
        return None  # native failure: caller falls back to crypto/ref
    return bytes(out)


def sm2_verify_batch(es: bytes, rs: bytes, ss: bytes, pubs: bytes, n: int):
    lib = load()
    if lib is None:
        return None
    out = (ctypes.c_uint8 * n)()
    lib.fisco_sm2_verify_batch(n, _buf(es), _buf(rs), _buf(ss), _buf(pubs), out)
    return [bool(b) for b in out]
