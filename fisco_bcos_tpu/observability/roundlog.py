"""Consensus round forensics — the per-node round ledger and the cross-node
aligner (ISSUE 16 tentpole, part 1).

Every observability layer so far is per-process; the committee questions —
"which replica is the straggler in round 4817", "how long did the prepare
quorum actually take *across* the fleet" — need per (height, view) timing
recorded at every replica and aligned afterwards. That is exactly the data
ByzCoin-style committee scaling (1602.06997) and the per-phase committee
vote cost model (2302.00418) are built on, and what the PBFT engine used to
throw away after observing its per-process latency histograms.

Two pieces:

- :class:`RoundLedger` — a bounded per-node ledger the PBFT engine drives:
  monotonic timestamps for pre-prepare receipt, own prepare/commit vote
  send, each signer's vote arrival (by committee index), execute start/end,
  the three quorums and the durable commit, plus view-change records with
  cause attribution. Notes are one dict write under a private lock — cheap
  enough for the engine's message path — and quorum notes emit the round
  metrics (``fisco_round_phase_ms{phase}``, ``fisco_vote_arrival_spread_ms``)
  on named bucket constants.
- The **aligner** (:func:`align_rounds` / :func:`round_doc` /
  :func:`rounds_doc`) — merges ledger snapshots from many nodes, corrects
  each node's monotonic clock by an exchanged-probe offset
  (:mod:`.fleet` measures them), computes per-phase spans, inter-node
  skew per round, and names the straggler signer (largest median vote
  lateness behind the first arrival, the 2302.00418 first-to-last spread).

``FISCO_FLEET_OBS=0`` turns the ledger into :data:`NOOP_LEDGER` — every
note is one attribute call on a shared do-nothing object (the bench
overhead A/B switch, same pattern as ``FISCO_PIPELINE_OBS``).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque

from ..utils.metrics import REGISTRY

# round phases: sub-ms vote hops on the in-proc mesh up to multi-second
# execute/commit spans on real chains under load
ROUND_PHASE_BUCKETS_MS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 1000.0, 5000.0,
)
# inter-node skew and intra-quorum vote spread: healthy committees sit in
# the sub-ms..tens-of-ms band; a straggler pushes into the tail buckets
ROUND_SKEW_BUCKETS_MS = (
    0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 500.0,
)
VOTE_SPREAD_BUCKETS_MS = ROUND_SKEW_BUCKETS_MS

ROUND_CAP = 256  # rounds retained per ledger
VIEW_CHANGE_CAP = 128

# the quorum event each phase span ends at, and the event it starts from —
# the aligner and the note-time metric emission share this one table
PHASE_EDGES: tuple[tuple[str, str, str], ...] = (
    ("prepare", "pre_prepare", "prepared"),
    ("commit", "prepared", "committed"),
    ("execute", "execute_start", "execute_end"),
    ("checkpoint", "committed", "stable"),
    ("durable", "stable", "durable"),
)

# quorum event -> vote kind whose arrival spread it closes
_QUORUM_VOTES = {"prepared": "prepare", "committed": "commit", "stable": "checkpoint"}


def fleet_obs_enabled() -> bool:
    return os.environ.get("FISCO_FLEET_OBS", "1") != "0"


class RoundRecord:
    """One (height, view) round at one node. Mutated only under the owning
    ledger's lock; ``to_doc`` copies under it."""

    __slots__ = ("height", "view", "events", "votes")

    def __init__(self, height: int, view: int):
        self.height = height
        self.view = view
        # event -> monotonic t, first occurrence wins (re-delivered frames
        # must not move a quorum edge)
        self.events: dict[str, float] = {}
        # vote kind -> committee index (str: survives JSON) -> arrival t
        self.votes: dict[str, dict[str, float]] = {}

    def to_doc(self) -> dict:
        return {
            "height": self.height,
            "view": self.view,
            "events": dict(self.events),
            "votes": {k: dict(v) for k, v in self.votes.items()},
        }


class RoundLedger:
    """Bounded per-node round ledger. ``clock`` is injectable (the state
    machine tests and the interleave harness drive deterministic time);
    ``emit_metrics=False`` keeps harness runs out of the process registry."""

    def __init__(
        self,
        node_tag: str = "",
        cap: int = ROUND_CAP,
        clock=time.perf_counter,
        emit_metrics: bool = True,
    ):
        self.node_tag = node_tag
        self.cap = int(cap)
        self.clock = clock
        self.emit_metrics = emit_metrics
        self.enabled = True
        self._lock = threading.Lock()
        self._rounds: "OrderedDict[tuple[int, int], RoundRecord]" = OrderedDict()
        self._view_changes: deque[dict] = deque(maxlen=VIEW_CHANGE_CAP)
        # phase edges double as flight-recorder events (the black box's
        # "engine" category); harness ledgers (emit_metrics=False) stay out
        # of the process ring. Imported here, not at module top — flight
        # imports this module for the enable switch.
        self._flight = None
        if emit_metrics:
            from .flight import FLIGHT

            self._flight = FLIGHT

    # -- engine-facing writes ------------------------------------------------

    def _round_locked(self, height: int, view: int) -> RoundRecord:
        key = (height, view)
        rec = self._rounds.get(key)
        if rec is None:
            rec = self._rounds[key] = RoundRecord(height, view)
            while len(self._rounds) > self.cap:
                self._rounds.popitem(last=False)
        return rec

    def _note_rec_locked(self, rec: RoundRecord, event: str, t: float) -> None:
        if event in rec.events:
            return
        rec.events[event] = t
        if self._flight is not None:
            self._flight.record(
                "engine", event, scope=self.node_tag, height=rec.height
            )
        if not self.emit_metrics:
            return
        for phase, start, end in PHASE_EDGES:
            if event == end and start in rec.events:
                REGISTRY.observe(
                    "fisco_round_phase_ms",
                    (t - rec.events[start]) * 1e3,
                    buckets=ROUND_PHASE_BUCKETS_MS,
                    phase=phase,
                    help="consensus round per-phase span (round forensics)",
                )
        kind = _QUORUM_VOTES.get(event)
        if kind:
            arrivals = rec.votes.get(kind)
            if arrivals and len(arrivals) > 1:
                ts = arrivals.values()
                REGISTRY.observe(
                    "fisco_vote_arrival_spread_ms",
                    (max(ts) - min(ts)) * 1e3,
                    buckets=VOTE_SPREAD_BUCKETS_MS,
                    kind=kind,
                    help="first-to-last vote arrival spread per quorum",
                )

    def note(self, height: int, view: int, event: str, t: float | None = None) -> None:
        """Record a phase edge (first occurrence wins). Quorum edges emit
        the per-phase span and, where a vote kind closes, its first-to-last
        arrival spread."""
        if t is None:
            t = self.clock()
        with self._lock:
            self._note_rec_locked(self._round_locked(height, view), event, t)

    def note_height(self, height: int, event: str, t: float | None = None) -> None:
        """Record a phase edge against the NEWEST round at ``height`` —
        the async-commit completion path knows the block number but not
        which view's round carried it."""
        if t is None:
            t = self.clock()
        with self._lock:
            for key in reversed(self._rounds):
                if key[0] == height:
                    self._note_rec_locked(self._rounds[key], event, t)
                    return

    def vote(
        self, height: int, view: int, kind: str, signer: int,
        t: float | None = None,
    ) -> None:
        """Record signer ``signer``'s ``kind`` vote arrival (first wins —
        rebroadcasts must not rewrite history)."""
        if t is None:
            t = self.clock()
        with self._lock:
            rec = self._round_locked(height, view)
            rec.votes.setdefault(kind, {}).setdefault(str(int(signer)), t)

    def view_change(
        self, height: int, from_view: int, to_view: int, cause: str,
        t: float | None = None,
    ) -> None:
        """Record a view transition with cause attribution (``timeout``,
        ``catchup``, ``entered``, ``recover``)."""
        if t is None:
            t = self.clock()
        with self._lock:
            self._view_changes.append(
                {
                    "t": t,
                    "height": height,
                    "from_view": from_view,
                    "to_view": to_view,
                    "cause": cause,
                }
            )

    # -- snapshot ------------------------------------------------------------

    def probe(self) -> float:
        """This node's monotonic clock NOW — the fleet clock-probe payload."""
        return self.clock()

    def snapshot(
        self, last: int | None = None, height: int | None = None
    ) -> dict:
        """JSON-able ledger dump: rounds (optionally only ``height`` or the
        newest ``last``), view-change records, and the clock reading the
        aligner pairs with the transport's probe offsets."""
        with self._lock:
            rounds = [r.to_doc() for r in self._rounds.values()]
            vcs = list(self._view_changes)
        if height is not None:
            rounds = [r for r in rounds if r["height"] == height]
        elif last is not None and last >= 0:
            rounds = rounds[-last:]
        return {
            "node": self.node_tag,
            "clock": self.clock(),
            "rounds": rounds,
            "view_changes": vcs,
        }


class _NoopLedger:
    """Shared do-nothing ledger for ``FISCO_FLEET_OBS=0`` — every engine
    note costs one attribute lookup and an immediate return."""

    __slots__ = ()
    enabled = False
    node_tag = ""
    clock = staticmethod(time.perf_counter)

    def note(self, *a, **k) -> None:
        pass

    def note_height(self, *a, **k) -> None:
        pass

    def vote(self, *a, **k) -> None:
        pass

    def view_change(self, *a, **k) -> None:
        pass

    def probe(self) -> float:
        return 0.0

    def snapshot(self, last=None, height=None) -> dict:
        return {"node": "", "clock": 0.0, "rounds": [], "view_changes": []}


NOOP_LEDGER = _NoopLedger()


# -- cross-node alignment -----------------------------------------------------


def phase_spans(round_doc_: dict) -> dict[str, float]:
    """Per-phase spans (ms) of one round dict (``RoundRecord.to_doc``)."""
    events = round_doc_.get("events", {})
    spans: dict[str, float] = {}
    for phase, start, end in PHASE_EDGES:
        if start in events and end in events:
            spans[phase] = (events[end] - events[start]) * 1e3
    return spans


def percentile(values, q: float) -> float:
    """Nearest-rank percentile of ``values`` (q in [0, 100])."""
    vs = sorted(values)
    if not vs:
        return 0.0
    idx = max(0, min(len(vs) - 1, int(round(q / 100.0 * (len(vs) - 1)))))
    return vs[idx]


def _corrected(t: float, offset: float) -> float:
    """Map a peer timestamp into the reference clock frame: ``offset`` is
    (peer clock - reference clock), so subtracting lands in reference time."""
    return t - offset


def align_rounds(
    ledgers: dict[str, dict],
    offsets: dict[str, float] | None = None,
    record_skew: bool = False,
) -> list[dict]:
    """Merge per-node ledger snapshots into per-round fleet documents.

    ``ledgers`` maps a node label to its ``RoundLedger.snapshot()``;
    ``offsets`` maps the same labels to (peer clock - reference clock)
    seconds from the clock-probe exchange (missing/reference label = 0.0).
    Every per-node timestamp is offset-corrected before comparison.

    Each returned round doc carries per-node phase spans, the fleet-wide
    span envelope, the inter-node skew (spread of the corrected quorum
    edge), and the named straggler signer — the committee index whose
    votes trail the first arrival by the largest median margin across
    the observing nodes. ``record_skew=True`` additionally observes each
    round's skew into ``fisco_round_skew_ms`` (the aggregation call paths
    — /fleet, the flood bench — own that; a passive GET must not double
    count)."""
    offsets = offsets or {}
    by_round: dict[tuple[int, int], dict[str, dict]] = {}
    for label, snap in ledgers.items():
        for rd in snap.get("rounds", ()):
            key = (rd["height"], rd["view"])
            by_round.setdefault(key, {})[label] = rd
    out: list[dict] = []
    for (height, view) in sorted(by_round):
        per_node = by_round[(height, view)]
        doc: dict = {"height": height, "view": view, "nodes": {}}
        # per-node spans + fleet envelope
        envelope: dict[str, list[float]] = {}
        for label, rd in per_node.items():
            spans = phase_spans(rd)
            doc["nodes"][label] = {"view": rd["view"], "phases": spans}
            for phase, ms in spans.items():
                envelope.setdefault(phase, []).append(ms)
        doc["phases"] = {
            phase: {"min_ms": min(v), "max_ms": max(v)}
            for phase, v in envelope.items()
        }
        # inter-node skew: spread of the corrected quorum edge across nodes
        # (prefer the stable commit — the edge every replica reaches)
        for edge in ("stable", "committed", "prepared"):
            ts = [
                _corrected(rd["events"][edge], offsets.get(label, 0.0))
                for label, rd in per_node.items()
                if edge in rd.get("events", {})
            ]
            if len(ts) > 1:
                doc["skew_ms"] = (max(ts) - min(ts)) * 1e3
                doc["skew_edge"] = edge
                break
        if record_skew and "skew_ms" in doc:
            REGISTRY.observe(
                "fisco_round_skew_ms",
                doc["skew_ms"],
                buckets=ROUND_SKEW_BUCKETS_MS,
                help="inter-node spread of the round's quorum edge "
                "(offset-corrected)",
            )
        # straggler: lateness of each signer's vote behind the first
        # arrival, aggregated over every observing node and vote kind —
        # offsets cancel (lateness is measured within ONE node's clock).
        # MEDIAN across observations, not mean: a slow OBSERVER processes
        # every arriving vote late and would inflate every OTHER signer's
        # lateness in its own ledger — the median keeps one pathological
        # observer from dominating attribution.
        lateness: dict[str, list[float]] = {}
        for rd in per_node.values():
            for kind in ("prepare", "commit", "checkpoint"):
                arrivals = rd.get("votes", {}).get(kind)
                if not arrivals or len(arrivals) < 2:
                    continue
                first = min(arrivals.values())
                for signer, t in arrivals.items():
                    lateness.setdefault(signer, []).append((t - first) * 1e3)
        if lateness:
            meds = {
                s: sorted(v)[len(v) // 2] for s, v in lateness.items()
            }
            straggler = max(meds, key=lambda s: meds[s])
            doc["vote_lateness_ms"] = {s: round(m, 3) for s, m in meds.items()}
            doc["straggler"] = int(straggler)
            doc["straggler_lateness_ms"] = meds[straggler]
        out.append(doc)
    return out


def round_doc(
    ledgers: dict[str, dict],
    offsets: dict[str, float] | None = None,
    height: int | None = None,
) -> dict:
    """The ``GET /round/<height>`` document: every aligned view of that
    height (re-proposals under view changes show up as separate rounds)."""
    aligned = [
        d for d in align_rounds(ledgers, offsets)
        if height is None or d["height"] == height
    ]
    return {
        "found": bool(aligned),
        "height": height,
        "rounds": aligned,
        "nodes": sorted(ledgers),
    }


def rounds_doc(
    ledgers: dict[str, dict],
    offsets: dict[str, float] | None = None,
    last: int = 32,
    record_skew: bool = False,
) -> dict:
    """The ``GET /rounds?last=N`` document: newest ``last`` aligned rounds
    plus fleet-wide skew percentiles and merged view-change records."""
    aligned = align_rounds(ledgers, offsets, record_skew=record_skew)
    if last >= 0:
        aligned = aligned[-last:]
    skews = [d["skew_ms"] for d in aligned if "skew_ms" in d]
    vcs = []
    for label, snap in ledgers.items():
        for vc in snap.get("view_changes", ()):
            vcs.append({"node": label, **vc})
    return {
        "rounds": aligned,
        "nodes": sorted(ledgers),
        "skew_ms": {
            "n": len(skews),
            "p50": percentile(skews, 50),
            "p95": percentile(skews, 95),
            "max": max(skews) if skews else 0.0,
        },
        "view_changes": vcs,
    }
