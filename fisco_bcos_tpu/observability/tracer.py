"""Span tracing over ``time.perf_counter`` with Chrome trace-event export.

Reference: the reference's per-stage BlockTrace logs (DMCExecute.0..6 in
bcos-scheduler BlockExecutive.cpp:849-1010) answer "where did this block's
wall time go?" by grepping; here the same stages are first-class spans in a
bounded in-memory ring, exported as Chrome trace-event JSON (the format
Perfetto / chrome://tracing load directly) from ``GET /trace`` next to
``/metrics``.

Threading model: each thread keeps its own span stack (thread-local), so
``span()`` context managers nest naturally and record parent/depth without
cross-thread locking; only the ring append takes the shared lock. Completed
spans from other timelines (e.g. PBFT phase gaps measured between message
arrivals) are added retroactively via :meth:`Tracer.record`.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field


@dataclass
class SpanRecord:
    name: str
    ts: float  # perf_counter at span start (seconds)
    dur: float  # seconds
    tid: int
    depth: int = 0
    parent: str | None = None
    attrs: dict = field(default_factory=dict)


class _NoopSpan:
    """Shared do-nothing span for a disabled tracer. `attrs` hands out a
    fresh throwaway dict per access so caller writes (``sp.attrs[k] = v``)
    are discarded instead of accumulating on the shared singleton."""

    __slots__ = ()

    @property
    def attrs(self) -> dict:
        return {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("_tracer", "name", "attrs", "_t0", "depth", "parent")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        stack = self._tracer._stack()
        self.parent = stack[-1].name if stack else None
        self.depth = len(stack)
        stack.append(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self._t0
        stack = self._tracer._stack()
        if stack and stack[-1] is self:
            stack.pop()
        self._tracer.record(
            self.name,
            t0=self._t0,
            dur=dur,
            depth=self.depth,
            parent=self.parent,
            **self.attrs,
        )
        return False


class Tracer:
    """Bounded ring of completed spans; thread-safe, cheap when disabled."""

    def __init__(self, capacity: int = 8192, enabled: bool = True):
        self.capacity = int(capacity)
        self.enabled = enabled
        self._buf: deque[SpanRecord] = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._tls = threading.local()

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def span(self, name: str, **attrs):
        """Context manager timing a region; yields the span so callers can
        add attrs (``sp.attrs["txs"] = n``) before it closes."""
        if not self.enabled:
            return _NOOP
        return _Span(self, name, attrs)

    def record(
        self,
        name: str,
        t0: float,
        dur: float,
        depth: int = 0,
        parent: str | None = None,
        **attrs,
    ) -> None:
        """Append a COMPLETED span with explicit timing — the retroactive
        path for phase gaps measured between events (PBFT quorum waits)."""
        if not self.enabled:
            return
        rec = SpanRecord(
            name, t0, max(dur, 0.0), threading.get_ident(), depth, parent, attrs
        )
        with self._lock:
            self._buf.append(rec)

    def spans(self) -> list[SpanRecord]:
        with self._lock:
            return list(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    # -- export ---------------------------------------------------------------

    def export_chrome(self) -> dict:
        """Chrome trace-event JSON object (Perfetto/chrome://tracing load it
        directly): complete ("X") events, timestamps in microseconds."""
        pid = os.getpid()
        events = []
        for rec in self.spans():
            args = {k: v for k, v in rec.attrs.items()}
            if rec.parent is not None:
                args["parent"] = rec.parent
            events.append(
                {
                    "ph": "X",
                    "name": rec.name,
                    "cat": "fisco",
                    "pid": pid,
                    "tid": rec.tid,
                    "ts": round(rec.ts * 1e6, 3),
                    "dur": round(rec.dur * 1e6, 3),
                    "args": args,
                }
            )
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_json(self) -> str:
        return json.dumps(self.export_chrome(), default=str)


# process-wide default tracer (modules import and use directly, like
# utils.metrics.REGISTRY); FISCO_TELEMETRY=0 starts it disabled
TRACER = Tracer(
    capacity=int(os.environ.get("FISCO_TRACE_CAPACITY", "8192")),
    enabled=os.environ.get("FISCO_TELEMETRY", "1") != "0",
)
